"""One fleet HOST as a runnable OS process: the multi-host serve-out unit.

    python -m avenir_tpu.serving.fleet_host \
        --registry <dir> --model <name> \
        [--models name[:ver],name2...] [--model-depth name=N] \
        --endpoints host:port[,host:port...] \
        [--workers N] [--host-label h] [--batching continuous|drain] \
        [--max-batch 64] [--max-wait-ms 2.0] [--slo-p99-ms 0] \
        [--max-queue-depth 0] [--buckets 8,64] \
        [--autoscale MIN:MAX] [--autoscale-interval-s 0.25] \
        [--request-queue rq] [--prediction-queue pq] \
        [--max-idle-s 30] [--metrics-port -1] [--stats-out file.json]

Starts a :class:`~avenir_tpu.serving.fleet.ServingFleet` (optionally
under a :class:`~avenir_tpu.serving.autoscaler.FleetAutoscaler`)
draining the given broker ring against the SHARED registry directory,
and exits on a wire ``stop`` message or after ``--max-idle-s`` without
traffic — whichever first.  On exit it prints ONE JSON line of fleet
stats + merged counters to stdout (and to ``--stats-out`` when given),
so a parent process — the multi-process saturation bench, the
two-process test lane — can collect per-host served/rejected tallies.

This is the data-plane process of the horizontal tier: N of these on N
hosts, all pointed at the same broker endpoints and the same published
registry (a shared filesystem, like the training shards' inputs).  The
PR 10 generation-counter hot-swap converges per host: push one
ADDRESSED ``reload,<host_label>`` per host (a fleet that pops a copy
addressed to a peer re-pushes it) — a bare broadcast 'reload' cannot
converge N hosts, because one host's workers, parked across every
shard, can pop all the copies.

``--metrics-port``: -1 = no endpoint, 0 = ephemeral (printed on
stderr), >0 = fixed — the off-host ``/metrics`` + ``/healthz`` bind
from PR 8 (set ``--metrics-host 0.0.0.0`` to expose beyond loopback).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def _parse_args(argv):
    ap = argparse.ArgumentParser(prog="fleet_host", description=__doc__)
    ap.add_argument("--registry", required=True)
    ap.add_argument("--model", default=None,
                    help="single resident model (classic form); "
                         "required unless --models is given")
    ap.add_argument("--models", default=None,
                    help="comma-separated resident model specs "
                         "(name or name:version): every worker runs a "
                         "ModelRouter over the set and requests route "
                         "by the wire m=<name[:version]> field; "
                         "--model (or the first spec) is the default "
                         "model for untagged requests")
    ap.add_argument("--model-depth", action="append", default=[],
                    metavar="NAME=DEPTH",
                    help="per-model admission queue depth (tenant "
                         "isolation; repeatable; default "
                         "--max-queue-depth)")
    ap.add_argument("--endpoints", required=True,
                    help="comma-separated broker shard host:port list")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--host-label", default=None)
    ap.add_argument("--batching", default="continuous",
                    choices=("continuous", "drain"))
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--slo-p99-ms", type=float, default=0.0)
    ap.add_argument("--max-queue-depth", type=int, default=0)
    ap.add_argument("--buckets", default="8,64")
    ap.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                    help="enable the autoscaler between MIN and MAX "
                         "active workers (workers start at MIN)")
    ap.add_argument("--autoscale-interval-s", type=float, default=0.25)
    ap.add_argument("--request-queue", default="requestQueue")
    ap.add_argument("--prediction-queue", default="predictionQueue")
    ap.add_argument("--lease-timeout-s", type=float, default=0.0,
                    help="drain under visibility-timeout leases with "
                         "this expiry (at-least-once + broker-side "
                         "reply dedup = exactly-once effect); 0 keeps "
                         "the classic destructive-pop wire path")
    ap.add_argument("--max-idle-s", type=float, default=30.0)
    ap.add_argument("--metrics-port", type=int, default=-1)
    ap.add_argument("--metrics-host", default="127.0.0.1")
    ap.add_argument("--trace-dir", default=None,
                    help="span/flow tracing: write this host's "
                         "trace-<run-id>.p<trace-index>.jsonl here "
                         "(default: AVENIR_TPU_TRACE_EVENTS_DIR, else "
                         "off); sampled wire requests' flow events land "
                         "in it for the tracetool merged timeline")
    ap.add_argument("--run-id", default="serve",
                    help="trace run id — every process of one serving "
                         "run (clients included) must share it")
    ap.add_argument("--trace-index", type=int, default=None,
                    help="this process's trace lane index (unique per "
                         "process of the run; the client convention is "
                         "index 0).  Default: derived from the pid, so "
                         "two hosts launched without it never "
                         "interleave one trace file")
    ap.add_argument("--wire-native", default="auto",
                    choices=("auto", "on", "off"),
                    help="native serving data plane (the ps.wire.native "
                         "knob): one C pass per drained batch for "
                         "message parse/assembly and reply RESP encode; "
                         "'auto' uses it when the toolchain can build "
                         "it, 'off' pins the pure-python path")
    ap.add_argument("--stats-out", default=None)
    ap.add_argument("--ready-file", default=None,
                    help="touched once the fleet is draining — a parent "
                         "orchestrating several hosts waits on these "
                         "before offering load, so a slow-starting host "
                         "isn't measured as absent")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(argv)
    from ..core.platform import force_platform
    force_platform()
    from . import (AutoscalePolicy, BatchPolicy, FleetAutoscaler,
                   ModelRegistry, ServingFleet)
    from ..io.respq import make_queue_client

    wire_cfg = {"redis.server.endpoints": args.endpoints,
                "redis.request.queue": args.request_queue,
                "redis.prediction.queue": args.prediction_queue,
                "redis.lease.timeout.s": args.lease_timeout_s}
    scale = None
    n_workers = args.workers
    if args.autoscale:
        lo, _, hi = args.autoscale.partition(":")
        scale = (int(lo), int(hi or lo))
        n_workers = scale[0]
    policy = BatchPolicy(max_batch=args.max_batch,
                         max_wait_ms=args.max_wait_ms,
                         batching=args.batching,
                         slo_p99_ms=args.slo_p99_ms,
                         max_queue_depth=args.max_queue_depth)
    registry = ModelRegistry(args.registry)
    tracer = None
    trace_dir = args.trace_dir or \
        os.environ.get("AVENIR_TPU_TRACE_EVENTS_DIR") or None
    if trace_dir:
        from ..telemetry import Tracer, install_tracer
        # unset index derives from hostname+pid: two fleet_hosts
        # launched without --trace-index — even on DIFFERENT machines
        # sharing an NFS trace dir, where bare pids can collide — must
        # never append into ONE lane file (interleaved lanes read as
        # false span-crossing problems and scramble the flow arrows)
        idx = args.trace_index
        if idx is None:
            import socket
            import zlib
            idx = (zlib.crc32(socket.gethostname().encode()) % 9000
                   + 1000) * 100000 + os.getpid() % 100000
        tracer = install_tracer(Tracer(trace_dir, run_id=args.run_id,
                                       process_index=idx))
        print(f"fleet_host: tracing to {tracer.path}", file=sys.stderr)
    metrics = msrv = None
    if args.metrics_port >= 0:
        from ..telemetry import MetricsRegistry, MetricsServer
        metrics = MetricsRegistry()
        msrv = MetricsServer(metrics, port=args.metrics_port,
                             host=args.metrics_host).start()
        print(f"fleet_host: /metrics on {msrv.url}", file=sys.stderr)
    from ..io import native_wire
    native_wire.set_mode(args.wire_native)
    if not args.model and not args.models:
        print("fleet_host: --model or --models is required",
              file=sys.stderr)
        return 2
    models = [s.strip() for s in (args.models or "").split(",")
              if s.strip()] or None
    depths = {}
    for spec in args.model_depth:
        mname, _, d = spec.partition("=")
        depths[mname.strip()] = int(d)
    fleet = ServingFleet(
        registry, args.model,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        policy=policy, n_workers=n_workers, config=wire_cfg,
        host_label=args.host_label, metrics=metrics,
        wire_native=args.wire_native,
        models=models, model_depths=depths or None)
    fleet.start()
    scaler = sensor = None
    if scale is not None:
        # the sensor needs its OWN broker connection (clients are
        # one-per-thread); autoscale SLO defaults to the batch policy's
        sensor = make_queue_client(wire_cfg, delim=fleet.delim)
        scaler = FleetAutoscaler(
            fleet, sensor, queue=args.request_queue,
            policy=AutoscalePolicy(min_workers=scale[0],
                                   max_workers=scale[1],
                                   slo_p99_ms=args.slo_p99_ms),
            interval_s=args.autoscale_interval_s,
            counters=fleet.workers[0].service.counters).start()
    rc = 0
    # graceful SIGTERM (ISSUE 17): break the wait loop instead of dying
    # mid-batch, so the finally path below runs fleet.stop() — pending
    # replies flushed (acking their leases in lease mode), accepted
    # requests answered, connections torn down — before the process
    # exits.  SIGKILL remains the chaos-drill crash; its leases expire
    # and redeliver broker-side.
    sigterm = {"hit": False}

    def _on_sigterm(signum, frame):  # noqa: ARG001 - signal signature
        sigterm["hit"] = True

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        pass  # not the main thread / platform without SIGTERM
    try:
        if args.ready_file:
            with open(args.ready_file, "w") as fh:
                fh.write("ready\n")
        # wait for a wire stop (fleet.wait returns once every drain
        # thread exited), SIGTERM, or the idle timeout
        idle_since = time.monotonic()
        last_served = -1
        while not fleet.wait(timeout_s=0.5):
            if sigterm["hit"]:
                print("fleet_host: SIGTERM, draining and exiting",
                      file=sys.stderr)
                break
            served = fleet.stats()["served"]
            if served != last_served:
                last_served = served
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since > args.max_idle_s:
                print(f"fleet_host: idle {args.max_idle_s}s, exiting",
                      file=sys.stderr)
                break
    finally:
        if scaler is not None:
            scaler.stop()
        fleet.stop()
        stats = fleet.stats()
        stats["counters"] = fleet.merged_counters().as_dict()
        if scaler is not None:
            stats["autoscaler"] = {
                "decisions": len(scaler.decisions),
                "final_active": fleet.active_workers(),
            }
        line = json.dumps(stats, sort_keys=True)
        print(line)
        if args.stats_out:
            with open(args.stats_out, "w") as fh:
                fh.write(line + "\n")
        if sensor is not None:
            sensor.close()
        if msrv is not None:
            msrv.stop()
        if tracer is not None:
            from ..telemetry import uninstall_tracer
            uninstall_tracer()
            tracer.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
