"""Online prediction serving: model registry, warm bucketed predictors,
micro-batched request loop.

The training side of the framework runs one-shot batch jobs (cli/jobs.py);
this package is the low-latency half of the TensorFlow lesson (PAPERS.md):
the same model core must also serve online traffic.  Three layers:

  * :mod:`.registry`  — versioned, atomically published model artifacts
    (uniform JSON/NPZ format for forest / naive bayes / logistic / MLP)
    with torn-version detection and hot-swap reload;
  * :mod:`.predictor` — per-model ``Predictor`` wrappers holding
    pre-warmed, shape-bucketed jitted predict functions (requests pad to
    bucket sizes so XLA compiles once per bucket — the Execution
    Templates insight: reuse pre-validated execution state);
  * :mod:`.service`   — the in-process micro-batching request loop plus
    the RESP wire transport (io/respq), same message conventions as the
    bandit loop in reinforce/serving.py.  Continuous (double-buffered)
    batching, an SLO-adaptive coalescing window, and bounded-queue
    admission control live here (BatchPolicy knobs);
  * :mod:`.fleet`     — :class:`ServingFleet`, the traffic-shaped tier:
    N workers with per-worker warm bucket caches draining ONE RESP
    request queue (or a ``ShardedRespClient`` ring of M broker shards),
    coordinated hot-swap, degraded-worker parking, autoscaler parking
    (``scale_to``), and per-worker ``/healthz/<name>`` targets;
  * :mod:`.autoscaler` — :class:`FleetAutoscaler`, the SLO-driven
    sensor→policy→actuator control loop over one fleet: queue-depth
    derivative + recent-p99-vs-SLO sensing, hysteresis so it never
    flaps, every decision traced and counted (``Autoscaler/*``);
  * :mod:`.router`    — :class:`ModelRouter`, the multi-model tier
    (ISSUE 18): N resident models behind one service-shaped surface,
    per-request routing by the wire ``m=<model[:version]>`` field,
    cross-model executable sharing, per-model admission depths
    (tenant isolation), canary/shadow deployment policies.
"""

from .registry import (FOREST, BAYES, LOGISTIC, MLP, LoadedModel,
                       ModelRegistry, load_model, save_model)
from .predictor import (DEFAULT_BUCKETS, BayesPredictor, ForestPredictor,
                        LogisticPredictor, MLPPredictor, Predictor,
                        make_predictor)
from .service import BatchPolicy, PredictionService, RespPredictionLoop
from .router import ModelRouter, canary_split, parse_model_spec
from .fleet import ServingFleet
from .autoscaler import AutoscalePolicy, FleetAutoscaler

__all__ = [
    "FOREST", "BAYES", "LOGISTIC", "MLP", "LoadedModel", "ModelRegistry",
    "load_model", "save_model", "DEFAULT_BUCKETS", "BayesPredictor",
    "ForestPredictor", "LogisticPredictor", "MLPPredictor", "Predictor",
    "make_predictor", "BatchPolicy", "PredictionService",
    "RespPredictionLoop", "ModelRouter", "canary_split",
    "parse_model_spec", "ServingFleet", "AutoscalePolicy",
    "FleetAutoscaler",
]
