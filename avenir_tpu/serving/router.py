"""Multi-model, multi-tenant routing over co-resident PredictionServices
(ISSUE 18).

One :class:`ModelRouter` holds N **resident** registry models — multiple
families AND multiple versions, each behind its own
:class:`~avenir_tpu.serving.service.PredictionService` with its own warm
shape-bucket predictor cache (quantized sidecar riding per model) — and
routes each request by the optional backward-compatible wire field
``m=<model[:version]>`` (telemetry/reqtrace.parse_model; absent = the
default model, byte for byte what a single-model service answers).  The
native C data plane never routes: a well-formed ``m=`` token punts the
whole batch to the authoritative python plane (io/serve_native.cpp),
exactly the ISSUE 17 deadline contract.

Executable sharing: resident predictors are built with
``shared_cores=True`` (serving/predictor.py), so two models whose
compiled programs are structurally identical — same family variant,
schema fingerprint, bucket ladder, mesh, parameter shapes — share ONE
jitted core keyed on the ProgramCache axes rather than model identity
(Execution Templates' install-once/instantiate-cheap argument applied
across the model zoo: residency is cheap where shapes agree).

Per-tenant isolation:

  * **admission** — each resident gets its OWN ``BatchPolicy`` copy with
    a per-model queue depth (``ps.model.<name>.queue.max.depth``,
    defaulting to ``ps.queue.max.depth``): a noisy tenant is answered
    ``busy`` at ITS depth while quiet tenants keep their full budget.
  * **observability** — every sub-service binds ``model``-labeled metric
    series (service.py's host+service labels, one level down), counts
    land in the shared Counters under ``Model/<name>/...``, and
    ``model_queue_depths()`` feeds the autoscaler's per-model sensing.

Deployment policies as routing rules:

  * **canary** (:meth:`install_canary`) — a DETERMINISTIC per-request-id
    x% split (``canary_split``: crc32(rid) % 100 < percent) routes to a
    candidate version of the model; everyone else stays on the champion.
    Splitting on the request id — never ``random()`` — means every
    worker, every plane, and the judging controller derive the SAME
    assignment from the id alone: outcome labels arriving minutes later
    attribute to the right arm with no per-request routing journal.
    Outcomes recorded through :meth:`record_canary_outcome` feed one
    :class:`~avenir_tpu.monitor.policy.AccuracyTracker` per arm — the
    same delayed-label machinery the live monitor alerts on — and the
    per-arm series are scrape-observable (``avenir_canary``).
  * **shadow** (:meth:`install_shadow`) — the candidate scores EVERY
    request for its model, replies are discarded (the champion answers
    the wire), and label divergence is counted
    (``Model/<name>/ShadowDivergence``) — full-traffic soak with zero
    blast radius.
"""

from __future__ import annotations

import dataclasses
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.metrics import Counters
from ..utils.tracing import StepTimer
from .predictor import DEFAULT_BUCKETS, make_predictor
from .service import BatchPolicy, PredictionService

UNKNOWN_MODEL_LABEL = "error"


def parse_model_spec(spec) -> Tuple[str, Optional[int]]:
    """``"name"`` / ``"name:3"`` / ``(name, version)`` -> (name, ver)."""
    if isinstance(spec, (tuple, list)):
        name, ver = spec
        return str(name), (None if ver is None else int(ver))
    spec = str(spec)
    if ":" in spec:
        name, _, ver = spec.rpartition(":")
        return name, int(ver)
    return spec, None


def canary_bucket(rid) -> int:
    """The deterministic 0..99 split bucket for a request id.  crc32 —
    stable across processes, platforms and python hash randomization —
    so every worker AND the judging controller agree on the assignment
    from the id alone (TPU_NOTES §30: split on request id, not
    random())."""
    return zlib.crc32(str(rid).encode("utf-8")) % 100


def canary_split(rid, percent: int) -> bool:
    """True when ``rid`` belongs to the canary arm at ``percent``%."""
    return canary_bucket(rid) < int(percent)


def _probe_tracker(pos_class: str, neg_class: str, window: int):
    """An AccuracyTracker whose capture policy ALWAYS fires (alert bar
    above 100, silenced logger) — the controller's accuracy_pct shape:
    a measurement probe, not a finding."""
    import logging

    from ..monitor.policy import AccuracyTracker, DriftPolicy
    policy = DriftPolicy(consecutive=1, accuracy_alert=101,
                         counters=Counters())
    probe_log = logging.getLogger("avenir_tpu.serving._canary_probe")
    if not probe_log.handlers:
        probe_log.addHandler(logging.NullHandler())
        probe_log.propagate = False
    policy._log = probe_log
    return AccuracyTracker(pos_class=pos_class, neg_class=neg_class,
                           policy=policy, window=window)


class _Canary:
    """Live canary state for one model name."""

    __slots__ = ("service", "version", "percent", "trackers", "accuracy",
                 "outcomes", "correct")

    def __init__(self, service: PredictionService, version: Optional[int],
                 percent: int, trackers: Dict[str, object]):
        self.service = service
        self.version = version
        self.percent = int(percent)
        # arm -> AccuracyTracker (or None when no pos/neg classes given)
        self.trackers = trackers
        # arm -> last closed-window accuracy pct (None until one closes)
        self.accuracy: Dict[str, Optional[int]] = {"champion": None,
                                                   "candidate": None}
        self.outcomes: Dict[str, int] = {"champion": 0, "candidate": 0}
        self.correct: Dict[str, int] = {"champion": 0, "candidate": 0}


class _Shadow:
    __slots__ = ("service", "version")

    def __init__(self, service: PredictionService, version: Optional[int]):
        self.service = service
        self.version = version


class ModelRouter:
    """N resident models behind one PredictionService-shaped surface.

    Duck-types the service verbs the fleet drain, the autoscaler and the
    controller link already speak (``submit`` / ``stats`` / ``refresh``
    / ``mark_degraded`` / ``start`` / ``stop`` / ``policy`` / ``timer``
    / ``counters`` / ``version`` / ``degraded``), plus the routed entry
    :meth:`submit_routed` for requests carrying a wire ``m=`` tag."""

    def __init__(self, registry, models: Sequence, *,
                 default_model: Optional[str] = None,
                 policy: Optional[BatchPolicy] = None,
                 model_depths: Optional[Dict[str, int]] = None,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 counters: Optional[Counters] = None,
                 timer: Optional[StepTimer] = None,
                 warm: bool = True,
                 delim: str = ",",
                 name: Optional[str] = None,
                 host_label: Optional[str] = None,
                 metrics=None,
                 latency_window: int = 8192,
                 quantized: bool = False,
                 wire_native: str = "auto",
                 shared_cores: bool = True,
                 device=None,
                 serve_mesh=None):
        if not models:
            raise ValueError("ModelRouter needs at least one resident "
                             "model spec")
        self.registry = registry
        self.policy = policy or BatchPolicy()
        self.delim = delim
        self.name = name
        self.host_label = host_label
        self.counters = counters if counters is not None else Counters()
        self._buckets = tuple(buckets)
        self._warm = warm
        self._metrics = metrics
        self._latency_window = int(latency_window)
        self._quantized = bool(quantized)
        self._wire_native = wire_native
        self._shared_cores = bool(shared_cores)
        # ISSUE 20 placement: one map for every resident (a router is
        # one worker — its residents share the worker's chip or mesh)
        self._device = device
        self._serve_mesh = serve_mesh
        self._depths = dict(model_depths or {})
        self._lock = threading.Lock()
        # model name -> resident services for that name, spec order
        # (first one is the name's default — usually the
        # follow-the-registry resident)
        self._residents: Dict[str, List[PredictionService]] = {}
        self._order: List[PredictionService] = []
        self._canaries: Dict[str, _Canary] = {}
        self._shadows: Dict[str, _Shadow] = {}
        self._canary_binding = None
        specs = [parse_model_spec(s) for s in models]
        for mname, ver in specs:
            svc = self._make_resident(mname, ver)
            self._residents.setdefault(mname, []).append(svc)
            self._order.append(svc)
        default_model = default_model or specs[0][0]
        if default_model not in self._residents:
            raise ValueError(f"default model {default_model!r} is not in "
                             f"the resident set {sorted(self._residents)}")
        self.default_model = default_model
        self._default = self._residents[default_model][0]
        if metrics is not None:
            self._bind_canary_metrics(metrics)

    # ---- residents ----
    def _sub_policy(self, mname: str) -> BatchPolicy:
        """The model's own admission policy: the shared BatchPolicy with
        a per-model queue depth (ps.model.<name>.queue.max.depth,
        defaulting to the fleet-wide ps.queue.max.depth) — the tenant
        isolation boundary."""
        depth = int(self._depths.get(mname,
                                     self.policy.max_queue_depth) or 0)
        return dataclasses.replace(self.policy, max_queue_depth=depth)

    def _make_resident(self, mname: str, ver: Optional[int],
                       sub: str = "") -> PredictionService:
        base = f"{self.name}.{mname}" if self.name else mname
        if ver is not None:
            base = f"{base}:{ver}"
        common = dict(policy=self._sub_policy(mname), warm=self._warm,
                      delim=self.delim, name=base + sub,
                      host_label=self.host_label, model_label=mname,
                      counters=self.counters,
                      timer=StepTimer(keep_samples=self._latency_window),
                      metrics=self._metrics,
                      wire_native=self._wire_native)
        if ver is None:
            # follow the registry's serving version (hot-swap refresh
            # converges this resident like any single-model service)
            return PredictionService(
                registry=self.registry, model_name=mname,
                buckets=self._buckets, quantized=self._quantized,
                shared_cores=self._shared_cores,
                device=self._device, serve_mesh=self._serve_mesh,
                **common)
        # version-pinned resident: fixed predictor, refresh is a no-op
        loaded = self.registry.load(mname, ver)
        pred = make_predictor(loaded, buckets=self._buckets,
                              delim=self.delim,
                              quantized=self._quantized,
                              shared_cores=self._shared_cores,
                              device=self._device,
                              serve_mesh=self._serve_mesh)
        svc = PredictionService(pred, **common)
        svc.version = ver
        svc.model_name = mname
        return svc

    def models(self) -> List[str]:
        """Resident model names, spec order."""
        return list(self._residents)

    def _resolve(self, tag) -> Optional[PredictionService]:
        if tag is None:
            return self._default
        mname, ver = tag
        svcs = self._residents.get(mname)
        if not svcs:
            return None
        if ver is None:
            return svcs[0]
        for s in svcs:
            if s.version == ver:
                return s
        return None

    # ---- request entries ----
    def submit(self, row, trace=None, sample_local: bool = True):
        """Unrouted submit: the default model (the single-model wire
        contract for requests carrying no ``m=`` field)."""
        return self.submit_routed(row, trace=trace,
                                  sample_local=sample_local)

    def submit_routed(self, row, rid=None, model_tag=None, trace=None,
                      sample_local: bool = True):
        """Route one request: resolve the ``m=`` tag (None = default
        model), apply the model's canary split and shadow policy, submit
        to the owning sub-service (whose OWN admission depth answers
        ``busy``).  An unknown tag resolves to an immediately-answered
        ``error`` future plus ``Serving/UnknownModel`` — never a
        silently mis-routed prediction."""
        svc = self._resolve(model_tag)
        if svc is None:
            from concurrent.futures import Future
            from ..telemetry import instant
            self.counters.increment("Serving", "UnknownModel")
            tag = model_tag[0] if model_tag else "?"
            instant("serve.unknown_model", cat="serving", model=tag)
            fut: "Future[str]" = Future()
            fut.set_result(self.error_label)
            return fut
        mname = svc.model_label or self.default_model
        self.counters.increment("Model", f"{mname}/Requests")
        can = self._canaries.get(mname)
        if can is not None and rid is not None \
                and canary_split(rid, can.percent):
            self.counters.increment("Model", f"{mname}/CanaryRequests")
            svc = can.service
        fut = svc.submit(row, trace=trace, sample_local=sample_local)
        if fut.done():
            # admission rejects (and late sheds) resolve synchronously:
            # attribute them to the tenant as well as the aggregate
            try:
                if fut.result(timeout=0) == svc.busy_label:
                    self.counters.increment("Model", f"{mname}/Rejected")
                    from ..telemetry import instant
                    instant("serve.rejected", cat="serving", model=mname)
            except Exception:
                pass
        sh = self._shadows.get(mname)
        if sh is not None and svc is not can_service(can):
            self._shadow_score(sh, mname, row, fut)
        return fut

    def _shadow_score(self, sh: _Shadow, mname: str, row, champ_fut):
        """Submit a copy to the shadow candidate; its reply is DISCARDED
        (the champion answers the wire), divergence from the champion's
        label is counted once both resolve."""
        shadow_fut = sh.service.submit(list(row), trace=None,
                                       sample_local=False)

        def when_shadow(sf):
            # chain (not two callbacks racing on "both done"): the
            # comparison runs exactly once, after both resolved
            def when_champ(cf):
                try:
                    a = cf.result(timeout=0)
                    b = sf.result(timeout=0)
                except Exception:
                    return
                self.counters.increment("Model",
                                        f"{mname}/ShadowRequests")
                if a != b:
                    self.counters.increment(
                        "Model", f"{mname}/ShadowDivergence")
            champ_fut.add_done_callback(when_champ)
        shadow_fut.add_done_callback(when_shadow)

    # ---- deployment policies ----
    def install_canary(self, mname: str, version: Optional[int] = None,
                       percent: int = 10,
                       predictor=None,
                       pos_class: Optional[str] = None,
                       neg_class: Optional[str] = None,
                       window: int = 32) -> None:
        """Start canarying ``mname``: a deterministic ``percent``% of its
        requests (by request id) route to the candidate — ``version``
        from the registry, or an explicit ``predictor`` (the retrain
        controller hands its just-built candidate directly, pre-publish).
        With ``pos_class``/``neg_class`` given, one AccuracyTracker per
        arm judges outcomes recorded via :meth:`record_canary_outcome`."""
        if mname not in self._residents:
            raise ValueError(f"model {mname!r} is not resident")
        if not 0 <= int(percent) <= 100:
            raise ValueError(f"canary percent must be 0..100, "
                             f"got {percent}")
        if predictor is None:
            if version is None:
                raise ValueError("install_canary needs version= or "
                                 "predictor=")
            loaded = self.registry.load(mname, version)
            predictor = make_predictor(
                loaded, buckets=self._buckets, delim=self.delim,
                quantized=self._quantized,
                shared_cores=self._shared_cores,
                device=self._device, serve_mesh=self._serve_mesh)
        base = f"{self.name}.{mname}" if self.name else mname
        svc = PredictionService(
            predictor, policy=self._sub_policy(mname), warm=self._warm,
            delim=self.delim, name=f"{base}.canary",
            host_label=self.host_label, model_label=mname,
            counters=self.counters,
            timer=StepTimer(keep_samples=self._latency_window),
            metrics=self._metrics, wire_native=self._wire_native)
        svc.version = version
        svc.start()
        trackers = {"champion": None, "candidate": None}
        if pos_class is not None and neg_class is not None:
            trackers = {
                arm: _probe_tracker(pos_class, neg_class, window)
                for arm in ("champion", "candidate")}
        with self._lock:
            old = self._canaries.get(mname)
            self._canaries[mname] = _Canary(svc, version, percent,
                                            trackers)
        if old is not None:
            old.service.stop(drain_s=1.0)

    def clear_canary(self, mname: str) -> Optional[_Canary]:
        """End ``mname``'s canary (champion takes 100% again).  Returns
        the retired state (final per-arm accuracy/outcome counts)."""
        with self._lock:
            can = self._canaries.pop(mname, None)
        if can is not None:
            can.service.stop(drain_s=1.0)
        return can

    def record_canary_outcome(self, mname: str, rid, predicted: str,
                              actual: str) -> Optional[str]:
        """Attribute one delayed-label outcome to its canary arm — the
        SAME deterministic split that routed the request re-derives the
        arm from the id — and fold it into that arm's AccuracyTracker
        window.  Returns the arm, or None when no canary is live."""
        can = self._canaries.get(mname)
        if can is None:
            return None
        arm = "candidate" if canary_split(rid, can.percent) \
            else "champion"
        can.outcomes[arm] += 1
        if predicted == actual:
            can.correct[arm] += 1
        tracker = can.trackers.get(arm)
        if tracker is not None:
            recs = tracker.record([predicted], [actual])
            if recs:
                can.accuracy[arm] = int(recs[-1].value)
        return arm

    def canary_state(self, mname: str) -> Optional[Dict]:
        """Scrape-shaped snapshot of a live canary: per-arm outcome
        counts, running accuracy, last closed AccuracyTracker window."""
        can = self._canaries.get(mname)
        if can is None:
            return None
        out = {"version": can.version, "percent": can.percent, "arms": {}}
        for arm in ("champion", "candidate"):
            n = can.outcomes[arm]
            out["arms"][arm] = {
                "outcomes": n,
                "correct": can.correct[arm],
                "running_accuracy":
                    (100.0 * can.correct[arm] / n) if n else None,
                "window_accuracy": can.accuracy[arm],
            }
        return out

    def install_shadow(self, mname: str,
                       version: Optional[int] = None,
                       predictor=None) -> None:
        """Shadow a candidate behind ``mname``: every request for the
        model also scores on the candidate; replies come ONLY from the
        champion, divergence is counted."""
        if mname not in self._residents:
            raise ValueError(f"model {mname!r} is not resident")
        if predictor is None:
            if version is None:
                raise ValueError("install_shadow needs version= or "
                                 "predictor=")
            loaded = self.registry.load(mname, version)
            predictor = make_predictor(
                loaded, buckets=self._buckets, delim=self.delim,
                quantized=self._quantized,
                shared_cores=self._shared_cores,
                device=self._device, serve_mesh=self._serve_mesh)
        base = f"{self.name}.{mname}" if self.name else mname
        svc = PredictionService(
            predictor, policy=self._sub_policy(mname), warm=self._warm,
            delim=self.delim, name=f"{base}.shadow",
            host_label=self.host_label, model_label=mname,
            counters=self.counters,
            timer=StepTimer(keep_samples=self._latency_window),
            metrics=self._metrics, wire_native=self._wire_native)
        svc.version = version
        svc.start()
        with self._lock:
            old = self._shadows.get(mname)
            self._shadows[mname] = _Shadow(svc, version)
        if old is not None:
            old.service.stop(drain_s=1.0)

    def clear_shadow(self, mname: str) -> None:
        with self._lock:
            sh = self._shadows.pop(mname, None)
        if sh is not None:
            sh.service.stop(drain_s=1.0)

    # ---- canary scrape series ----
    def _bind_canary_metrics(self, registry) -> None:
        g = registry.gauge(
            "avenir_canary",
            "per-arm canary deployment state (accuracy pct, outcome "
            "counts, split percent)",
            labels=("host", "model", "arm", "key"))
        host = self.host_label or ""

        def probe():
            for mname in list(self._canaries):
                st = self.canary_state(mname)
                if st is None:
                    continue
                for arm, a in st["arms"].items():
                    g.set(a["outcomes"], host=host, model=mname,
                          arm=arm, key="outcomes")
                    if a["running_accuracy"] is not None:
                        g.set(a["running_accuracy"], host=host,
                              model=mname, arm=arm, key="accuracy")
                    if a["window_accuracy"] is not None:
                        g.set(a["window_accuracy"], host=host,
                              model=mname, arm=arm,
                              key="window_accuracy")
                g.set(st["percent"], host=host, model=mname,
                      arm="candidate", key="percent")
        registry.register_probe(probe)
        self._canary_binding = (registry, probe, g)

    # ---- service-shaped surface (fleet/autoscaler/controller verbs) ----
    @property
    def version(self) -> Optional[int]:
        return self._default.version

    @property
    def model_name(self) -> Optional[str]:
        return self.default_model

    @property
    def degraded(self) -> Optional[str]:
        return self._default.degraded

    @property
    def error_label(self) -> str:
        return self._default.error_label

    @property
    def busy_label(self) -> str:
        return self._default.busy_label

    @property
    def late_label(self) -> str:
        return self._default.late_label

    def record_request_trace(self, ctx) -> None:
        """Close one sampled wire request's trace (fleet flush calls
        this after the reply pushed).  The default resident owns the
        component histograms — routed requests' spans already carry
        their model label from the serving service itself."""
        self._default.record_request_trace(ctx)

    @property
    def timer(self) -> StepTimer:
        """One merged StepTimer over every resident's samples — built on
        read (stats callers, the autoscaler's p99 sense).  ``calls`` are
        SUMMED from the sub-timers so staleness checks see a monotonic
        count even when the bounded sample windows are full."""
        merged = StepTimer(keep_samples=self._latency_window
                           * max(1, len(self._order)))
        for svc in self._all_services():
            for sname, dq in list(svc.timer.samples.items()):
                for _ in range(3):
                    try:
                        samples = list(dq)
                        break
                    except RuntimeError:
                        continue
                else:
                    samples = []
                for s in samples:
                    merged.record(sname, s)
        totals: Dict[str, float] = {}
        calls: Dict[str, int] = {}
        for svc in self._all_services():
            for sname, c in svc.timer.calls.items():
                calls[sname] = calls.get(sname, 0) + c
            for sname, t in svc.timer.totals.items():
                totals[sname] = totals.get(sname, 0.0) + t
        merged.calls.update(calls)
        merged.totals.update(totals)
        return merged

    def model_timers(self) -> Dict[str, StepTimer]:
        """model name -> that resident's own StepTimer (per-tenant p99,
        the noisy-neighbor bench instrument)."""
        return {mname: svcs[0].timer
                for mname, svcs in self._residents.items()}

    def _all_services(self) -> List[PredictionService]:
        with self._lock:
            extra = [c.service for c in self._canaries.values()] \
                + [s.service for s in self._shadows.values()]
        return self._order + extra

    def model_queue_depths(self) -> Dict[str, int]:
        """model name -> queued-request depth (summed over that name's
        residents) — the autoscaler's per-tenant pressure sensor."""
        out: Dict[str, int] = {}
        for mname, svcs in self._residents.items():
            out[mname] = sum(s.stats()["queue_depth"] for s in svcs)
        return out

    def stats(self) -> Dict:
        """Aggregate snapshot in the PredictionService shape (the fleet
        sums these keys across workers) plus a ``per_model`` breakdown
        keyed by model name."""
        per = {}
        for mname, svcs in self._residents.items():
            st = {"queue_depth": 0, "in_flight": 0, "model_version": None}
            for s in svcs:
                ss = s.stats()
                st["queue_depth"] += ss["queue_depth"]
                st["in_flight"] += ss["in_flight"]
            st["model_version"] = svcs[0].version
            st["requests"] = self.counters.get("Model", f"{mname}/Requests")
            st["rejected"] = self.counters.get("Model", f"{mname}/Rejected")
            per[mname] = st
        return {
            "queue_depth": sum(p["queue_depth"] for p in per.values()),
            "in_flight": sum(p["in_flight"] for p in per.values()),
            "served": self.counters.get("Serving", "Requests"),
            "errors": self.counters.get("Serving", "BadRequests"),
            "batches": self.counters.get("Serving", "Batches"),
            "hot_swaps": self.counters.get("Serving", "HotSwaps"),
            "rejected": self.counters.get("Serving", "Rejected"),
            "window_ms": self._default._adaptive_wait_ms,
            "degraded": self.degraded,
            "model_version": self.version,
            "host": self.host_label or "",
            "model": self.default_model,
            "models": list(self._residents),
            "per_model": per,
        }

    def refresh(self) -> bool:
        """Converge every follow-the-registry resident onto its model's
        serving version (version-pinned residents stay pinned).  Returns
        whether ANY resident swapped."""
        swapped = False
        for svc in self._order:
            try:
                swapped = bool(svc.refresh()) or swapped
            except Exception:
                raise
        return swapped

    def mark_degraded(self, reason: str) -> None:
        for svc in self._order:
            svc.mark_degraded(reason)

    def start(self) -> "ModelRouter":
        for svc in self._all_services():
            svc.start()
        return self

    def stop(self, drain_s: float = 5.0) -> None:
        if self._canary_binding is not None:
            registry, probe, g = self._canary_binding
            self._canary_binding = None
            registry.unregister_probe(probe)
        for svc in self._all_services():
            svc.stop(drain_s=drain_s)


def can_service(can: Optional[_Canary]):
    """The canary's candidate service, or None — so identity checks
    against "the service that answered" read cleanly at the call site."""
    return can.service if can is not None else None
