"""Warm, shape-bucketed predictors: the per-request compile problem solved
once for every model family.

A naive serving loop hands XLA a fresh batch shape per request mix — and a
fresh multi-second compile with it (the Execution Templates problem:
repeated short tasks must reuse pre-validated execution state).  Every
``Predictor`` here pads incoming micro-batches up to a fixed bucket size
(mirroring PR 1's pad-to-one-shape forest level kernels), so each bucket
compiles exactly once and every later request of any size <= that bucket
reuses the warm executable.  ``warm()`` pre-compiles all buckets at model
load, moving the cost off the request path entirely.

Compile accounting: the per-instance jitted cores bump ``compile_count``
from INSIDE the traced function — tracing runs once per compilation, so the
counter is a true retrace/compile meter (the bucketed-jit tests pin it).

Padding uses a copy of the batch's last row: per-row prediction is
independent in every model family, so pad rows cannot perturb real rows;
results are sliced back to the true request count.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.schema import FeatureSchema
from ..core.table import ColumnarTable, _make_splitter, encode_rows
from .registry import BAYES, FOREST, LOGISTIC, MLP, LoadedModel

DEFAULT_BUCKETS = (1, 8, 64, 512)
AMBIGUOUS = "ambiguous"   # the ensemble's min-odds veto, as a wire label

# --------------------------------------------------------------------------
# cross-model executable sharing (ISSUE 18)
# --------------------------------------------------------------------------
# Co-resident models whose compiled programs are structurally identical —
# same family variant, same schema fingerprint, same bucket ladder, same
# mesh, same parameter shapes/dtypes — share ONE jitted core: the weights
# travel as call arguments, not as closed-over constants, so a second
# model with matching axes reuses the first model's warm executables
# instead of recompiling them (Execution Templates' install-once/
# instantiate-cheap argument applied across the model zoo).  The key is
# derived from the same axes ProgramCache uses (stage variant, schema fp,
# shapes/dtypes, mesh fp) — NEVER model identity.  Opt-in per predictor
# (``shared_cores=True``, the router default): the per-instance closure
# path stays byte-for-byte what it was.  Trace attribution: the shared
# core bumps the BUILDING predictor's ``compile_count`` (tracing happens
# once, under the builder), so a sharing model's own count stays 0 — the
# pinned instrument for the sharing tests.
_SHARED_CORES: Dict[tuple, Any] = {}


def _shared_core_key(variant, schema: FeatureSchema,
                     buckets: Sequence[int], arg_fp) -> tuple:
    from ..parallel.mesh import runtime_context
    from ..pipeline.cache import mesh_fingerprint, schema_fingerprint
    return (variant, schema_fingerprint(schema), tuple(buckets),
            mesh_fingerprint(runtime_context()), arg_fp)


def _shared_core(key: tuple, build):
    fn = _SHARED_CORES.get(key)
    if fn is None:
        fn = build()
        _SHARED_CORES[key] = fn
    return fn


def _array_fp(arrays) -> tuple:
    """Shape/dtype fingerprint of a flat array sequence (the
    shapes/dtypes cache axis)."""
    return tuple((tuple(np.shape(a)), str(np.result_type(a)))
                 for a in arrays)


@jax.jit
def _delta_patch_jit(cur, upd, idx):
    """Functional scatter of a delta slice into a resident stacked
    tensor: NO donation on purpose — serving threads snapshot the
    predictor outside the swap lock, so the old buffer must stay valid
    until the argument-tuple swap completes (apply_delta)."""
    return cur.at[idx].set(upd)


class Predictor:
    """Base: tokenized-row requests -> class-label strings, bucketed."""

    kind = "?"

    def __init__(self, schema: FeatureSchema,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 delim: str = ",", shared_cores: bool = False):
        self.schema = schema
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        self.delim = delim
        self._split = _make_splitter(delim)
        self.compile_count = 0
        self.shared_cores = bool(shared_cores)

    # ---- bucketing ----
    def bucket_size(self, n: int) -> int:
        """Smallest bucket >= n; requests beyond the largest bucket are
        chunked by the caller (predict_rows)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def dummy_row(self) -> List[str]:
        """One schema-valid record (used to pre-compile buckets)."""
        row = [""] * self.schema.num_columns
        for f in self.schema.fields:
            if f.is_categorical:
                row[f.ordinal] = (f.cardinality or [""])[0]
            elif f.is_numeric:
                lo = f.min if f.min is not None else 0
                row[f.ordinal] = str(int(lo)) if f.is_integer \
                    else repr(float(lo))
            else:
                row[f.ordinal] = "x"
        return row

    def warm(self) -> "Predictor":
        """Compile every bucket before traffic arrives: one dummy batch per
        bucket size runs the full predict path, so the first real request
        hits a warm executable."""
        d = self.dummy_row()
        for b in self.buckets:
            self.predict_rows([list(d)] * b)
        return self

    # ---- request entries ----
    def predict_line(self, line: str) -> Optional[str]:
        return self.predict_rows([self._split(line)])[0]

    def _bucketed_tables(self, rows: List[List[str]]):
        """Yield (table, n_valid) per top-bucket chunk: rows are split at
        the largest bucket, each chunk padded up to its bucket size with
        copies of its last row — THE shape discipline every predict entry
        shares, so a padding/bucketing fix lands everywhere at once."""
        top = self.buckets[-1]
        for s in range(0, len(rows), top):
            chunk = rows[s:s + top]
            n = len(chunk)
            b = self.bucket_size(n)
            yield encode_rows(chunk + [chunk[-1]] * (b - n),
                              self.schema), n

    def prepare_rows(self, rows: List[List[str]]):
        """The HOST half of predict_rows: tokenized records -> encoded,
        bucket-padded tables.  Split out so the continuous serving loop
        can run it on the assembler thread while the previous batch's
        device predict is in flight (stage_chunks' parse ‖ compute split
        applied to serving — encode is the dominant non-device cost of a
        small-model predict).  The returned value is opaque; hand it to
        :meth:`predict_prepared` on the SAME predictor instance (a
        hot-swap between the two must finish the batch on the old
        model).  The native wire codec (io/native_wire.WireCodec) is the
        alternate producer of the same (table, n_valid) chunk list —
        assembled straight from socket bytes, bit-identical to this
        path by the differential fuzz contract."""
        return list(self._bucketed_tables(rows)) if rows else []

    def predict_prepared(self, prepared) -> List[Optional[str]]:
        """The DEVICE half: run warm bucket executables over tables from
        :meth:`prepare_rows`."""
        out: List[Optional[str]] = []
        for table, n in prepared:
            out.extend(self._predict_table(table)[:n])
        return out

    def predict_rows(self, rows: List[List[str]]) -> List[Optional[str]]:
        """Predict a micro-batch of tokenized records.  Batches larger than
        the top bucket split into top-bucket chunks (each still one warm
        executable)."""
        if not rows:
            return []
        return self.predict_prepared(self.prepare_rows(rows))

    # ---- pre-binned int8 wire form (predictq) ----
    @property
    def supports_prebinned(self) -> bool:
        """True when :meth:`predict_prebinned` can serve the int8
        ``predictq`` wire form (quantized forests only)."""
        return False

    @property
    def prebinned_width(self) -> int:
        """F of the (n, F) int8 pre-binned row — 0 when unsupported."""
        return 0

    def predict_prebinned(self, qv, qc) -> List[Optional[str]]:
        raise NotImplementedError(
            f"{type(self).__name__} has no pre-binned serving path")

    # ---- subclass contract ----
    def _predict_table(self, table: ColumnarTable) -> List[Optional[str]]:
        raise NotImplementedError

    def _note_trace(self) -> None:
        """Called from inside traced cores: fires once per (re)trace."""
        self.compile_count += 1


class ForestPredictor(Predictor):
    """Decision forest serving via the batch path's own vote kernel
    (models/forest._ensemble_vote_body) wrapped in a per-instance jit, so
    responses are exactly what the offline ModelPredictor job would emit
    for the same records — the only difference is who owns the compile
    cache.  ``None`` (min-odds veto) maps to ``ambiguous_label`` by the
    service layer.

    Placement (TPU_NOTES §32): by default the core binds the runtime
    default device.  ``device=`` pins this predictor's stacked tensors
    (and each request batch) to one specific chip — the fleet's
    round-robin worker map.  ``serve_mesh=`` shards the stacked member
    tensors over the TREE axis of a multi-chip mesh instead (forests too
    big for one chip's HBM): each chip computes its local members'
    partial (n, K) vote tally and ONE psum merges them — bit-identical
    to the single-chip vote because tallies are sums of integer-valued
    f32 terms.  In every placement the member tensors travel as runtime
    ARGUMENTS (``self._extra``), never closed-over constants, so (a) the
    PR 18 shared-core keys still hold and (b) ``apply_delta`` can patch
    changed trees in place and swap the argument tuple atomically
    without touching the compiled program."""

    kind = FOREST

    def __init__(self, path_lists, schema: FeatureSchema,
                 weights: Optional[Sequence[float]] = None,
                 min_odds_ratio: float = 1.0, quantized=None,
                 serve_mesh=None, device=None,
                 tree_shas: Optional[Sequence[str]] = None, **kw):
        super().__init__(schema, **kw)
        from ..models.forest import EnsembleModel
        from ..models.tree import DecisionTreeModel
        if serve_mesh is not None and device is not None:
            raise ValueError("serve_mesh and device are mutually "
                             "exclusive placements")
        self.models = [DecisionTreeModel(pl, schema) for pl in path_lists]
        self.single = len(self.models) == 1
        self.quantized = None
        self._core_q = None
        self._extra = None
        self._jitted = None
        self._device = device
        self._serve_mesh = None
        self._min_odds = float(min_odds_ratio)
        self._vote_backend = "xla"
        self.tree_shas = list(tree_shas) if tree_shas else None
        if self.single:
            if quantized is not None:
                import warnings
                warnings.warn(
                    "ps.quantized: single-tree forests serve through the "
                    "per-tree predict path; quantized sidecar ignored, "
                    "serving the float model", RuntimeWarning)
            self.ensemble = None
            self._core = None
            return
        mesh = self._resolve_serve_mesh(serve_mesh)
        self.ensemble = EnsembleModel(self.models, weights=weights,
                                      min_odds_ratio=min_odds_ratio,
                                      require_odd=False,
                                      stack=mesh is None)
        if mesh is not None and self.ensemble.stacked_host() is None:
            import warnings
            warnings.warn(
                "serve_mesh: ensemble has no stacked device form "
                "(degenerate member or non-f32-exact bounds); serving "
                "the host vote path single-chip", RuntimeWarning)
            mesh = None
            self.ensemble._stacked = self.ensemble._stack_members()
        self._serve_mesh = mesh
        if mesh is not None:
            self._build_sharded_core(mesh)
        elif self.ensemble._stacked is not None:
            self._build_core()
        else:
            # degenerate member / non-f32-exact bounds: the host vote path
            # is exact and compile-free, so bucketing is moot
            self._core = None
        if quantized is not None:
            # int8 serving (serving/quantized.py): valid only when it was
            # quantized from THIS ensemble's stacked form and label order
            if self._core is None:
                import warnings
                warnings.warn(
                    "ps.quantized: ensemble has no stacked device form; "
                    "serving the float host path", RuntimeWarning)
            elif list(quantized.classes) != list(self.ensemble.classes):
                import warnings
                warnings.warn(
                    "ps.quantized: sidecar class order does not match "
                    "the loaded model; serving the float model",
                    RuntimeWarning)
            else:
                self.quantized = quantized
                vote = quantized.vote_fn()

                def core_q(qv, qc):
                    self._note_trace()
                    return vote(qv, qc)
                self._core_q = jax.jit(core_q)

    @staticmethod
    def _resolve_serve_mesh(serve_mesh):
        """``serve_mesh`` -> a 1-axis Mesh (or None for single-chip):
        ``True`` = a tree-axis mesh over all devices, an int = over the
        first n, a Mesh = as given.  A 1-device result degrades to the
        plain single-chip core (nothing to shard)."""
        if serve_mesh is None or serve_mesh is False:
            return None
        from jax.sharding import Mesh
        from ..parallel.mesh import tree_mesh
        if isinstance(serve_mesh, Mesh):
            mesh = serve_mesh
        elif serve_mesh is True:
            mesh = tree_mesh()
        else:
            mesh = tree_mesh(int(serve_mesh))
        if len(mesh.axis_names) != 1:
            raise ValueError(
                f"serve_mesh must be a 1-axis mesh, got axes "
                f"{mesh.axis_names}")
        return mesh if mesh.devices.size > 1 else None

    def _build_core(self):
        """The single-device core (optionally pinned to ``device=``):
        member tensors as runtime args (``self._extra``), vote body
        dispatched xla/pallas exactly as before."""
        from ..models.forest import _ensemble_vote_body
        from ..ops.pallas.dispatch import pallas_interpret, resolve_backend
        *consts, wvec, _kernel = self.ensemble._stacked
        min_odds = jnp.float32(self._min_odds)
        self._vote_backend = resolve_backend()
        if self._vote_backend == "pallas":
            import functools as _ft
            from ..ops.pallas.vote import ensemble_vote
            body = _ft.partial(ensemble_vote, interpret=pallas_interpret())
        else:
            body = _ensemble_vote_body
        if self._device is not None:
            consts = [jax.device_put(c, self._device) for c in consts]
            wvec = jax.device_put(wvec, self._device)
        self._extra = (*consts, wvec, min_odds)
        variant = ("forest", self._vote_backend) \
            if self._device is None \
            else ("forest", self._vote_backend, self._device.id)

        def build():
            def core(vals, codes, *cs):
                self._note_trace()
                return body(vals, codes, *cs)
            return jax.jit(core)
        if self.shared_cores:
            # weights as call args, keyed on the ProgramCache axes: a
            # co-resident model with the same variant/schema/buckets/
            # mesh/shape structure reuses this executable
            key = _shared_core_key(variant, self.schema, self.buckets,
                                   _array_fp(self._extra))
            self._jitted = _shared_core(key, build)
        else:
            self._jitted = build()
        dev = self._device
        if dev is None:
            self._core = lambda vals, codes: \
                self._jitted(vals, codes, *self._extra)
        else:
            # request batches follow the model's chip (D2D re-place when
            # the feature cache staged them on the default device)
            self._core = lambda vals, codes: \
                self._jitted(jax.device_put(vals, dev),
                             jax.device_put(codes, dev), *self._extra)

    def _build_sharded_core(self, mesh):
        """The mesh-sharded core: member tensors shard over the tree
        axis (leading T dim, padded to the shard count with zero-weight
        never-match members), rows/tally replicate.  Each shard computes
        its local (n, K) partial tally — pallas kernel or XLA body, both
        mesh-aware — and ONE ``psum`` merges; the min-odds finalize runs
        on the complete tally.  Bit-identical to the single-chip vote
        (integer-exact f32 sums commute with the shard partition)."""
        import functools as _ft
        from jax.experimental.shard_map import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..models.forest import _member_votes_body, _vote_finalize
        from ..ops.pallas.dispatch import pallas_interpret, resolve_backend
        from ..parallel.mesh import runtime_context
        ax = mesh.axis_names[0]
        S = int(mesh.devices.size)
        lo, hi, num_r, cat_m, cat_r, cls_oh = self.ensemble.stacked_host()
        wv = np.asarray(self.ensemble.weights, np.float32)
        T = lo.shape[0]
        padT = (-T) % S
        if padT:
            # zero-weight never-match pad members: every predicate row
            # rejects (lo=+inf restricted), the class one-hot is zero and
            # the weight is zero — three independent reasons the pad
            # shard slots contribute exactly 0.0 to the psum'd tally
            def padm(a, fill):
                return np.concatenate(
                    [a, np.full((padT,) + a.shape[1:], fill, a.dtype)])
            lo = padm(lo, np.inf)
            hi = padm(hi, -np.inf)
            num_r = padm(num_r, True)
            cat_m = padm(cat_m, False)
            cat_r = padm(cat_r, False)
            cls_oh = padm(cls_oh, 0.0)
            wv = padm(wv, 0.0)
        shard = NamedSharding(mesh, P(ax))
        repl = NamedSharding(mesh, P())
        consts = [jax.device_put(a, shard)
                  for a in (lo, hi, num_r, cat_m, cat_r, cls_oh)]
        wvec = jax.device_put(wv, shard)
        min_odds = jax.device_put(np.float32(self._min_odds), repl)
        platform = runtime_context().device_platform
        self._vote_backend = resolve_backend(platform, S, mesh_aware=True,
                                             site="serve.predict")
        if self._vote_backend == "pallas":
            from ..ops.pallas.vote import ensemble_partial_votes
            partial_body = _ft.partial(ensemble_partial_votes,
                                       interpret=pallas_interpret(platform))
        else:
            partial_body = _member_votes_body

        def shard_body(vals, codes, lo, hi, num_r, cat_m, cat_r, cls_oh,
                       wvec, min_odds):
            part = partial_body(vals, codes, lo, hi, num_r, cat_m, cat_r,
                                cls_oh, wvec)
            votes = jax.lax.psum(part, ax)   # THE one cross-shard merge
            return _vote_finalize(votes, min_odds)
        # check_rep=False: pallas_call has no replication rule, and the
        # out spec is genuinely replicated only after the psum anyway
        sharded = shard_map(shard_body, mesh=mesh, check_rep=False,
                            in_specs=(P(), P()) + (P(ax),) * 7 + (P(),),
                            out_specs=P())
        self._extra = (*consts, wvec, min_odds)

        def build():
            def core(vals, codes, *cs):
                self._note_trace()
                return sharded(vals, codes, *cs)
            return jax.jit(core)
        if self.shared_cores:
            # the serve mesh is NOT the runtime mesh the shared-core key
            # fingerprints, so its device set rides in the variant
            dev_ids = tuple(int(d.id) for d in mesh.devices.flat)
            key = _shared_core_key(
                ("forest-sharded", self._vote_backend, S, dev_ids),
                self.schema, self.buckets, _array_fp(self._extra))
            self._jitted = _shared_core(key, build)
        else:
            self._jitted = build()
        self._core = lambda vals, codes: \
            self._jitted(vals, codes, *self._extra)
        # the batch path (ensemble.predict) and the device gate see the
        # SAME resident tensors: pad members vote zero, so the padded
        # stacked form is vote-identical to the unpadded one
        from ..models.forest import _jitted_ensemble_vote_kernel
        Tp, P_, F = lo.shape
        cmax, K = cat_m.shape[3], cls_oh.shape[2]
        self.ensemble._vote_backend = "xla"
        self.ensemble._stacked = tuple(consts) + (
            wvec, _jitted_ensemble_vote_kernel(Tp, P_, F, cmax, K, "xla",
                                               False))

    # ---- O(delta) hot patch (ISSUE 20) ----
    def apply_delta(self, dmeta: Dict[str, Any], arrays) -> int:
        """Patch ONLY the changed trees of the resident model in place:
        upload each delta slice, scatter it into a fresh functional copy
        of the stacked tensors, and swap the core's argument tuple
        atomically at the end — the compiled program is untouched (same
        shapes, so zero recompiles) and a concurrently-dispatching
        request thread keeps a fully valid tuple at every instant (no
        donation: serving snapshots the predictor OUTSIDE the swap lock,
        so donating a resident buffer could invalidate an in-flight
        batch — TPU_NOTES §32).  Raises on ANY mismatch — parent sha
        chain, class vocabulary, slice layout — so the caller falls back
        to a full-artifact load: never wrong weights.  Returns the H2D
        bytes moved (∝ changed trees; also recorded to the active
        TransferLedger)."""
        import json as _json
        from ..core.faults import fault_point
        from ..models.tree import DecisionPathList, DecisionTreeModel
        from ..utils.tracing import note_h2d
        if self.single or self.ensemble is None:
            raise ValueError("delta patch: single-tree predictors reload "
                             "in full")
        if self._core is None or self._extra is None:
            raise ValueError("delta patch needs the stacked device vote "
                             "path (host-path ensembles reload in full)")
        if self._core_q is not None:
            raise ValueError("delta patch: quantized serving rebuilds its "
                             "int8 sidecar per version; reload in full")
        parent = list(dmeta.get("parent_tree_shas") or [])
        if not self.tree_shas or parent != list(self.tree_shas):
            raise ValueError("delta patch: parent sha chain does not "
                             "match the resident model")
        if list(dmeta.get("classes") or []) != list(self.ensemble.classes):
            raise ValueError("delta patch: class vocabulary mismatch")
        idx = np.asarray(arrays["idx"], np.int32)
        T = len(self.models)
        if idx.size and (idx.min() < 0 or idx.max() >= T):
            raise ValueError("delta patch: changed-tree index out of "
                             "range")
        *consts, wvec, min_odds = self._extra
        names = ("lo", "hi", "num_r", "cat_m", "cat_r", "cls_oh")
        for name, cur in zip(names, consts):
            upd = np.asarray(arrays[name])
            if upd.shape[1:] != tuple(cur.shape[1:]) or \
                    upd.shape[0] != idx.size or \
                    np.dtype(upd.dtype) != np.dtype(cur.dtype):
                raise ValueError(
                    f"delta patch: slice {name} layout "
                    f"{upd.shape}/{upd.dtype} does not match resident "
                    f"{cur.shape}/{cur.dtype}")
        new_wv = np.asarray(arrays["wvec"], np.float32)
        if new_wv.shape != (T,):
            raise ValueError("delta patch: wvec shape mismatch")
        changed_trees = dmeta.get("changed_trees") or []
        if len(changed_trees) != idx.size:
            raise ValueError("delta patch: changed_trees does not match "
                             "the index list")
        moved = 0
        idx_dev = jnp.asarray(idx)
        moved += idx.nbytes
        note_h2d(idx.nbytes)
        new_consts = []
        for name, cur in zip(names, consts):
            # a kill anywhere in this loop leaves self._extra untouched
            # (old tuple fully valid) — the torn-delta full-load fallback
            fault_point("swap_patch")
            upd = np.asarray(arrays[name])
            note_h2d(upd.nbytes)
            moved += upd.nbytes
            new = _delta_patch_jit(cur, jnp.asarray(upd), idx_dev)
            new_consts.append(jax.device_put(new, cur.sharding))
        fault_point("swap_patch")
        # wvec ships whole — (T,) f32 is noise next to any slice — padded
        # back out to the resident (sharded) length
        Tp = int(wvec.shape[0])
        wv_padded = new_wv if Tp == T else \
            np.concatenate([new_wv, np.zeros(Tp - T, np.float32)])
        note_h2d(wv_padded.nbytes)
        moved += wv_padded.nbytes
        new_wvec = jax.device_put(jnp.asarray(wv_padded), wvec.sharding)
        # host-side twins: the changed members' DecisionTreeModels (the
        # host fallback path and _lut stay coherent with the device form)
        new_models = {
            int(i): DecisionTreeModel(
                DecisionPathList.from_json(_json.dumps(tj)), self.schema)
            for i, tj in zip(idx, changed_trees)}
        for i, m in new_models.items():
            self.models[i] = m       # self.models IS ensemble.models
        self.ensemble.weights = [float(w) for w in new_wv]
        # atomic swap: one tuple assignment, old arrays stay alive for
        # any in-flight batch that already snapshotted them
        self._extra = (*new_consts, new_wvec, min_odds)
        if self.ensemble._stacked is not None:
            kernel = self.ensemble._stacked[-1]
            self.ensemble._stacked = tuple(new_consts) + (new_wvec, kernel)
        self.tree_shas = list(dmeta["tree_shas"])
        return moved

    def dispatch_prepared(self, prepared):
        """The ASYNC half of predict_prepared: run the host prep and
        LAUNCH the jitted vote per bucket chunk without forcing the
        result — jax dispatch returns while XLA computes on its own
        thread pool (the §18 async-dispatch discipline), so a continuous
        serving loop can gather+encode the next batch during this one's
        device time.  Chunks that fail the device gate (or the
        single-tree/host-vote paths) compute synchronously here and ride
        along pre-resolved."""
        from ..models.tree import FeatureCache
        from ..utils.tracing import note_dispatch, note_h2d
        from ..ops.pallas.dispatch import note_backend
        staged = []
        for table, n in prepared:
            if self._core_q is not None:
                # int8 quantized wire: ~4x fewer request bytes than the
                # float path (f32/int16 vals + i32 codes); no f32-exact
                # gate — binning subsumes it.  Budget enforced at publish.
                cache = FeatureCache()
                vals, codes = cache.host(self.models[0].matrix, table)
                qv, qc = self.quantized.quantize_rows(vals, codes)
                note_h2d(qv.nbytes + qc.nbytes, transfers=2)
                note_dispatch(site="serve.predict")
                note_backend("serve.predict", "quantized")
                staged.append((True, self._core_q(jnp.asarray(qv),
                                                  jnp.asarray(qc)), n))
                continue
            if not self.single and self._core is not None:
                # same device-path gate and label decode as the batch
                # path — serving only substitutes the compile-counted
                # jit.  The cache rides into the host fallback so a
                # failed gate does not rebuild the feature arrays it
                # already built.
                cache = FeatureCache()
                dev = self.ensemble.device_inputs(table, cache)
                if dev is not None:
                    note_dispatch(site="serve.predict")
                    note_backend("serve.predict", self._vote_backend)
                    if self._serve_mesh is not None:
                        # the sharded core's single psum per batch —
                        # ledger-pinned as exactly ONE merge dispatch
                        from ..telemetry import instant
                        note_dispatch(site="serve.shard_merge")
                        instant("serve.shard_merge", cat="serving",
                                shards=int(self._serve_mesh.devices.size))
                    staged.append((True, self._core(*dev), n))
                    continue
                staged.append(
                    (False, self.ensemble._predict_host(table, cache), n))
            elif self.single:
                staged.append(
                    (False, list(self.models[0].predict(table)[0]), n))
            else:
                staged.append((False, self.ensemble.predict(table), n))
        return staged

    def readback_dispatched(self, staged) -> List[Optional[str]]:
        """The BLOCKING half: force each staged device result and decode
        labels (host-path chunks are already resolved)."""
        out: List[Optional[str]] = []
        for is_dev, v, n in staged:
            if is_dev:
                out.extend(list(self.ensemble._lut[np.asarray(v)])[:n])
            else:
                out.extend(list(v)[:n])
        return out

    def _predict_table(self, table: ColumnarTable) -> List[Optional[str]]:
        return self.readback_dispatched(
            self.dispatch_prepared([(table, table.n_rows)]))

    # ---- pre-binned int8 wire form (predictq) ----
    @property
    def supports_prebinned(self) -> bool:
        return self._core_q is not None

    @property
    def prebinned_width(self) -> int:
        if self._core_q is None:
            return 0
        return len(self.models[0].matrix.feat_ordinals)

    def predict_prebinned(self, qv, qc) -> List[Optional[str]]:
        """Serve client-pre-binned int8 rows (the ``predictq`` wire
        form): the entire host encode — tokenize, ``float()``,
        ``quantize_rows`` — is already done on the client, so a request
        is memcpy -> device.  Same bucket/pad shape discipline as
        ``_bucketed_tables`` (the warm() pass over the quantized core
        pre-compiled these shapes)."""
        if self._core_q is None:
            raise NotImplementedError(
                "predict_prebinned needs a quantized sidecar "
                "(ps.quantized)")
        from ..utils.tracing import note_dispatch, note_h2d
        from ..ops.pallas.dispatch import note_backend
        qv = np.asarray(qv, np.int8)
        qc = np.asarray(qc, np.int8)
        n_all = qv.shape[0]
        staged = []
        top = self.buckets[-1]
        for s in range(0, n_all, top):
            n = min(top, n_all - s)
            b = self.bucket_size(n)
            cv, cc = qv[s:s + n], qc[s:s + n]
            if b != n:  # pad with copies of the chunk's last row
                cv = np.concatenate([cv, np.repeat(cv[-1:], b - n, 0)])
                cc = np.concatenate([cc, np.repeat(cc[-1:], b - n, 0)])
            note_h2d(cv.nbytes + cc.nbytes, transfers=2)
            note_dispatch(site="serve.predict")
            note_backend("serve.predict", "quantized")
            staged.append((self._core_q(jnp.asarray(cv),
                                        jnp.asarray(cc)), n))
        out: List[Optional[str]] = []
        for v, n in staged:
            out.extend(list(self.ensemble._lut[np.asarray(v)])[:n])
        return out


class BayesPredictor(Predictor):
    """Naive bayes serving through models/bayes.predict itself (its kernels
    are module-level jits keyed by batch shape, so the bucket padding here
    is exactly what bounds their compile count — and co-resident bayes
    models already share executables by construction; ``shared_cores``
    is a no-op here)."""

    kind = BAYES

    def __init__(self, model, schema: Optional[FeatureSchema] = None,
                 ctx=None, **kw):
        super().__init__(schema or model.schema, **kw)
        from ..parallel.mesh import runtime_context
        self.model = model
        self.ctx = ctx or runtime_context()

    def _predict_table(self, table: ColumnarTable) -> List[Optional[str]]:
        from ..models import bayes
        return list(bayes.predict(self.model, table, self.ctx).pred_class)


class LogisticPredictor(Predictor):
    """Binary logistic serving: the trainer's exact predict math
    (sigmoid of the f32 [1, x...] design row dotted with f32 weights,
    regress/logistic.LogisticTrainer.predict) behind a per-instance jit."""

    kind = LOGISTIC

    def __init__(self, w, schema: FeatureSchema, pos_class_value: str,
                 threshold: float = 0.5, **kw):
        super().__init__(schema, **kw)
        from ..regress.logistic import pos_neg_codes
        self.w = np.asarray(w, np.float64)
        self.threshold = float(threshold)
        cf = schema.class_attr_field
        self.card = list(cf.cardinality or [])
        self.pos_code, self.neg_code = pos_neg_codes(cf, pos_class_value)

        def core(X, w):
            self._note_trace()
            return jax.nn.sigmoid(X @ w)
        if self.shared_cores:
            self._core = _shared_core(
                _shared_core_key(LOGISTIC, self.schema, self.buckets,
                                 _array_fp((self.w,))),
                lambda: jax.jit(core))
        else:
            self._core = jax.jit(core)

    def _proba_table(self, table: ColumnarTable) -> np.ndarray:
        """sigmoid([1, x...] @ w) for one bucket-padded table — the
        trainer's exact design matrix and dtypes."""
        feats = table.feature_matrix(dtype=np.float32)
        X = np.concatenate(
            [np.ones((table.n_rows, 1), np.float32), feats], axis=1)
        return np.asarray(self._core(jnp.asarray(X),
                                     jnp.asarray(self.w, jnp.float32)))

    def _predict_table(self, table: ColumnarTable) -> List[Optional[str]]:
        from ..regress.logistic import threshold_codes
        codes = threshold_codes(self._proba_table(table), self.threshold,
                                self.pos_code, self.neg_code)
        if self.card:
            return [self.card[int(c)] for c in codes]
        return [str(int(c)) for c in codes]

    def predict_proba_rows(self, rows: List[List[str]]) -> np.ndarray:
        """Bucketed positive-class probabilities (same core, same
        top-bucket chunking as predict_rows)."""
        if not rows:
            return np.zeros((0,), np.float32)
        return np.concatenate([self._proba_table(t)[:n]
                               for t, n in self._bucketed_tables(rows)])


class MLPPredictor(Predictor):
    """MLP serving: nn/mlp.forward_logits argmax (identical to mlp.predict)
    behind a per-instance jit over bucket-padded batches."""

    kind = MLP

    def __init__(self, params: Dict[str, Any], schema: FeatureSchema,
                 class_values: Optional[Sequence[str]] = None, **kw):
        super().__init__(schema, **kw)
        from ..nn import mlp as _mlp
        self.params = {k: jnp.asarray(v) for k, v in params.items()}
        cf = schema.class_attr_field
        self.class_values = list(class_values or cf.cardinality or [])

        def core(X, params):
            self._note_trace()
            return jnp.argmax(_mlp.forward_logits(params, X), axis=-1)
        if self.shared_cores:
            arg_fp = tuple(sorted(
                (k, tuple(v.shape), str(v.dtype))
                for k, v in self.params.items()))
            self._core = _shared_core(
                _shared_core_key(MLP, self.schema, self.buckets, arg_fp),
                lambda: jax.jit(core))
        else:
            self._core = jax.jit(core)

    def _predict_table(self, table: ColumnarTable) -> List[Optional[str]]:
        X = jnp.asarray(table.feature_matrix(dtype=np.float32))
        idx = np.asarray(self._core(X, self.params))
        cv = self.class_values
        return [cv[i] if i < len(cv) else str(int(i)) for i in idx]


def make_predictor(loaded: LoadedModel,
                   schema: Optional[FeatureSchema] = None,
                   buckets: Sequence[int] = DEFAULT_BUCKETS,
                   delim: str = ",", quantized: bool = False,
                   serve_mesh=None, device=None,
                   **kw) -> Predictor:
    """Registry artifact -> the right Predictor (kind-dispatched), using
    the artifact's embedded schema unless one is passed explicitly.

    ``quantized=True`` (forest only — the ``ps.quantized`` knob) loads
    the version's int8 sidecar (serving/quantized.py) and serves the
    budget-pinned quantized vote; a version without an intact sidecar
    warns and serves the float model — never refuses traffic.

    ``serve_mesh``/``device`` (forest only) select the multi-chip
    placement — tree-axis model-parallel core or a per-worker chip pin
    (see ForestPredictor); other kinds warn and serve on the default
    device."""
    schema = schema or loaded.schema
    if schema is None:
        raise ValueError(
            f"model {loaded.name!r} v{loaded.version} has no embedded "
            "schema; pass schema= to make_predictor")
    common = dict(buckets=buckets, delim=delim)
    if quantized and loaded.kind != FOREST:
        import warnings
        warnings.warn(
            f"ps.quantized: only forest artifacts have a quantized "
            f"serving path (got kind {loaded.kind!r}); serving the "
            f"float model", RuntimeWarning)
    if (serve_mesh is not None or device is not None) \
            and loaded.kind != FOREST:
        import warnings
        warnings.warn(
            f"serve_mesh/device placement applies to forest serving "
            f"only (got kind {loaded.kind!r}); serving on the default "
            f"device", RuntimeWarning)
    if loaded.kind == FOREST:
        p = loaded.params
        qf = None
        if quantized:
            import warnings
            if loaded.base_dir is None:
                warnings.warn(
                    "ps.quantized: model was not loaded from a registry "
                    "(no sidecar source); serving the float model",
                    RuntimeWarning)
            else:
                from .quantized import load_quantized
                from .registry import ModelRegistry
                qf = load_quantized(ModelRegistry(loaded.base_dir),
                                    loaded.name, loaded.version)
        return ForestPredictor(
            loaded.model, schema,
            weights=p.get("weights"),
            min_odds_ratio=float(p.get("min_odds_ratio", 1.0)),
            quantized=qf,
            serve_mesh=serve_mesh, device=device,
            tree_shas=loaded.meta.get("tree_shas"),
            **common, **kw)
    if loaded.kind == BAYES:
        return BayesPredictor(loaded.model, schema, **common, **kw)
    if loaded.kind == LOGISTIC:
        p = loaded.params
        if "pos_class_value" not in p:
            raise ValueError("logistic artifact is missing the "
                             "pos_class_value param (publish with "
                             "params={'pos_class_value': ...})")
        return LogisticPredictor(
            loaded.model, schema, p["pos_class_value"],
            threshold=float(p.get("threshold", 0.5)), **common, **kw)
    if loaded.kind == MLP:
        return MLPPredictor(loaded.model, schema,
                            class_values=loaded.class_values or None,
                            **common, **kw)
    raise ValueError(f"unknown model kind {loaded.kind!r}")
