"""Int8-quantized forest serving: 4x fewer request bytes, budget-pinned.

The float serving path ships every request's feature values (f32/f64 ->
f32 on device) and categorical codes (int32) over the host->device link;
the forest itself only ever COMPARES those values against thresholds.
Quantize both sides onto one per-feature int8 grid and the comparisons
survive as int8 compares: the per-request wire shrinks ~4x (the ``~4x``
acceptance number of ISSUE 11, measured on the serve_forest bench), and
the vote kernel's operands shrink with it.

Scheme: per feature ``f`` an affine grid ``q(v) = clip(floor((v -
fmin_f) / scale_f), 0, 254) - 127`` over the union of the member
thresholds' finite range and the schema min/max; thresholds bin through
the SAME map (-inf -> -128, +inf -> +127 sentinels), so ``v > lo``
becomes ``q(v) > q(lo)`` exactly except where a value and its threshold
collide in one bin.  That collision is the WHOLE accuracy cost, and it
is pinned: :func:`publish_quantized` scores the quantized vote against
the float ensemble on a sample at publish time and REFUSES to attach
the sidecar when the mismatch fraction exceeds the pinned budget
(default ``DEFAULT_BUDGET``).  NaN values map to the -128 sentinel (an
int8 value no finite threshold interval admits — the float path's
NaN-never-matches semantics).

Artifact: a ``quantized.json`` + ``quantized.npz`` sidecar pair on the
published registry version (the generic ``add_sidecar`` manifest
machinery, so the intactness probe covers it).  Serving selects it with
the ``ps.quantized`` knob; a version without an intact sidecar WARNS
and serves the float model — quantization is an optimization, never a
reason to refuse traffic (torn-sidecar fallback pinned by
tests/test_pallas_kernels.py fault injection).

The vote kernel is the int8 twin of ``models.forest._ensemble_vote_body``
(same structure, int32 compares), backend-dispatched like the float
kernel: pallas (ops/pallas/vote.quantized_vote) on TPU / forced, XLA
otherwise — launches tagged ``serve.predict`` / backend ``quantized``
in the ledger either way.
"""

from __future__ import annotations

import io as _io
import json
import re
import warnings
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

QUANTIZED_JSON = "quantized.json"
QUANTIZED_NPZ = "quantized.npz"
FORMAT_VERSION = 1
DEFAULT_BUDGET = 0.01      # default pinned accuracy-delta budget (1%)

_LEVELS = 254              # int8 grid cells: q in [-127, 127]
_NAN_Q = np.int8(-128)     # sentinel no finite interval admits
_LO_NEG_INF = np.int8(-128)
_HI_POS_INF = np.int8(127)


def _quantized_vote_body(qvals, qcodes, q_lo, q_hi, num_r, cat_m, cat_r,
                         cls_oh, wvec, min_odds):
    """The int8 twin of ``models.forest._ensemble_vote_body``: identical
    match/vote/veto structure over int32-upcast int8 operands.  One
    implementation for the XLA jit and the pallas tile kernel
    (ops/pallas/vote.quantized_vote wraps this body)."""
    import jax
    import jax.numpy as jnp
    P = cls_oh.shape[1]
    K = cls_oh.shape[2]
    v = qvals.astype(jnp.int32)
    c = qcodes.astype(jnp.int32)

    def member(lo, hi, nr, cm, cr):
        interval = (v[:, None, :] > lo[None].astype(jnp.int32)) \
            & (v[:, None, :] <= hi[None].astype(jnp.int32))
        num_ok = jnp.where(nr[None], interval, True)
        C = cm.shape[2]
        safe = jnp.clip(c, 0, C - 1)
        oh = jax.nn.one_hot(safe, C, dtype=jnp.float32)        # (n, F, C)
        gathered = jnp.einsum("nfc,pfc->npf", oh,
                              cm.astype(jnp.float32)) > 0
        cat_ok = jnp.where(cr[None], gathered & (c >= 0)[:, None, :], True)
        return (num_ok & cat_ok).all(axis=2)

    ok = jax.vmap(member)(q_lo, q_hi, num_r, cat_m, cat_r)     # (T, n, P)
    ok = ok.transpose(1, 0, 2)                                 # (n, T, P)
    first = jnp.argmax(ok, axis=2)
    foh = jax.nn.one_hot(first, P, dtype=jnp.float32)
    votes = jnp.einsum("ntp,tpk,t->nk", foh,
                       cls_oh.astype(jnp.float32), wvec,
                       precision=jax.lax.Precision.HIGHEST)
    best = jnp.argmax(votes, axis=1)
    top = votes.max(axis=1)
    second = jnp.where(jax.nn.one_hot(best, K, dtype=bool), -jnp.inf,
                       votes).max(axis=1)
    veto = (min_odds > 1.0) & \
        (top / jnp.maximum(second, 1e-12) <= min_odds)
    return jnp.where(veto, K, best).astype(jnp.int32)


@dataclass
class QuantizedForest:
    """The int8 sidecar payload: quantized member tensors + the grid."""

    q_lo: np.ndarray           # (T, P, F) int8
    q_hi: np.ndarray           # (T, P, F) int8
    num_r: np.ndarray          # (T, P, F) bool
    cat_m: np.ndarray          # (T, P, F, Cmax) bool
    cat_r: np.ndarray          # (T, P, F) bool
    cls_oh: np.ndarray         # (T, P, K) uint8 leaf votes
    wvec: np.ndarray           # (T,) float32 member weights
    scale: np.ndarray          # (F,) float64 grid cell width
    fmin: np.ndarray           # (F,) float64 grid origin
    classes: List[str]         # vote-index -> label order
    min_odds: float = 1.0
    budget: float = DEFAULT_BUDGET
    mismatch: float = 0.0      # measured at publish time

    # ---- request-side encode (host) ----
    def quantize_rows(self, vals: np.ndarray, codes: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """(n, F) float vals + int codes -> int8 pair, the per-request
        wire form (~4x fewer H2D bytes than f32 vals + i32 codes).
        Non-finite values follow the float path's comparison semantics:
        +inf clips to the top cell (it passes every finite/-inf lower
        bound and only an hi=+inf upper bound, like the float compare);
        NaN and -inf take the -128 sentinel no restricted interval
        admits (NaN never matches; -inf fails the strict ``> lo`` even
        against lo=-inf)."""
        v = np.asarray(vals, np.float64)
        with np.errstate(invalid="ignore"):
            q = np.floor((v - self.fmin[None, :]) / self.scale[None, :])
            q = np.clip(q, 0, _LEVELS) - 127
        qv = np.where(np.isposinf(v), float(_HI_POS_INF),
                      np.where(np.isfinite(v), q, float(_NAN_Q))
                      ).astype(np.int8)
        qc = np.clip(codes, -1, 127).astype(np.int8)
        return qv, qc

    # ---- sidecar round trip ----
    def to_sidecar(self) -> Dict[str, bytes]:
        meta = {
            "format_version": FORMAT_VERSION,
            "classes": list(self.classes),
            "min_odds": float(self.min_odds),
            "budget": float(self.budget),
            "mismatch": float(self.mismatch),
        }
        buf = _io.BytesIO()
        np.savez(buf, q_lo=self.q_lo, q_hi=self.q_hi, num_r=self.num_r,
                 cat_m=self.cat_m, cat_r=self.cat_r, cls_oh=self.cls_oh,
                 wvec=self.wvec, scale=self.scale, fmin=self.fmin)
        return {QUANTIZED_JSON: json.dumps(meta, indent=2).encode(),
                QUANTIZED_NPZ: buf.getvalue()}

    @classmethod
    def from_sidecar(cls, meta_bytes: bytes,
                     npz_bytes: bytes) -> "QuantizedForest":
        meta = json.loads(meta_bytes.decode())
        with np.load(_io.BytesIO(npz_bytes)) as z:
            a = {k: z[k] for k in z.files}
        return cls(q_lo=a["q_lo"], q_hi=a["q_hi"], num_r=a["num_r"],
                   cat_m=a["cat_m"], cat_r=a["cat_r"], cls_oh=a["cls_oh"],
                   wvec=a["wvec"], scale=a["scale"], fmin=a["fmin"],
                   classes=list(meta["classes"]),
                   min_odds=float(meta["min_odds"]),
                   budget=float(meta["budget"]),
                   mismatch=float(meta["mismatch"]))

    # ---- device vote ----
    def vote_fn(self):
        """Jitted ``(qvals int8, qcodes int8) -> (n,) int32`` vote
        kernel, backend-dispatched (TPU_NOTES §24)."""
        import jax
        import jax.numpy as jnp
        from ..ops.pallas.dispatch import pallas_interpret, resolve_backend
        consts = tuple(jnp.asarray(a) for a in
                       (self.q_lo, self.q_hi, self.num_r, self.cat_m,
                        self.cat_r, self.cls_oh, self.wvec))
        mo = jnp.float32(self.min_odds)
        if resolve_backend() == "pallas":
            from ..ops.pallas.vote import quantized_vote
            interp = pallas_interpret()

            def core(qv, qc):
                return quantized_vote(qv, qc, *consts, mo,
                                      interpret=interp)
            return jax.jit(core)
        return jax.jit(lambda qv, qc: _quantized_vote_body(
            qv, qc, *consts, mo))


def quantize_ensemble(ensemble, schema=None,
                      budget: float = DEFAULT_BUDGET) -> QuantizedForest:
    """Quantize a stacked ``models.forest.EnsembleModel`` onto the int8
    grid.  Raises when the ensemble cannot take the stacked device path
    (degenerate member / fractional weights) or a categorical alphabet
    exceeds the int8 code range — those serve float, there is nothing
    meaningful to quantize."""
    host = ensemble.stacked_host()
    if host is None:
        raise ValueError(
            "cannot quantize: ensemble has no stacked device form "
            "(degenerate member, non-f32-exact bounds, or fractional "
            "vote weights) — the float host path serves it")
    lo, hi, num_r, cat_m, cat_r, cls_oh = host
    T, P, F = lo.shape
    if cat_m.shape[3] > 127:
        raise ValueError(
            f"cannot quantize: categorical alphabet {cat_m.shape[3]} "
            f"exceeds the int8 code range (127)")
    # per-feature grid over the finite threshold range, widened by the
    # schema's min/max when it pins one (request values live there)
    fmin = np.zeros((F,), np.float64)
    scale = np.ones((F,), np.float64)
    feat_fields = None
    if schema is not None:
        mats = ensemble.models[0].matrix
        feat_fields = [schema.find_field_by_ordinal(o)
                       for o in mats.feat_ordinals]
    for f in range(F):
        finite = []
        m = num_r[:, :, f] & np.isfinite(lo[:, :, f])
        finite.extend(lo[:, :, f][m].tolist())
        m = num_r[:, :, f] & np.isfinite(hi[:, :, f])
        finite.extend(hi[:, :, f][m].tolist())
        if feat_fields is not None and feat_fields[f].is_numeric:
            if feat_fields[f].min is not None:
                finite.append(float(feat_fields[f].min))
            if feat_fields[f].max is not None:
                finite.append(float(feat_fields[f].max))
        if finite:
            gmin, gmax = min(finite), max(finite)
            fmin[f] = gmin
            scale[f] = (gmax - gmin) / _LEVELS if gmax > gmin else 1.0
    def q_thresh(t):
        with np.errstate(invalid="ignore"):
            q = np.floor((t - fmin[None, None, :]) / scale[None, None, :])
        return np.clip(q, -1, _LEVELS) - 127
    q_lo = np.where(np.isneginf(lo), float(_LO_NEG_INF),
                    q_thresh(lo.astype(np.float64)))
    # pad paths carry lo=+inf (never match): +inf quantizes past the top
    # cell, so clip keeps them unreachable (q_lo=127 admits no q_v)
    q_lo = np.where(np.isposinf(lo), float(_HI_POS_INF), q_lo)
    q_hi = np.where(np.isposinf(hi), float(_HI_POS_INF),
                    q_thresh(hi.astype(np.float64)))
    q_hi = np.where(np.isneginf(hi), float(_LO_NEG_INF), q_hi)
    return QuantizedForest(
        q_lo=q_lo.astype(np.int8), q_hi=q_hi.astype(np.int8),
        num_r=num_r, cat_m=cat_m, cat_r=cat_r,
        cls_oh=cls_oh.astype(np.uint8),
        wvec=np.asarray(ensemble.weights, np.float32),
        scale=scale, fmin=fmin, classes=list(ensemble.classes),
        min_odds=float(ensemble.min_odds_ratio), budget=float(budget))


def publish_quantized(registry, name: str, version: int, models,
                      schema, sample_table, *,
                      budget: float = DEFAULT_BUDGET,
                      weights: Optional[Sequence[float]] = None,
                      min_odds_ratio: float = 1.0) -> Dict[str, float]:
    """Quantize + budget-check + attach the sidecar to a COMMITTED
    registry version.  The accuracy contract is enforced HERE, at
    publish time: the quantized vote runs against the float ensemble on
    ``sample_table`` and a mismatch fraction above ``budget`` RAISES —
    an over-budget quantized model never reaches the registry, so
    serving never has to second-guess the sidecar it loads.  Returns
    ``{"mismatch": ..., "budget": ..., "n_sample": ...}``."""
    from ..models.forest import EnsembleModel
    from ..models.tree import DecisionTreeModel, FeatureCache
    tree_models = [DecisionTreeModel(pl, schema) for pl in models]
    ens = EnsembleModel(tree_models, weights=weights,
                        min_odds_ratio=min_odds_ratio, require_odd=False)
    qf = quantize_ensemble(ens, schema, budget=budget)
    n = sample_table.n_rows
    if n == 0:
        raise ValueError("publish_quantized needs a non-empty sample "
                         "table to enforce the accuracy budget")
    float_pred = ens.predict(sample_table)
    cache = FeatureCache()
    vals, codes = cache.host(tree_models[0].matrix, sample_table)
    qv, qc = qf.quantize_rows(vals, codes)
    import jax.numpy as jnp
    idx = np.asarray(qf.vote_fn()(jnp.asarray(qv), jnp.asarray(qc)))
    lut = np.concatenate([np.asarray(qf.classes, object), [None]])
    q_pred = list(lut[idx])
    mismatch = sum(a != b for a, b in zip(float_pred, q_pred)) / n
    if mismatch > budget:
        raise ValueError(
            f"quantized forest {name!r} v{version} exceeds the pinned "
            f"accuracy budget: mismatch {mismatch:.4f} > {budget:.4f} "
            f"on {n} sample rows — sidecar NOT published")
    qf.mismatch = float(mismatch)
    registry.add_sidecar(name, version, qf.to_sidecar())
    return {"mismatch": float(mismatch), "budget": float(budget),
            "n_sample": float(n)}


# --------------------------------------------------------------------------
# the int8 wire form: client-side pre-binning (PR 16)
# --------------------------------------------------------------------------
#
# A client that holds the published grid (sidecar ``scale``/``fmin``) can
# quantize request rows ITSELF and ship the int8 form:
#
#   predictq,<rid>[,t=<us>:<0|1>],<F>,<qv_0..qv_{F-1}>,<qc_0..qc_{F-1}>
#
# where F = len(feat_ordinals) of the serving forest and every qv/qc
# token is a CANONICAL signed decimal int8: ``0`` or ``-?[1-9][0-9]{0,2}``
# in [-128, 127] — no '+', no '-0', no leading zeros, so one byte pattern
# per value and the native parser (io/serve_native.cpp) and this python
# codec can never disagree on a valid payload.  The width echo <F> lets
# the server reject a grid-shape mismatch before touching the payload.
# The layout is pinned by tests/test_golden_bytes.py (wire flow).

QUANTIZED_VERB = "predictq"

_Q_INT_RE = re.compile(r"^(?:0|-?[1-9][0-9]{0,2})$")
_WIDTH_RE = re.compile(r"^(?:0|[1-9][0-9]*)$")


def wire_encode_rows(rids: Sequence[str], qv: np.ndarray, qc: np.ndarray,
                     *, delim: str = ",") -> List[str]:
    """Encode pre-binned rows (``quantize_rows`` output) as predictq wire
    messages, one per request id — the canonical on-wire layout."""
    qv = np.asarray(qv, np.int8)
    qc = np.asarray(qc, np.int8)
    width = qv.shape[1]
    out = []
    for rid, vrow, crow in zip(rids, qv, qc):
        parts = [QUANTIZED_VERB, str(rid), str(width)]
        parts.extend(str(int(x)) for x in vrow)
        parts.extend(str(int(x)) for x in crow)
        out.append(delim.join(parts))
    return out


def wire_decode_tokens(tokens: Sequence[str], width: int
                       ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Strict decode of a predictq payload (the row fields after
    rid/trace): ``(qv, qc)`` int8 arrays, or None when the payload is
    malformed — wrong arity, width-echo mismatch, or any non-canonical
    token.  This python decoder is the semantics oracle the native
    parser defers to (it FALLS BACK rather than guess)."""
    if len(tokens) != 1 + 2 * width:
        return None
    if _WIDTH_RE.match(tokens[0]) is None or int(tokens[0]) != width:
        return None
    vals = []
    for tok in tokens[1:]:
        if _Q_INT_RE.match(tok) is None:
            return None
        v = int(tok)
        if not -128 <= v <= 127:
            return None
        vals.append(v)
    return (np.asarray(vals[:width], np.int8),
            np.asarray(vals[width:], np.int8))


def load_quantized(registry, name: str,
                   version: int) -> Optional[QuantizedForest]:
    """Read a version's quantized sidecar; ``None`` (with a warning)
    when the version carries none or the payload is torn/unreadable —
    the caller serves the float model.  Quantization is an optimization:
    a missing or torn sidecar must never refuse traffic."""
    try:
        meta_b = registry.read_sidecar(name, version, QUANTIZED_JSON)
        npz_b = registry.read_sidecar(name, version, QUANTIZED_NPZ)
        return QuantizedForest.from_sidecar(meta_b, npz_b)
    except FileNotFoundError:
        warnings.warn(
            f"ps.quantized: model {name!r} v{version} carries no "
            f"quantized sidecar; serving the float model",
            RuntimeWarning)
        return None
    except Exception as exc:
        warnings.warn(
            f"ps.quantized: quantized sidecar of {name!r} v{version} is "
            f"torn or unreadable ({type(exc).__name__}: {exc}); serving "
            f"the float model", RuntimeWarning)
        return None
