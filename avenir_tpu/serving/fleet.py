"""The traffic-shaped serving fleet: N workers draining ONE RESP queue.

The tier above :class:`~avenir_tpu.serving.service.PredictionService`
(the reference avenir's Storm topology role, shaped like TensorFlow's
serving story — many stateless workers against shared published
parameters).  Each worker owns:

  * its OWN :class:`PredictionService` (continuous or drain batching per
    the shared :class:`BatchPolicy`) with its OWN warm shape-bucket
    predictor cache built against the SHARED model registry — the
    Execution Templates discipline: staged bucket executables are reused
    across requests, never re-traced on the serving path;
  * its own :class:`~avenir_tpu.io.respq.RespClient` connection draining
    the one request queue with pipelined ``rpop_many`` (the multi-client
    stress test in tests/test_respq.py is the no-loss/no-dup proof this
    leans on) and parking on ``brpop`` when idle instead of spin-polling;
  * its own metrics identity (``<model>-w<i>``): per-worker labeled
    gauges on the registry and a per-worker ``/healthz/<name>`` target.

Fleet-level semantics:

  * **coordinated hot-swap** — a ``reload`` message seen by ANY worker
    bumps one shared generation counter; every worker notices at its
    next poll and refreshes off the registry, so the whole fleet
    converges to the newest intact version (in-flight batches finish on
    the model they started on).
  * **degraded parking** — a worker whose service was ``mark_degraded``
    (drift guardrail) stops pulling: it flushes what it already
    accepted, then parks until a hot-swap clears the flag.  Its
    ``/healthz/<name>`` answers 503 while its peers keep serving.
  * **admission control** — the bounded service queue is the admission
    point for BOTH transports: a submit past ``policy.max_queue_depth``
    resolves immediately as ``busy`` and the worker answers
    ``<id>,busy`` on the wire.  Every popped request is answered with
    SOMETHING (prediction, ``error``, or ``busy``) — no accepted
    request is ever dropped, fleet-wide.
  * **horizontal tier** (ISSUE 13) — ``redis.server.endpoints`` listing
    M broker shards makes every worker drain a
    :class:`~avenir_tpu.io.respq.ShardedRespClient` ring (a dead shard
    degrades that worker's ring with a ``Broker/BrokerShardDown``
    counter in the merged dump); ``host_label`` stamps every metric
    series and ``stats()`` so N fleets on N hosts scraped into one
    registry stay disjoint; ``scale_to``/``add_worker`` are the
    autoscaler's actuator — autoscale-parked workers keep their warm
    compiled services resident (unpark is repointing traffic, not a
    cold start), and the last worker can never be parked.  Run one
    fleet per host with ``python -m avenir_tpu.serving.fleet_host``.
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence

from ..core.metrics import Counters
from ..telemetry import reqtrace
from ..utils.tracing import StepTimer
from .predictor import DEFAULT_BUCKETS, Predictor
from .router import ModelRouter, parse_model_spec
from .service import BatchPolicy, PredictionService


class _Worker:
    """One fleet member: service + wire connection + drain thread."""

    __slots__ = ("index", "name", "service", "client", "thread",
                 "seen_gen", "pending", "parked", "down_since", "unsent")

    def __init__(self, index: int, name: str, service: PredictionService):
        self.index = index
        self.name = name
        self.service = service
        self.client = None
        self.thread: Optional[threading.Thread] = None
        self.seen_gen = 0
        # (request_id, future, trace_ctx_or_None) in submit order;
        # service batches complete in order, so FIFO head-flush is
        # completion order
        self.pending: "deque[tuple]" = deque()
        # broker-outage grace: when the WHOLE ring is unreachable the
        # drain parks and retries (down_since starts the grace clock);
        # replies whose push failed mid-outage wait in unsent rather
        # than being dropped
        self.down_since: Optional[float] = None
        self.unsent: List[str] = []
        # autoscaler parking: a parked worker stops PULLING but keeps
        # its warm service (compiled buckets resident) so unparking is
        # instant — distinct from degraded parking (health stays OK)
        self.parked = threading.Event()


class ServingFleet:
    """Run ``n_workers`` PredictionService workers against one RESP
    request queue.  Construct around a shared ``registry`` +
    ``model_name`` (hot-swap enabled) or a ``predictor_factory``
    returning a fresh per-worker :class:`Predictor` (no registry, reload
    is a no-op) — then :meth:`start`, feed the request queue, and
    :meth:`stop` (or push a literal ``stop`` message, which stops every
    worker after the requests already popped are answered)."""

    def __init__(self, registry=None, model_name: Optional[str] = None, *,
                 predictor_factory: Optional[Callable[[], Predictor]] = None,
                 schema=None,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 policy: Optional[BatchPolicy] = None,
                 n_workers: int = 2,
                 config: Optional[Dict] = None,
                 warm: bool = True,
                 delim: str = ",",
                 metrics=None,
                 latency_window: int = 8192,
                 idle_sleep_s: float = 0.002,
                 max_idle_sleep_s: float = 0.05,
                 broker_grace_s: float = 10.0,
                 quantized: bool = False,
                 host_label: Optional[str] = None,
                 wire_native: str = "auto",
                 models: Optional[Sequence] = None,
                 model_depths: Optional[Dict[str, int]] = None,
                 shared_cores: bool = True,
                 device_map: Optional[str] = None,
                 reward_sink=None):
        # multi-model residency (ISSUE 18): models= lists the resident
        # set ("name" or "name:version" specs); every worker then runs a
        # ModelRouter over N co-resident services instead of one
        # PredictionService, and predict messages carrying the optional
        # wire field m=<name[:version]> route per request.  model_name
        # (or the first spec) is the default model — requests without an
        # m= field serve it byte for byte as a single-model fleet would.
        self.models_spec = list(models) if models else None
        self._model_depths = dict(model_depths or {})
        self._shared_cores = bool(shared_cores)
        if self.models_spec:
            if registry is None:
                raise ValueError("models= needs registry=")
            if model_name is None:
                model_name = parse_model_spec(self.models_spec[0])[0]
        elif predictor_factory is None and (registry is None
                                            or model_name is None):
            raise ValueError("need registry= + model_name=, or "
                             "predictor_factory=")
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        cfg = dict(config or {})
        self.registry = registry
        self.model_name = model_name
        self.predictor_factory = predictor_factory
        self._schema = schema
        self._buckets = tuple(buckets)
        self.policy = policy or BatchPolicy()
        self.n_workers = int(n_workers)
        self._warm = warm
        self.delim = delim
        self._metrics = metrics
        self._quantized = bool(quantized)
        # ps.wire.native: every worker service shares one mode (the
        # native batch assembler is per-service state; the mode is
        # config) — fleet _ingest keeps its python parse, the codec
        # rides inside each worker's process_batch
        self._wire_native = wire_native
        # online-learning reward intake (ISSUE 19): a fleet built with a
        # reward_sink= runs online-capable workers — ``reward,<id>,<v>``
        # rows drained off the shared request queue route to the sink
        # through each worker's PredictionService instead of counting
        # as BadRequests.  One sink serves every worker: the sink (the
        # online plane's pending-outcome table) is host-side state and
        # does its own locking.  Unavailable with models= (the router
        # owns per-model parsing; an online fleet is single-model).
        if reward_sink is not None and models:
            raise ValueError("reward_sink= does not combine with models=")
        self._reward_sink = reward_sink
        # device placement map (ISSUE 20): by default every worker's
        # registry-built predictor binds the default device (chip 0 on a
        # multi-chip host).  "round_robin" spreads workers over the
        # host's chips (worker i -> device i % n, parallel.mesh.
        # worker_device); "sharded" gives every worker a tree-axis
        # mesh-sharded core over ALL chips (model-parallel: forests too
        # big for one chip's HBM) — with shared_cores the N workers
        # share ONE compiled sharded program and ONE set of resident
        # shards.  Registry-built predictors only: a predictor_factory
        # owns its own placement.
        if device_map not in (None, "round_robin", "sharded"):
            raise ValueError(
                "device_map must be None, 'round_robin' or 'sharded', "
                f"got {device_map!r}")
        if device_map is not None and predictor_factory is not None:
            raise ValueError(
                "device_map= does not combine with predictor_factory= "
                "(the factory owns placement)")
        self.device_map = device_map
        self._latency_window = int(latency_window)
        self.idle_sleep_s = float(idle_sleep_s)
        self.max_idle_sleep_s = float(max_idle_sleep_s)
        # total-ring-loss grace: a kill-and-restart drill routinely
        # leaves EVERY shard unreachable for a beat (the replacement is
        # still binding / replaying its journal), and the sharded
        # client recovers on its own once one comes back — so a drain
        # thread parks and retries for this long before treating the
        # outage as permanent and exiting
        self.broker_grace_s = float(broker_grace_s)
        self.host = cfg.get("redis.server.host", "127.0.0.1")
        self.port = int(cfg.get("redis.server.port", 6379))
        # the broker ring: with redis.server.endpoints listing M shards
        # every worker drains through a ShardedRespClient (consistent-
        # hash fan-out); single host/port keeps the plain client
        self._wire_cfg = cfg
        self.request_q = cfg.get("redis.request.queue", "requestQueue")
        self.prediction_q = cfg.get("redis.prediction.queue",
                                    "predictionQueue")
        # ps.broker.lease.timeout.s (ISSUE 17): > 0 switches the drain
        # to leased at-least-once delivery — requests are acquired
        # under a visibility-timeout LEASE and acked by the reply
        # ACKPUSH, so a worker killed mid-batch redelivers instead of
        # stranding its popped requests.  0 (default) keeps the classic
        # destructive rpop/brpop/lpush path, byte for byte.
        self.lease_timeout_s = float(
            cfg.get("redis.lease.timeout.s", 0.0) or 0.0)
        # multi-host identity: labels every worker's metric series and
        # rides stats() so N fleets scraped into one registry stay
        # disjoint (None = single-host, this process's hostname)
        import socket as _socket
        self.host_label = host_label or _socket.gethostname()
        self._reload_gen = 0
        self._stop = threading.Event()
        # set alongside _stop ONLY by a wire 'stop': gates the
        # drain-then-stop ring sweep.  A programmatic stop() means
        # "stop pulling" — it must not start draining the whole broker.
        self._wire_stop = False
        self._scale_lock = threading.Lock()
        self.workers: List[_Worker] = []

    # ---- lifecycle ----
    def _placement(self, index: int) -> Dict:
        """device=/serve_mesh= kwargs for worker ``index`` under the
        fleet's device_map (empty dict = the old default placement)."""
        if self.device_map == "round_robin":
            from ..parallel.mesh import worker_device
            return {"device": worker_device(index)}
        if self.device_map == "sharded":
            return {"serve_mesh": True}
        return {}

    def _make_service(self, wname: str, index: int = 0):
        placement = self._placement(index)
        if self.models_spec:
            # one router per worker: N resident models, each with its
            # own warm predictor cache, sharing compiled executables
            # where the ProgramCache axes agree (shared_cores)
            return ModelRouter(self.registry, self.models_spec,
                               default_model=self.model_name,
                               policy=self.policy,
                               model_depths=self._model_depths,
                               buckets=self._buckets,
                               counters=Counters(),
                               warm=self._warm, delim=self.delim,
                               name=wname,
                               host_label=self.host_label,
                               metrics=self._metrics,
                               latency_window=self._latency_window,
                               quantized=self._quantized,
                               wire_native=self._wire_native,
                               shared_cores=self._shared_cores,
                               **placement)
        common = dict(policy=self.policy, warm=self._warm,
                      delim=self.delim, name=wname,
                      host_label=self.host_label,
                      model_label=self.model_name,
                      counters=Counters(),
                      timer=StepTimer(keep_samples=self._latency_window),
                      metrics=self._metrics,
                      wire_native=self._wire_native,
                      reward_sink=self._reward_sink)
        if self.predictor_factory is not None:
            return PredictionService(self.predictor_factory(), **common)
        if self.device_map == "sharded":
            # N workers over ONE tree-sharded model: the sharded vote
            # program is identical across workers (weights are runtime
            # args), so share the compiled executable instead of
            # compiling it once per worker
            common["shared_cores"] = True
        return PredictionService(registry=self.registry,
                                 model_name=self.model_name,
                                 schema=self._schema,
                                 buckets=self._buckets,
                                 quantized=self._quantized,
                                 **placement, **common)

    def _make_client(self, counters=None):
        from ..io.respq import make_queue_client
        cfg = dict(self._wire_cfg)
        cfg.setdefault("redis.server.host", self.host)
        cfg.setdefault("redis.server.port", self.port)
        # the worker's counters ride into the sharded client so a dead
        # broker shard lands as Broker/BrokerShardDown in the fleet's
        # merged dump
        return make_queue_client(cfg, delim=self.delim, counters=counters)

    def start(self) -> "ServingFleet":
        if self.workers:
            return self
        self._stop.clear()
        base = self.model_name or "fleet"
        for i in range(self.n_workers):
            wname = f"{base}-w{i}"
            w = _Worker(i, wname, self._make_service(wname, i))
            w.service.start()
            w.client = self._make_client(w.service.counters)
            self.workers.append(w)
        # connect everything before pulling: a worker that starts draining
        # while a peer is still warming would skew the first measurements
        for w in self.workers:
            w.thread = threading.Thread(target=self._drain, args=(w,),
                                        daemon=True,
                                        name=f"avenir-fleet-{w.name}")
            w.thread.start()
        return self

    # ---- the autoscaler's actuator surface ----
    def _add_worker_locked(self) -> "_Worker":
        i = len(self.workers)
        wname = f"{self.model_name or 'fleet'}-w{i}"
        w = _Worker(i, wname, self._make_service(wname, i))
        w.service.start()
        w.client = self._make_client(w.service.counters)
        self.workers.append(w)
        w.thread = threading.Thread(target=self._drain, args=(w,),
                                    daemon=True,
                                    name=f"avenir-fleet-{w.name}")
        w.thread.start()
        return w

    def add_worker(self) -> "_Worker":
        """Grow the fleet by one live worker mid-run (warm-started: the
        service compiles its buckets before the drain thread pulls)."""
        with self._scale_lock:
            return self._add_worker_locked()

    def active_workers(self) -> int:
        return sum(1 for w in self.workers if not w.parked.is_set())

    def scale_to(self, n: int) -> int:
        """Set the ACTIVE (pulling) worker count — the autoscaler's
        actuator.  Scale-up unparks before it adds: a parked worker
        keeps its warm per-worker predictor cache (service thread +
        compiled bucket executables stay resident), so re-admitting it
        is repointing traffic, not a cold start — the Execution
        Templates control-plane/data-plane split applied to serving.
        Scale-down parks the tail workers (they flush everything
        already accepted first — parking never drops a request).  Never
        parks the last worker.  Returns the new active count."""
        n = max(1, int(n))
        with self._scale_lock:
            if self.workers:
                while len(self.workers) < n:
                    self._add_worker_locked()
            for i, w in enumerate(self.workers):
                if i < n:
                    w.parked.clear()
                else:
                    w.parked.set()
            return self.active_workers()

    def request_reload(self) -> None:
        """Coordinated hot-swap: every worker refreshes from the shared
        registry at its next poll (the caller may be any worker's drain
        thread, or operator code)."""
        self._reload_gen += 1

    # ---- guardrail-action + controller surface ----
    # The monitor's refresh_action/degrade_action (and the retrain
    # controller's fleet link) duck-type against a PredictionService;
    # these three methods give the fleet the same verbs so a policy wired
    # at fleet scope converges ALL workers instead of touching one.
    def refresh(self) -> bool:
        """Fleet-addressed refresh: bump the generation counter so every
        worker (parked ones included — the generation check precedes the
        park check in the drain loop) re-resolves the registry's serving
        version at its next poll.  Returns whether a swap is actually
        due (some worker is off the registry's serving version) — the
        same will-it-swap meaning `PredictionService.refresh` returns,
        so a counter like `DriftMonitor/RefreshSwaps` is not inflated by
        alerts that had nothing to swap to.  The swap itself is
        asynchronous per worker; :meth:`converged_version` is the ack."""
        self.request_reload()
        if self.registry is None or self.model_name is None:
            return False
        target = self.registry.serving_version(self.model_name)
        return target is not None and \
            any(w.service.version != target for w in self.workers)

    def mark_degraded(self, reason: str) -> None:
        """Flag EVERY worker's service degraded (drift-policy guardrail at
        fleet scope).  The PR 12 parking rules then apply per worker: a
        degraded worker parks only while a healthy unparked peer keeps
        pulling, and the last active worker keeps serving flagged — a
        fleet-wide degrade never stops the fleet answering."""
        for w in self.workers:
            w.service.mark_degraded(reason)

    def converged_version(self) -> Optional[int]:
        """The single model version every worker is serving, or None
        while workers disagree (mid-swap) — the controller's swap-ack:
        poll until this equals the version it published/pinned."""
        versions = {w.service.version for w in self.workers}
        if len(versions) == 1:
            return versions.pop()
        return None

    # ---- multi-model deployment surface (ISSUE 18) ----
    # Present only on a models= fleet (workers are ModelRouters); the
    # retrain controller's canary_validate stage and operator tooling
    # address deployment policies at fleet scope so every worker's
    # router applies the same split.
    def _routers(self) -> List[ModelRouter]:
        return [w.service for w in self.workers
                if isinstance(w.service, ModelRouter)]

    def install_canary(self, mname: str, version: Optional[int] = None,
                       percent: int = 10, **kw) -> None:
        """Canary ``mname`` on EVERY worker: the split is deterministic
        on the request id, so N workers each applying it locally is one
        fleet-wide x% split — no coordination traffic."""
        routers = self._routers()
        if not routers:
            raise ValueError("install_canary needs a models= fleet")
        for r in routers:
            r.install_canary(mname, version=version, percent=percent,
                             **kw)

    def clear_canary(self, mname: str):
        out = None
        for r in self._routers():
            got = r.clear_canary(mname)
            out = out or got
        return out

    def install_shadow(self, mname: str, version: Optional[int] = None,
                       **kw) -> None:
        routers = self._routers()
        if not routers:
            raise ValueError("install_shadow needs a models= fleet")
        for r in routers:
            r.install_shadow(mname, version=version, **kw)

    def clear_shadow(self, mname: str) -> None:
        for r in self._routers():
            r.clear_shadow(mname)

    def record_canary_outcome(self, mname: str, rid, predicted: str,
                              actual: str):
        """Outcome labels land on ONE router's trackers (the first
        worker's) — the arm attribution is re-derived from the id, so
        any router gives the same answer; one series, not N copies."""
        routers = self._routers()
        if not routers:
            return None
        return routers[0].record_canary_outcome(mname, rid, predicted,
                                                actual)

    def canary_state(self, mname: str):
        routers = self._routers()
        return routers[0].canary_state(mname) if routers else None

    def model_queue_depths(self) -> Dict[str, int]:
        """model name -> queued depth summed across workers — the
        autoscaler's per-tenant pressure sensor (empty for a
        single-model fleet)."""
        out: Dict[str, int] = {}
        for r in self._routers():
            for mname, d in r.model_queue_depths().items():
                out[mname] = out.get(mname, 0) + d
        return out

    def wait(self, timeout_s: float = 60.0) -> bool:
        """Block until every drain thread exited (a wire ``stop`` message
        or :meth:`stop` ended the fleet); True when all did."""
        deadline = time.monotonic() + timeout_s
        ok = True
        for w in self.workers:
            if w.thread is not None:
                w.thread.join(timeout=max(0.0, deadline - time.monotonic()))
                ok = ok and not w.thread.is_alive()
        return ok

    def stop(self, drain_s: float = 5.0) -> None:
        """Stop pulling, answer everything already accepted (pending wire
        replies flushed, then each service's queued requests served in
        ``max_batch`` chunks), tear down connections.  Workers stay
        listed for post-run ``stats()``/``merged_counters()`` reads; a
        stopped fleet is not restartable."""
        self._stop.set()
        self.wait(timeout_s=max(drain_s, 0.1) + 30.0)
        for w in self.workers:
            w.service.stop(drain_s=drain_s)
            if w.client is not None:
                try:
                    w.client.close()
                except OSError:
                    pass

    # ---- observability ----
    def stats(self) -> Dict:
        """Aggregate + per-worker snapshot: total served/rejected/errors,
        per-worker model versions (converged after a coordinated
        hot-swap), queue depths, degraded flags."""
        per = {w.name: w.service.stats() for w in self.workers}
        per_model: Dict[str, Dict] = {}
        for s in per.values():
            # multi-model workers (ModelRouter) expose a per_model
            # breakdown; fold the per-tenant numbers across workers
            for mname, ms in (s.get("per_model") or {}).items():
                agg = per_model.setdefault(
                    mname, {"queue_depth": 0, "requests": 0,
                            "rejected": 0, "model_version": None})
                agg["queue_depth"] += ms["queue_depth"]
                agg["requests"] += ms["requests"]
                agg["rejected"] += ms["rejected"]
                agg["model_version"] = ms["model_version"]
        return {
            "host": self.host_label,
            "per_model": per_model,
            "workers": len(self.workers),
            "active_workers": self.active_workers(),
            "parked": {w.name: w.parked.is_set() for w in self.workers},
            "reload_generation": self._reload_gen,
            "served": sum(s["served"] for s in per.values()),
            "rejected": sum(s["rejected"] for s in per.values()),
            "errors": sum(s["errors"] for s in per.values()),
            "queue_depth": sum(s["queue_depth"] for s in per.values()),
            "model_versions": {n: s["model_version"]
                               for n, s in per.items()},
            "per_worker": per,
        }

    def merged_counters(self) -> Counters:
        """One Counters summing every worker's Serving group (the job
        dump view; per-worker splits stay on the metrics registry)."""
        out = Counters()
        for w in self.workers:
            for grp, names in w.service.counters.as_dict().items():
                for n, v in names.items():
                    if n.startswith("Max"):
                        # high-water marks (MaxBatchObserved) merge by
                        # max — summing two workers' 16s would report a
                        # 32-row batch nothing ever served
                        out.max(grp, n, v)
                    else:
                        out.increment(grp, n, v)
        out.set("Serving", "Workers", len(self.workers)
                or self.n_workers)
        return out

    def merged_timer(self) -> StepTimer:
        """One StepTimer holding every worker's latency samples (fleet
        percentiles; per-worker percentiles stay on each service).
        Sized by the LIVE worker count, not the constructed one — an
        autoscaled fleet that grew past n_workers must not evict the
        early workers' samples from the merged window."""
        merged = StepTimer(keep_samples=self._latency_window
                           * max(1, len(self.workers) or self.n_workers))
        for w in self.workers:
            for name, dq in list(w.service.timer.samples.items()):
                # the worker's predict thread appends concurrently; a
                # live-stats caller must not crash on a mutating deque
                for _ in range(3):
                    try:
                        samples = list(dq)
                        break
                    except RuntimeError:
                        continue
                else:
                    samples = []
                for s in samples:
                    merged.record(name, s)
        return merged

    # ---- the drain loop (one thread per worker) ----
    def _drain(self, w: _Worker) -> None:
        svc = w.service
        sleep_s = self.idle_sleep_s
        try:
            while not self._stop.is_set():
                if w.seen_gen != self._reload_gen:
                    w.seen_gen = self._reload_gen
                    try:
                        svc.refresh()
                    except Exception as exc:
                        warnings.warn(
                            f"fleet {w.name}: hot-swap refresh failed "
                            f"({type(exc).__name__}: {exc}); serving "
                            f"stays on version {svc.version}",
                            RuntimeWarning)
                if w.parked.is_set() and \
                        any(not p.parked.is_set() for p in self.workers
                            if p is not w):
                    # autoscaler parking: stop pulling, answer what was
                    # already accepted, keep the warm service resident
                    # for the unpark.  Like degraded parking, never the
                    # last worker (scale_to can't park it, but guard
                    # against racing list mutation anyway).
                    self._flush(w, wait=True)
                    svc.counters.increment("Serving", "ParkedPolls")
                    time.sleep(self.max_idle_sleep_s)
                    continue
                if svc.degraded is not None and \
                        any(p.service.degraded is None
                            and not p.parked.is_set()
                            for p in self.workers if p is not w):
                    # a degraded worker stops pulling WHILE a healthy
                    # UNPARKED peer keeps draining: answer what it
                    # already accepted, then park (a hot-swap clears the
                    # flag via refresh above).  When every other worker
                    # is degraded OR autoscale-parked the last active
                    # one keeps serving (flagged, /healthz 503) —
                    # otherwise a scaled-down fleet whose sole active
                    # worker degrades would have NOBODY pulling (parked
                    # peers wait for an active one, the degraded one
                    # waits for a healthy peer) and the queue would
                    # wedge unanswered, unreachable even by the wire
                    # 'reload' recovery path.
                    self._flush(w, wait=True)
                    svc.counters.increment("Serving", "ParkedPolls")
                    time.sleep(self.max_idle_sleep_s)
                    continue
                try:
                    if self.lease_timeout_s > 0:
                        msgs = w.client.lease_many(self.request_q,
                                                   svc.policy.max_batch,
                                                   self.lease_timeout_s)
                    else:
                        msgs = w.client.rpop_many(self.request_q,
                                                  svc.policy.max_batch)
                except (ConnectionError, OSError, RuntimeError) as exc:
                    # a sharded client degrades around ONE dead shard on
                    # its own; reaching here means the whole broker tier
                    # is unreachable RIGHT NOW — park and retry within
                    # the grace window (a restarting shard rejoins the
                    # ring on a later verb), exit only when it stays gone
                    if self._broker_gone(w, exc):
                        break
                    continue
                w.down_since = None
                svc.counters.increment("Serving", "Polls")
                if msgs:
                    sleep_s = self.idle_sleep_s
                    self._ingest(w, msgs)
                else:
                    svc.counters.increment("Serving", "EmptyPolls")
                    try:
                        self._flush(w, wait=False)
                    except (ConnectionError, OSError,
                            RuntimeError) as exc:
                        if self._broker_gone(w, exc):
                            break
                        continue
                    # park on the server instead of spin-polling; keep
                    # the park short while replies are still pending so
                    # a batch finishing mid-park is flushed promptly
                    park = 0.001 if w.pending else sleep_s
                    try:
                        if self.lease_timeout_s > 0:
                            got = w.client.lease_many(
                                self.request_q, 1, self.lease_timeout_s,
                                block_s=park)
                            v = got[0] if got else None
                        else:
                            v = w.client.brpop(self.request_q,
                                               timeout_s=park)
                    except (ConnectionError, OSError,
                            RuntimeError) as exc:
                        if self._broker_gone(w, exc):
                            break
                        continue
                    w.down_since = None
                    if v is not None:
                        sleep_s = self.idle_sleep_s
                        self._ingest(w, [v])
                    elif not w.pending:
                        sleep_s = min(sleep_s * 2.0, self.max_idle_sleep_s)
                try:
                    self._flush(w, wait=False)
                except (ConnectionError, OSError, RuntimeError) as exc:
                    if self._broker_gone(w, exc):
                        break
            # drain-then-stop: the single-queue FIFO invariant
            # ("everything queued before the stop was already popped")
            # does NOT hold across a shard ring — the stop lands on ONE
            # shard while tail requests sit on others.  Sweep the ring
            # empty before exiting so a WIRE stop never strands
            # accepted traffic (a surplus stop swept up here is
            # re-pushed for its own fleet by _ingest; a programmatic
            # stop() does not sweep — it means "stop pulling").
            if self._wire_stop:
                try:
                    while True:
                        msgs = w.client.rpop_many(self.request_q,
                                                  svc.policy.max_batch)
                        if not msgs:
                            break
                        # requests get answered; surplus stops are
                        # re-pushed for their own fleets by _ingest
                        self._ingest(w, msgs)
                        self._flush(w, wait=False)
                        if all(m == "stop" for m in msgs):
                            break   # only (re-pushed) stops remain —
                            # don't ping-pong with our own re-push
                except (ConnectionError, OSError, RuntimeError) as exc:
                    warnings.warn(
                        f"fleet {w.name}: stop-drain sweep cut short "
                        f"({type(exc).__name__}: {exc})", RuntimeWarning)
        finally:
            # answer everything this worker accepted before it exits —
            # the no-drop guarantee holds through 'stop' and crashes
            try:
                self._flush(w, wait=True)
            except Exception as exc:
                warnings.warn(f"fleet {w.name}: final flush failed "
                              f"({type(exc).__name__}: {exc})",
                              RuntimeWarning)

    def _broker_gone(self, w: _Worker, exc: BaseException) -> bool:
        """Total-ring-loss triage for a drain thread: every broker shard
        is unreachable at this instant.  A kill-and-restart drill passes
        through this state routinely (the replacement shard needs a beat
        to bind and replay its journal) and the sharded client CAN
        recover — its rejoin probe folds a revived shard back into the
        ring on a later verb — so park briefly and retry; only a ring
        that stays empty past ``broker_grace_s`` is a real outage, and
        then the worker exits (answering what it already accepted).
        Returns True when the worker should exit."""
        now = time.monotonic()
        if w.down_since is None:
            w.down_since = now
            warnings.warn(
                f"fleet {w.name}: broker tier unreachable "
                f"({type(exc).__name__}: {exc}); parking to retry for "
                f"up to {self.broker_grace_s:.0f}s", RuntimeWarning)
        w.service.counters.increment("Serving", "BrokerRetries")
        if now - w.down_since >= self.broker_grace_s:
            warnings.warn(
                f"fleet {w.name}: broker unreachable for "
                f"{now - w.down_since:.1f}s ({type(exc).__name__}: "
                f"{exc}); worker exiting", RuntimeWarning)
            return True
        if self._stop.is_set():
            return True   # stopping anyway — don't sit out the grace
        time.sleep(0.05)
        return False

    def _ingest(self, w: _Worker, msgs: List[str]) -> None:
        svc = w.service
        for m in msgs:
            if m == "stop":
                # fleet-wide: peers see the event at their next poll.
                # Everything queued BEFORE the stop was already popped
                # (FIFO) by someone and will be answered.
                if self._stop.is_set():
                    # a SECOND stop drained by this fleet was aimed at
                    # another fleet process (multi-host topologies push
                    # one per host): put it back instead of eating it
                    try:
                        w.client.lpush(self.request_q, "stop")
                    except Exception:
                        pass
                else:
                    self._wire_stop = True
                    self._stop.set()
                continue
            parts = m.split(svc.delim)
            if parts[0] == "reload":
                # 'reload' (unaddressed) swaps THIS fleet;
                # 'reload,<host_label>' is multi-host convergence: one
                # addressed copy per host (ShardedRespClient.broadcast
                # alone cannot converge N hosts — one host's workers,
                # parked across every shard, can pop all the copies).
                # A copy addressed to a peer host is re-pushed for it.
                if len(parts) > 1 and parts[1] \
                        and parts[1] != self.host_label:
                    try:
                        w.client.lpush(self.request_q, m)
                    except Exception:
                        pass
                else:
                    self.request_reload()
            elif parts[0] == "predict" and len(parts) >= 3:
                # admission happens inside submit(): past the depth
                # threshold the future comes back already resolved
                # 'busy' and the flush answers <id>,busy.  A sampled
                # request (optional wire trace field, ISSUE 15) gets its
                # worker-pop flow step here and rides its context into
                # the service batch.  The optional m=<model[:version]>
                # field (ISSUE 18) routes a multi-model worker; a
                # single-model service serves its one model for any tag.
                rid, row, ctx, deadline_us, model_tag = \
                    reqtrace.split_predict_route(parts)
                if ctx is not None:
                    ctx.t_pop_us = reqtrace.now_us()
                    mspec = ""
                    if model_tag:
                        mspec = model_tag[0] + (
                            f":{model_tag[1]}"
                            if model_tag[1] is not None else "")
                    reqtrace.emit_flow("t", rid, "pop",
                                       ts_us=ctx.t_pop_us,
                                       worker=w.name,
                                       host=self.host_label,
                                       model=mspec)
                if deadline_us is not None \
                        and reqtrace.now_us() > deadline_us:
                    # deadline-aware admission (ISSUE 17): past-deadline
                    # requests — fresh, replayed, or redelivered —
                    # answer late BEFORE a device dispatch, so a
                    # replayed backlog can't brown out fresh traffic
                    svc.counters.increment("Broker", "LateShed")
                    fut: "Future[str]" = Future()
                    fut.set_result(svc.late_label)
                    w.pending.append((rid, fut, ctx))
                    continue
                if hasattr(svc, "submit_routed"):
                    fut = svc.submit_routed(row, rid=rid,
                                            model_tag=model_tag,
                                            trace=ctx,
                                            sample_local=False)
                else:
                    fut = svc.submit(row, trace=ctx, sample_local=False)
                w.pending.append((rid, fut, ctx))
            else:
                svc.counters.increment("Serving", "BadRequests")
                warnings.warn(f"fleet {w.name}: dropping malformed "
                              f"message {m!r}", RuntimeWarning)

    def _flush(self, w: _Worker, wait: bool,
               timeout_s: float = 120.0) -> None:
        """Answer completed futures onto the prediction queue, in FIFO
        order, as ONE pipelined variadic LPUSH per flush (a whole served
        batch costs one wire round trip, not one per reply).  ``wait=True``
        blocks until every pending future resolved (shutdown / parking);
        ``wait=False`` only flushes the done head."""
        svc = w.service
        # replies whose push failed during a broker outage were parked
        # in w.unsent — re-offer them ahead of the newly completed head
        # (they are older, so FIFO order is preserved)
        replies: List[str] = w.unsent
        w.unsent = []
        traced = None
        while w.pending:
            rid, fut, ctx = w.pending[0]
            if not fut.done() and not wait:
                break
            try:
                label = fut.result(timeout=timeout_s)
            except Exception:
                # per-request isolation already counted it; the waiter
                # still gets a reply line
                label = svc.error_label
            replies.append(f"{rid}{svc.delim}{label}")
            if ctx is not None:
                if traced is None:
                    traced = []
                traced.append(ctx)
            w.pending.popleft()
        if replies:
            try:
                if self.lease_timeout_s > 0:
                    # the ack piggybacks on the reply push (ONE trip):
                    # every answered request's lease is released, and a
                    # duplicate answer (redelivery race) is dropped
                    # broker-side
                    w.client.ackpush(self.prediction_q, self.request_q,
                                     replies)
                else:
                    w.client.lpush_many(self.prediction_q, replies)
            except (ConnectionError, OSError, RuntimeError):
                # broker tier momentarily gone: an ANSWERED request is
                # never dropped — buffer the replies on the worker and
                # let the drain loop's grace retry re-offer them once a
                # shard rejoins the ring
                w.unsent = replies
                raise
            if traced:
                # the replies are actually on the wire now: stamp the
                # reply-push time and close each sampled request's flow
                # (+ component histograms/exemplars) at its service
                t = reqtrace.now_us()
                for ctx in traced:
                    ctx.t_reply_us = t
                    svc.record_request_trace(ctx)
