"""Model registry: versioned model artifacts with atomic hot-swap publish.

Every servable model kind saves through ONE artifact format so the serving
layer never special-cases training code:

    <base_dir>/<name>/v_000001/meta.json     # kind, class labels, dtypes,
                                             # params, schema, JSON payload
    <base_dir>/<name>/v_000001/arrays.npz    # numeric payload (pinned dtypes)

Publish is crash-safe the same way core/checkpoint.py steps are: the version
directory is fully written as ``v_NNNNNN.tmp`` and renamed into place, so a
reader either sees the previous latest or the complete new version — never a
half-written one.  ``latest_version`` additionally probes intactness (a torn
directory left by a crash mid-publish, or a copy-in from a dying node, is
skipped with a warning instead of being served).

The artifact JSON pins the contract the round-trip tests enforce:
``class_values`` (label order — prediction indices are meaningless without
it) and ``dtypes`` (per-array dtype strings — a silently float64->float32
narrowed weight vector would shift decision boundaries).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
import warnings
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.artifacts import ArtifactStore, write_json
from ..core.faults import fault_point, with_retry
from ..core.schema import FeatureSchema
from ..telemetry import instant

FOREST = "forest"
BAYES = "bayes"
LOGISTIC = "logistic"
MLP = "mlp"
KINDS = (FOREST, BAYES, LOGISTIC, MLP)

META_FILE = "meta.json"
ARRAYS_FILE = "arrays.npz"
# O(delta) distribution sidecars (ISSUE 20): a forest version published
# via publish_delta carries the changed-tree slices + the parent
# version's per-tree sha chain, so a serving tier resident on the parent
# patches only what changed instead of re-uploading the whole model
DELTA_JSON = "delta.json"
DELTA_NPZ = "delta.npz"
DELTA_FORMAT_VERSION = 1
# serving pin: <base>/<name>/serving.json selects the version the serving
# tier resolves (rollback surface); absent = newest intact, the historic
# behavior.  Written tmp-then-rename like every other registry artifact.
PIN_FILE = "serving.json"
FORMAT_VERSION = 1

_VERSION_RE = re.compile(r"^v_(\d{6})$")
# abandoned publish/pin tmps a dead process left behind (the trailing
# group is the pid retire()'s sweep liveness-checks); younger tmps are
# never swept — a remote host's live publisher looks pid-dead locally
_TMP_RE = re.compile(r"^(?:v_\d{6}|" + re.escape(PIN_FILE)
                     + r")\.tmp\.(\d+)$")
_TMP_GRACE_S = float(os.environ.get("AVENIR_TPU_REGISTRY_TMP_GRACE_S",
                                    "3600"))


@dataclass
class LoadedModel:
    """What :meth:`ModelRegistry.load` returns: the reconstructed model
    object plus everything needed to build a serving Predictor around it."""
    name: str
    version: int
    kind: str
    model: Any                       # kind-specific (see _decode)
    meta: Dict[str, Any]
    schema: Optional[FeatureSchema]  # from the artifact, when saved with one
    base_dir: Optional[str] = None   # registry root this was loaded from
    # (sidecar access for the serving layer, e.g. the quantized forest)

    @property
    def params(self) -> Dict[str, Any]:
        return self.meta.get("params", {})

    @property
    def class_values(self) -> List[str]:
        return list(self.meta.get("class_values") or [])


# --------------------------------------------------------------------------
# kind-specific encode/decode
# --------------------------------------------------------------------------

def _tree_shas(trees_json: List[Any]) -> List[str]:
    """Per-tree content shas over the canonical (sorted-key, no-space)
    JSON form — THE identity the delta chain is keyed on: a delta's
    recorded parent shas must match the resident model's tree-for-tree
    before any patch applies (never wrong weights)."""
    import hashlib
    return [hashlib.sha256(
        json.dumps(t, sort_keys=True,
                   separators=(",", ":")).encode()).hexdigest()
        for t in trees_json]


def _pad_stacked_to(c_host, p_host):
    """Re-pad a child forest's stacked host tensors into the parent's
    ``(P, cmax)`` layout so delta slices align with a parent-layout
    resident.  Raises when the child cannot fit — a changed tree with
    more paths (or wider categorical sets) than the parent layout holds
    has no O(delta) form; refresh full-loads instead."""
    lo, hi, num_r, cat_m, cat_r, cls_oh = c_host
    T, Pc, F = lo.shape
    cmax_c, Kc = cat_m.shape[3], cls_oh.shape[2]
    P, Fp = p_host[0].shape[1], p_host[0].shape[2]
    cmax, K = p_host[3].shape[3], p_host[5].shape[2]
    if F != Fp or Kc != K:
        raise ValueError("feature/class axis changed; patch slices "
                         "would not align")
    if Pc > P or cmax_c > cmax:
        raise ValueError(
            f"child outgrows the parent stacked layout "
            f"(P {Pc}>{P} or cmax {cmax_c}>{cmax}); no O(delta) form")
    # identical fill pattern to EnsembleModel.stacked_host's pad rows:
    # never-match bounds, unrestricted categoricals, vote-nothing one-hot
    nlo = np.full((T, P, F), np.inf, np.float32)
    nhi = np.full((T, P, F), -np.inf, np.float32)
    nnum = np.ones((T, P, F), dtype=bool)
    ncm = np.zeros((T, P, F, cmax), dtype=bool)
    ncr = np.zeros((T, P, F), dtype=bool)
    ncls = np.zeros((T, P, K), np.float32)
    nlo[:, :Pc], nhi[:, :Pc], nnum[:, :Pc] = lo, hi, num_r
    ncm[:, :Pc, :, :cmax_c] = cat_m
    ncr[:, :Pc], ncls[:, :Pc] = cat_r, cls_oh
    return nlo, nhi, nnum, ncm, ncr, ncls


def _detect_kind(model: Any) -> str:
    from ..models.bayes import NaiveBayesModel
    from ..models.tree import DecisionPathList
    if isinstance(model, NaiveBayesModel):
        return BAYES
    if isinstance(model, DecisionPathList):
        return FOREST
    if isinstance(model, (list, tuple)) and model and \
            all(isinstance(m, DecisionPathList) for m in model):
        return FOREST
    if isinstance(model, np.ndarray) and model.ndim == 1:
        return LOGISTIC
    if isinstance(model, dict) and {"W1", "b1", "W2", "b2"} <= set(model):
        return MLP
    raise TypeError(f"cannot infer model kind for {type(model).__name__}; "
                    f"pass kind= explicitly (one of {KINDS})")


def _encode(model: Any, kind: str, schema: Optional[FeatureSchema]
            ) -> Tuple[Dict[str, np.ndarray], Optional[Any],
                       Optional[List[str]]]:
    """-> (arrays, model_json, class_values)."""
    if kind == FOREST:
        from ..models.tree import DecisionPathList
        trees = [model] if isinstance(model, DecisionPathList) else list(model)
        model_json = {"trees": [json.loads(t.to_json()) for t in trees]}
        cls = list(schema.class_attr_field.cardinality or []) if schema \
            else None
        return {}, model_json, cls
    if kind == BAYES:
        arrays = {
            "post_counts": np.asarray(model.post_counts),
            "class_counts": np.asarray(model.class_counts),
            "prior_counts": np.asarray(model.prior_counts),
            "cont_post_mean": np.asarray(model.cont_post_mean),
            "cont_post_std": np.asarray(model.cont_post_std),
            "cont_prior_mean": np.asarray(model.cont_prior_mean),
            "cont_prior_std": np.asarray(model.cont_prior_std),
            "binned_ordinals": np.asarray(model.binned_ordinals, np.int64),
            "cont_ordinals": np.asarray(model.cont_ordinals, np.int64),
            "num_bins": np.asarray(model.num_bins, np.int64),
        }
        model_json = {"total": float(model.total)}
        return arrays, model_json, list(model.class_values)
    if kind == LOGISTIC:
        w = np.asarray(model)
        if w.ndim != 1:
            raise ValueError(f"logistic weights must be 1-D, got {w.shape}")
        cls = list(schema.class_attr_field.cardinality or []) if schema \
            else None
        return {"w": w}, None, cls
    if kind == MLP:
        arrays = {k: np.asarray(v) for k, v in model.items()}
        cls = list(schema.class_attr_field.cardinality or []) if schema \
            else None
        return arrays, None, cls
    raise ValueError(f"unknown model kind {kind!r}; known: {KINDS}")


def _decode(kind: str, arrays: Dict[str, np.ndarray], meta: Dict[str, Any],
            schema: Optional[FeatureSchema]) -> Any:
    if kind == FOREST:
        from ..models.tree import DecisionPathList
        return [DecisionPathList.from_json(json.dumps(t))
                for t in meta["model_json"]["trees"]]
    if kind == BAYES:
        from ..models.bayes import NaiveBayesModel
        if schema is None:
            raise ValueError("bayes artifact needs a schema (save one into "
                             "the artifact or pass schema= to load)")
        return NaiveBayesModel(
            schema=schema,
            class_values=list(meta.get("class_values") or []),
            binned_ordinals=[int(o) for o in arrays["binned_ordinals"]],
            cont_ordinals=[int(o) for o in arrays["cont_ordinals"]],
            num_bins=[int(b) for b in arrays["num_bins"]],
            post_counts=arrays["post_counts"],
            class_counts=arrays["class_counts"],
            prior_counts=arrays["prior_counts"],
            total=float(meta["model_json"]["total"]),
            cont_post_mean=arrays["cont_post_mean"],
            cont_post_std=arrays["cont_post_std"],
            cont_prior_mean=arrays["cont_prior_mean"],
            cont_prior_std=arrays["cont_prior_std"])
    if kind == LOGISTIC:
        return arrays["w"]
    if kind == MLP:
        return {k: v for k, v in arrays.items()}
    raise ValueError(f"unknown model kind {kind!r}; known: {KINDS}")


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

class ModelRegistry:
    """Versioned model store over an ArtifactStore base directory."""

    def __init__(self, base_dir: str):
        self.store = ArtifactStore(base_dir)
        self.base_dir = self.store.base_dir

    # ---- layout ----
    def version_dir(self, name: str, version: int) -> str:
        return self.store.path(name, f"v_{version:06d}")

    def versions(self, name: str) -> List[int]:
        """All committed (renamed-into-place) version numbers, ascending.
        ``.tmp`` publishes in flight (or abandoned) are not versions."""
        d = self.store.path(name)
        if not os.path.isdir(d):
            return []
        out = []
        for entry in os.listdir(d):
            m = _VERSION_RE.match(entry)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def is_intact(self, name: str, version: int) -> bool:
        """True when the version's meta.json parses, declares a known kind,
        and every file in its manifest probes intact (npz zip directory
        opens, json parses, anything else exists non-empty — a torn copy
        fails here, same probe as core/checkpoint.is_intact).  The
        manifest (``meta["files"]``) covers optional sidecars generically
        (e.g. the monitor baseline pair); artifacts published before the
        manifest existed fall back to the arrays.npz probe."""
        d = self.version_dir(name, version)
        try:
            with open(os.path.join(d, META_FILE)) as fh:
                meta = json.load(fh)
            if meta.get("kind") not in KINDS:
                return False
            for fname in meta.get("files") or [ARRAYS_FILE]:
                path = os.path.join(d, fname)
                if fname.endswith(".npz"):
                    with np.load(path) as z:
                        z.files
                elif fname.endswith(".json"):
                    with open(path) as fh:
                        json.load(fh)
                elif not (os.path.isfile(path)
                          and os.path.getsize(path) > 0):
                    return False
            return True
        except Exception:
            return False

    def latest_version(self, name: str) -> Optional[int]:
        """Newest INTACT version — a torn newest directory is skipped with
        a warning so hot-swap reload never serves a half-written model."""
        for v in reversed(self.versions(name)):
            if self.is_intact(name, v):
                return v
            warnings.warn(
                f"model {name!r} version {v} in {self.base_dir!r} is torn "
                f"or unreadable; skipping it for serving", RuntimeWarning)
        return None

    # ---- serving pin (the rollback surface) ----
    def _pin_path(self, name: str) -> str:
        return self.store.path(name, PIN_FILE)

    def pin_version(self, name: str, version: int) -> None:
        """Pin the version the serving tier resolves (tmp-then-rename, so
        readers see the old pin or the new one, never a torn file).  The
        retrain controller uses this for BOTH directions: forward swap
        (clears any stale rollback pin that would mask the new candidate)
        and rollback (repoint the fleet at the prior version).  Refuses a
        version that is not committed+intact — pinning a torn version
        would wedge every later hot-swap refresh."""
        if not self.is_intact(name, version):
            raise ValueError(
                f"refusing to pin model {name!r} version {version}: not a "
                f"committed intact version in {self.base_dir!r}")
        final = self._pin_path(name)
        tmp = final + f".tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump({"version": int(version),
                       "pinned_unix": time.time()}, fh)
        os.replace(tmp, final)
        # pin flips are control-plane decisions serving latencies hang
        # off: mark them on the run's timeline (ISSUE 15)
        instant("registry.pin", cat="registry", model=name,
                version=int(version))

    def clear_pin(self, name: str) -> None:
        """Back to newest-intact resolution (idempotent)."""
        try:
            os.remove(self._pin_path(name))
        except FileNotFoundError:
            return
        instant("registry.unpin", cat="registry", model=name)

    def pinned_version(self, name: str) -> Optional[int]:
        """The pinned version number, or None (no pin / unreadable pin —
        an unreadable pin file warns and reads as absent: serving must
        never wedge on a torn control-plane artifact)."""
        try:
            with open(self._pin_path(name)) as fh:
                return int(json.load(fh)["version"])
        except FileNotFoundError:
            return None
        except Exception as exc:
            warnings.warn(
                f"model {name!r} serving pin in {self.base_dir!r} is "
                f"unreadable ({type(exc).__name__}: {exc}); falling back "
                f"to newest intact version", RuntimeWarning)
            return None

    def serving_version(self, name: str) -> Optional[int]:
        """THE version the serving tier should run: the pinned version
        when a pin exists and its target is intact (rollback contract),
        otherwise the newest intact version (the historic hot-swap
        resolution).  A pin whose target tore (dying-node copy-in)
        degrades to newest-intact with a warning instead of refusing
        traffic."""
        pin = self.pinned_version(name)
        if pin is not None:
            if self.is_intact(name, pin):
                return pin
            warnings.warn(
                f"model {name!r} pinned version {pin} in "
                f"{self.base_dir!r} is torn or missing; serving falls "
                f"back to the newest intact version", RuntimeWarning)
        return self.latest_version(name)

    # ---- retention ----
    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:
            return True        # exists, just not ours
        except OSError:
            return True        # unknown: err on the safe side

    def retire(self, name: str, keep_last: int = 3,
               dry_run: bool = False) -> List[int]:
        """GC old versions so a controller's publish cadence cannot grow
        the registry unboundedly: keep the newest ``keep_last`` committed
        versions, plus — always — the pinned version and the resolved
        serving version (retiring the version a rollback points at, or
        the one the fleet is converging onto, would turn the next refresh
        into a FileNotFoundError).  Abandoned ``.tmp`` publishes are
        swept too — but ONLY when the pid in their suffix is dead: a
        cadenced GC racing a live publisher's in-flight tmp must not
        yank the directory out from under its payload write.  Returns
        the retired version numbers; ``dry_run`` computes the same list
        (the single source of the keep rule) without deleting anything."""
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        versions = self.versions(name)
        keep = set(versions[-keep_last:])
        for protected in (self.pinned_version(name),
                          self.serving_version(name)):
            if protected is not None:
                keep.add(protected)
        # the ACTIVE delta window stays intact: a version a consumer can
        # be told to load next (latest / pinned / serving) may carry a
        # delta sidecar, and fleets resident on its parent are the ones
        # mid-O(delta)-swap right now — retiring that parent would orphan
        # the sidecar of the very version being distributed (registrytool
        # verify flags exactly that).  Only the DIRECT parent matters: a
        # grandparent's residents fail the sha-chain gate and full-load
        # anyway, and every delta child owns full artifacts, so historic
        # chains never pin the registry open (the controller's cadenced
        # retire_keep_last must stay bounded even when every publish is
        # incremental).
        all_v = set(versions)
        loadable = {v for v in (versions[-1] if versions else None,
                                self.pinned_version(name),
                                self.serving_version(name))
                    if v is not None}
        for v in loadable:
            info = self.delta_info(name, v)
            if not info:
                continue
            p = int(info.get("parent_version", -1))
            if p in all_v:
                keep.add(p)
        retired = [v for v in versions if v not in keep]
        if dry_run:
            return retired
        for v in retired:
            shutil.rmtree(self.version_dir(name, v), ignore_errors=True)
        d = self.store.path(name)
        if os.path.isdir(d):
            now = time.time()
            for entry in os.listdir(d):
                m = _TMP_RE.match(entry)
                if not m or self._pid_alive(int(m.group(1))):
                    continue
                path = os.path.join(d, entry)
                try:
                    age = now - os.path.getmtime(path)
                except OSError:
                    continue
                if age < _TMP_GRACE_S:
                    # the pid probe only sees THIS host; on a shared
                    # (NFS) registry a remote publisher's in-flight tmp
                    # looks pid-dead here — the age grace is what keeps
                    # a cadenced GC from yanking it mid-write.  A real
                    # orphan is still swept one grace period later.
                    continue
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    try:
                        os.remove(path)   # an orphaned pin tmp file
                    except OSError:
                        pass
        return retired

    def names(self) -> List[str]:
        """All model names with at least one committed version (the
        registrytool listing surface)."""
        if not os.path.isdir(self.base_dir):
            return []
        out = []
        for entry in sorted(os.listdir(self.base_dir)):
            if os.path.isdir(os.path.join(self.base_dir, entry)) \
                    and self.versions(entry):
                out.append(entry)
        return out

    # ---- publish ----
    def publish(self, name: str, model: Any, *,
                schema: Optional[FeatureSchema] = None,
                kind: Optional[str] = None,
                params: Optional[Dict[str, Any]] = None) -> int:
        """Write the model as the next version and atomically commit it.
        Returns the new version number.  Readers polling
        :meth:`latest_version` pick it up on their next refresh — the
        hot-swap contract."""
        kind = kind or _detect_kind(model)
        arrays, model_json, class_values = _encode(model, kind, schema)
        versions = self.versions(name)
        version = (versions[-1] + 1) if versions else 1
        final = self.version_dir(name, version)
        # single publisher per model name is the contract (multi-process
        # jobs publish from process 0 only); the pid suffix just keeps an
        # abandoned .tmp from a dead publisher out of a later one's way
        tmp = final + f".tmp.{os.getpid()}"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        meta = {
            "format_version": FORMAT_VERSION,
            "name": name,
            "version": version,
            "kind": kind,
            "class_values": class_values,
            "dtypes": {k: str(v.dtype) for k, v in arrays.items()},
            "params": dict(params or {}),
            "model_json": model_json,
            "schema": schema.to_dict() if schema is not None else None,
            # manifest of payload files the intactness probe must cover;
            # add_sidecar extends it (meta.json itself is implied)
            "files": [ARRAYS_FILE],
        }
        if kind == FOREST and model_json is not None:
            # the delta chain's identity axis: every forest version
            # records its members' content shas at publish time
            meta["tree_shas"] = _tree_shas(model_json["trees"])

        def write_arrays():
            fault_point("registry_publish")
            np.savez(os.path.join(tmp, ARRAYS_FILE), **arrays)
        with_retry(write_arrays, what=f"registry publish {name} v{version}")
        write_json(os.path.join(tmp, META_FILE), meta)
        os.replace(tmp, final)
        instant("registry.publish", cat="registry", model=name,
                version=version, kind=kind)
        return version

    # ---- O(delta) distribution (ISSUE 20) ----
    def publish_delta(self, name: str, model: Any, *,
                      parent_version: int,
                      schema: Optional[FeatureSchema] = None,
                      params: Optional[Dict[str, Any]] = None) -> int:
        """Publish a forest as the next version PLUS a ``delta.npz`` /
        ``delta.json`` sidecar pair holding only the trees that changed
        relative to ``parent_version`` — a serving tier resident on the
        parent patches O(changed trees) device bytes instead of
        re-uploading the model (serving/predictor.apply_delta).

        The FULL artifact is always written first (the delta is an
        overlay, never the only copy), and the sidecar attach is
        best-effort: any incompatibility — parent torn/retired, member
        count or class vocabulary changed, a changed tree outgrowing
        the parent's stacked layout (smaller layouts re-pad fine) —
        warns and returns the plain full publish; consumers detect the
        missing sidecar and fall back to full-artifact load.  Returns
        the new version number either way."""
        params = dict(params or {})
        params["delta_parent"] = int(parent_version)
        version = self.publish(name, model, schema=schema, params=params)
        try:
            self._attach_delta(name, version, int(parent_version))
        except Exception as exc:
            warnings.warn(
                f"model {name!r} v{version}: delta sidecar against "
                f"parent v{parent_version} not attached "
                f"({type(exc).__name__}: {exc}); consumers will load "
                f"the full artifact", RuntimeWarning)
        return version

    def _attach_delta(self, name: str, version: int,
                      parent_version: int) -> None:
        """Compute + attach the delta sidecars (raises on any layout or
        chain mismatch — publish_delta turns that into a warning)."""
        import io
        from ..models.forest import EnsembleModel
        from ..models.tree import DecisionTreeModel
        if not self.is_intact(name, parent_version):
            raise ValueError(f"parent v{parent_version} is not intact")
        child = self.load(name, version)
        parent = self.load(name, parent_version)
        if child.kind != FOREST or parent.kind != FOREST:
            raise ValueError("delta publish is forest-only")
        child_shas = list(child.meta.get("tree_shas") or [])
        parent_shas = list(parent.meta.get("tree_shas") or [])
        if not child_shas or not parent_shas:
            raise ValueError("parent predates per-tree shas")
        if len(child_shas) != len(parent_shas):
            raise ValueError(
                f"member count changed ({len(parent_shas)} -> "
                f"{len(child_shas)}); no O(delta) form exists")
        if child.schema is None:
            raise ValueError("forest artifact has no embedded schema")

        def host_form(loaded):
            models = [DecisionTreeModel(pl, loaded.schema)
                      for pl in loaded.model]
            ens = EnsembleModel(
                models, weights=loaded.params.get("weights"),
                min_odds_ratio=float(
                    loaded.params.get("min_odds_ratio", 1.0)),
                require_odd=False, stack=False)
            return ens, ens.stacked_host()
        c_ens, c_host = host_form(child)
        p_ens, p_host = host_form(parent)
        if c_host is None or p_host is None:
            raise ValueError("no stacked device form (degenerate member "
                             "or non-f32-exact bounds)")
        if c_ens.classes != p_ens.classes:
            raise ValueError("class vocabulary changed")
        if any(c.shape[1:] != p.shape[1:]
               for c, p in zip(c_host, p_host)):
            # the patch targets a resident stacked in the PARENT's
            # layout, so re-pad the child slices to the parent's
            # (P, cmax) — per-tree slots are laid out independently of
            # the global max (sentinel at the tree's own path count,
            # never-match / vote-nothing rows after), so padding is
            # bit-exact.  Only a changed tree that OUTGROWS the parent
            # layout has no O(delta) form.
            c_host = _pad_stacked_to(c_host, p_host)
        changed = [i for i, (cs, ps) in
                   enumerate(zip(child_shas, parent_shas)) if cs != ps]
        lo, hi, num_r, cat_m, cat_r, cls_oh = c_host
        idx = np.asarray(changed, np.int32)
        buf = io.BytesIO()
        np.savez(buf, idx=idx, lo=lo[idx], hi=hi[idx], num_r=num_r[idx],
                 cat_m=cat_m[idx], cat_r=cat_r[idx], cls_oh=cls_oh[idx],
                 wvec=np.asarray(c_ens.weights, np.float32))
        trees = child.meta["model_json"]["trees"]
        dmeta = {
            "format": DELTA_FORMAT_VERSION,
            "parent_version": int(parent_version),
            "parent_tree_shas": parent_shas,
            "tree_shas": child_shas,
            "classes": list(c_ens.classes),
            "n_trees": len(child_shas),
            "changed": [int(i) for i in changed],
            "changed_trees": [trees[i] for i in changed],
            "stacked_shape": {"P": int(lo.shape[1]),
                              "F": int(lo.shape[2]),
                              "cmax": int(cat_m.shape[3]),
                              "K": int(cls_oh.shape[2])},
        }
        self.add_sidecar(name, version, {
            DELTA_NPZ: buf.getvalue(),
            DELTA_JSON: json.dumps(dmeta).encode(),
        })
        instant("registry.delta_publish", cat="registry", model=name,
                version=version, parent=int(parent_version),
                changed=len(changed), total=len(child_shas))

    def delta_info(self, name: str, version: int) -> Optional[Dict]:
        """The parsed ``delta.json`` sidecar, or None when the version
        carries no (readable) delta — absence means full-artifact load,
        never an error."""
        try:
            return json.loads(
                self.read_sidecar(name, version, DELTA_JSON))
        except FileNotFoundError:
            return None
        except Exception as exc:
            warnings.warn(
                f"model {name!r} v{version}: delta sidecar unreadable "
                f"({type(exc).__name__}: {exc}); treating as absent",
                RuntimeWarning)
            return None

    def load_delta(self, name: str, version: int
                   ) -> Tuple[Dict, Dict[str, np.ndarray]]:
        """(delta meta, delta arrays) for a version published with an
        attached delta sidecar; FileNotFoundError when it has none."""
        import io
        dmeta = json.loads(self.read_sidecar(name, version, DELTA_JSON))
        with np.load(io.BytesIO(
                self.read_sidecar(name, version, DELTA_NPZ))) as z:
            arrays = {k: z[k] for k in z.files}
        return dmeta, arrays

    # ---- sidecars ----
    def add_sidecar(self, name: str, version: int,
                    files: Dict[str, bytes]) -> None:
        """Attach extra payload files to a COMMITTED version and extend
        its meta.json manifest, crash-safely: every sidecar file writes
        ``<file>.tmp.<pid>`` and renames into place BEFORE the manifest
        update (itself tmp-then-rename), so a crash at any point leaves
        the version either intact-without-sidecar or intact-with — a
        half-written sidecar is never listed, and a listed one that later
        tears (dying-node copy-in) fails the is_intact probe."""
        if not files:
            return
        d = self.version_dir(name, version)
        meta_path = os.path.join(d, META_FILE)
        with open(meta_path) as fh:
            meta = json.load(fh)
        reserved = {META_FILE, ARRAYS_FILE}
        for fname, payload in files.items():
            if os.path.basename(fname) != fname or fname in reserved:
                raise ValueError(f"bad sidecar file name {fname!r}")
            final = os.path.join(d, fname)
            tmp = final + f".tmp.{os.getpid()}"

            def write(tmp=tmp, final=final, payload=payload):
                fault_point("registry_sidecar")
                with open(tmp, "wb") as fh:
                    fh.write(payload)
                os.replace(tmp, final)
            with_retry(write,
                       what=f"sidecar write {name} v{version} {fname}")
        manifest = list(meta.get("files") or [ARRAYS_FILE])
        manifest.extend(f for f in files if f not in manifest)
        meta["files"] = manifest
        tmp_meta = meta_path + f".tmp.{os.getpid()}"
        with open(tmp_meta, "w") as fh:
            json.dump(meta, fh, indent=2)
        os.replace(tmp_meta, meta_path)

    def read_sidecar(self, name: str, version: int, fname: str) -> bytes:
        """Read one sidecar payload; FileNotFoundError when the version
        does not carry it (not listed in the manifest)."""
        d = self.version_dir(name, version)
        with open(os.path.join(d, META_FILE)) as fh:
            meta = json.load(fh)
        if fname not in (meta.get("files") or []):
            raise FileNotFoundError(
                f"model {name!r} v{version} has no sidecar {fname!r}")
        with open(os.path.join(d, fname), "rb") as fh:
            return fh.read()

    # ---- load ----
    def load(self, name: str, version: Optional[int] = None,
             schema: Optional[FeatureSchema] = None) -> LoadedModel:
        """Reconstruct a model (+ its schema when the artifact carries one).
        Default version: the newest intact one.  Dtype pins from the
        artifact JSON are enforced — a payload whose arrays do not match
        the dtypes recorded at publish time fails loudly instead of
        serving subtly different predictions."""
        if version is None:
            version = self.latest_version(name)
            if version is None:
                raise FileNotFoundError(
                    f"no intact versions of model {name!r} in "
                    f"{self.base_dir!r}")
        d = self.version_dir(name, version)
        with open(os.path.join(d, META_FILE)) as fh:
            meta = json.load(fh)
        with np.load(os.path.join(d, ARRAYS_FILE)) as z:
            arrays = {k: z[k] for k in z.files}
        declared = meta.get("dtypes", {})
        actual = {k: str(v.dtype) for k, v in arrays.items()}
        if declared != actual:
            raise ValueError(
                f"model {name!r} v{version}: array dtypes {actual} do not "
                f"match the artifact's declared {declared}")
        if schema is None and meta.get("schema") is not None:
            schema = FeatureSchema.from_dict(meta["schema"])
        kind = meta["kind"]
        model = _decode(kind, arrays, meta, schema)
        return LoadedModel(name=name, version=version, kind=kind,
                           model=model, meta=meta, schema=schema,
                           base_dir=self.base_dir)


# --------------------------------------------------------------------------
# module-level conveniences
# --------------------------------------------------------------------------

def save_model(base_dir: str, name: str, model: Any, *,
               schema: Optional[FeatureSchema] = None,
               kind: Optional[str] = None,
               params: Optional[Dict[str, Any]] = None) -> int:
    return ModelRegistry(base_dir).publish(name, model, schema=schema,
                                           kind=kind, params=params)


def load_model(base_dir: str, name: str, version: Optional[int] = None,
               schema: Optional[FeatureSchema] = None) -> LoadedModel:
    return ModelRegistry(base_dir).load(name, version, schema=schema)
