"""Univariate Fisher linear discriminant.

Reference: discriminant/FisherDiscriminant.java — reuses the chombo
``NumericalAttrStats`` MR (per-(attr, classValue) count/mean/variance) and in
the reducer cleanup emits, per attribute, the two-class boundary:

  pooledVariance = (var0*n0 + var1*n1) / (n0+n1)
  logOddsPrior   = ln(n0/n1)
  discrimValue   = (mean0+mean1)/2 - logOddsPrior * pooledVariance / meanDiff

(FisherDiscriminant.java:44-55).  Class order follows first-seen in the
reference reducer; here it is the schema cardinality order, which is
deterministic.

TPU design: the class-conditional moments for ALL attributes are two one-hot
contractions — onehot(class).T @ X and onehot(class).T @ X² — one jitted
pass over the sharded rows (the NumericalAttrStats MR + combiner collapse
into a psum of per-shard partials).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.table import ColumnarTable


@dataclass
class FisherResult:
    attr_ordinals: List[int]
    counts: np.ndarray        # (2,) per-class record counts
    means: np.ndarray         # (2, F)
    variances: np.ndarray     # (2, F)

    def boundary(self, fi: int) -> Tuple[float, float, float]:
        """(logOddsPrior, pooledVariance, discrimValue) for feature index."""
        n0, n1 = float(self.counts[0]), float(self.counts[1])
        v0, v1 = float(self.variances[0, fi]), float(self.variances[1, fi])
        m0, m1 = float(self.means[0, fi]), float(self.means[1, fi])
        pooled = (v0 * n0 + v1 * n1) / (n0 + n1)
        log_odds = math.log(n0 / n1)
        mean_diff = m0 - m1
        # a constant feature (equal class means) has no prior-shift term; the
        # midpoint is the only defensible boundary rather than a div-by-zero
        discrim = (m0 + m1) / 2 - \
            (log_odds * pooled / mean_diff if mean_diff != 0.0 else 0.0)
        return log_odds, pooled, discrim

    def to_lines(self, delim: str = ",") -> List[str]:
        lines = []
        for fi, o in enumerate(self.attr_ordinals):
            lo, pv, dv = self.boundary(fi)
            lines.append(f"{o}{delim}{lo:.9g}{delim}{pv:.9g}{delim}{dv:.9g}")
        return lines


@jax.jit
def _class_moments(X, cls_onehot):
    counts = cls_onehot.sum(0)                       # (2,)
    s1 = cls_onehot.T @ X                            # (2, F)
    s2 = cls_onehot.T @ (X * X)
    safe = jnp.maximum(counts, 1.0)[:, None]
    mean = s1 / safe
    var = s2 / safe - mean * mean
    return counts, mean, var


def fisher_discriminant(table: ColumnarTable) -> FisherResult:
    schema = table.schema
    num_fields = [f for f in schema.feature_fields if f.is_numeric]
    if not num_fields:
        raise ValueError("Fisher discriminant needs numeric feature fields")
    card = schema.class_attr_field.cardinality or []
    if len(card) != 2:
        raise ValueError("Fisher discriminant is two-class "
                         f"(class cardinality = {len(card)})")
    X = np.stack([table.columns[f.ordinal] for f in num_fields],
                 axis=1).astype(np.float64)
    cls = table.class_codes()
    onehot = np.zeros((table.n_rows, 2))
    valid = cls >= 0
    onehot[np.arange(table.n_rows)[valid], cls[valid]] = 1.0
    # shift by the global per-feature mean before the one-pass moment
    # contraction: E[x²]-E[x]² in float32 cancels catastrophically for
    # features with large means; variance is shift-invariant, so centering
    # first keeps the f32 device path accurate
    shift = X.mean(axis=0)
    counts, mean, var = _class_moments(jnp.asarray(X - shift, jnp.float32),
                                       jnp.asarray(onehot, jnp.float32))
    mean = np.asarray(mean, np.float64) + shift
    counts_np = np.asarray(counts, np.float64)
    if counts_np.min() <= 0:
        missing = card[int(np.argmin(counts_np))]
        raise ValueError(f"class {missing!r} has no rows; Fisher boundary "
                         "needs both classes present")
    return FisherResult(attr_ordinals=[f.ordinal for f in num_fields],
                        counts=counts_np,
                        means=mean,
                        variances=np.asarray(var, np.float64))


def classify(result: FisherResult, table: ColumnarTable, fi: int) -> np.ndarray:
    """Classify by the univariate boundary on feature index fi: class 0 when
    the value is on mean0's side of discrimValue."""
    _, _, dv = result.boundary(fi)
    x = table.columns[result.attr_ordinals[fi]].astype(np.float64)
    m0, m1 = result.means[0, fi], result.means[1, fi]
    side0 = x >= dv if m0 >= m1 else x < dv
    return np.where(side0, 0, 1)
