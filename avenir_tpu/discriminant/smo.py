"""Sequential Minimal Optimization (Platt) SVM trainer + batched predictor.

Reference: discriminant/SequentialMinimalOptimization.java — full in-memory
SMO with linear kernel: the outer loop alternates examine-all /
examine-non-bound sweeps (:76-110), ``examine`` applies Platt's second-choice
heuristic then falls back to random sweeps over support vectors and the full
set (:115-160), ``step`` is the standard two-Lagrangian analytic update with
L/H clipping and threshold update.  discriminant/SupportVectorMachine.java
wraps it: each mapper trains on its partition and emits the support vectors
(:70-85).

TPU split: the SMO loop is inherently sequential (each step depends on the
previous alphas) so it stays host-side — but every inner quantity is a
*vector* op over the whole dataset (error cache refresh after a step is one
(n,d)@(d,) product), so numpy does per-step O(n d) work with no Python inner
loops.  Batch *prediction* is a device GEMM (models/knn-style): for the linear
kernel f(X) = X @ w - b.  Multiple per-group SVMs train independently
(the reference's per-mapper parallelism) — each group is small by
construction, so host training + device prediction is the right split.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..parallel.mesh import runtime_context

KERNEL_LINEAR = "linear"


@dataclass
class SMOParams:
    penalty_factor: float = 0.05      # C (svm.pnalty.factor default :62)
    tolerance: float = 1e-3
    eps: float = 1e-3
    kernel_type: str = KERNEL_LINEAR
    max_sweeps: int = 200             # safety bound on outer sweeps
    seed: int = 0


@dataclass
class SVMModel:
    weights: np.ndarray               # (d,) for linear kernel
    threshold: float                  # b in f(x) = w.x - b
    sup_vec_idx: np.ndarray           # indices of alpha>0 rows
    alphas: np.ndarray                # (n,)
    X: np.ndarray
    y: np.ndarray

    def support_vector_lines(self, delim: str = ",") -> List[str]:
        """Reference output: support vector rows = features..., target, alpha
        (SupportVectorMachine.java:76-85 emits data rows incl. lagrangian)."""
        lines = []
        for i in self.sup_vec_idx:
            vals = [f"{v:.6f}" for v in self.X[i]] + \
                [f"{self.y[i]:.0f}", f"{self.alphas[i]:.6f}"]
            lines.append(delim.join(vals))
        return lines


class SMOTrainer:
    def __init__(self, params: SMOParams):
        if params.kernel_type != KERNEL_LINEAR:
            raise ValueError(f"invalid kernel type {params.kernel_type!r} "
                             "(reference supports linear only, "
                             "SequentialMinimalOptimization.java:33-38)")
        self.p = params

    def train(self, X: np.ndarray, y: np.ndarray) -> SVMModel:
        """X (n,d) float, y (n,) in {-1,+1}."""
        p = self.p
        rng = np.random.default_rng(p.seed)
        n, d = X.shape
        self.X, self.y = X.astype(np.float64), y.astype(np.float64)
        self.alpha = np.zeros(n)
        self.b = 0.0
        self.w = np.zeros(d)
        # error cache: E_i = f(x_i) - y_i, refreshed vectorized
        self.E = -self.y.copy()
        C = p.penalty_factor

        num_changed, examine_all, sweeps = 0, True, 0
        while (num_changed > 0 or examine_all) and sweeps < p.max_sweeps:
            num_changed = 0
            if examine_all:
                for i2 in range(n):
                    num_changed += self._examine(i2, rng)
            else:
                for i2 in np.where((self.alpha > 0) & (self.alpha < C))[0]:
                    num_changed += self._examine(int(i2), rng)
            if examine_all:
                examine_all = False
            elif num_changed == 0:
                examine_all = True
            sweeps += 1

        sup = np.where(self.alpha > 1e-12)[0]
        return SVMModel(weights=self.w.copy(), threshold=self.b,
                        sup_vec_idx=sup, alphas=self.alpha.copy(),
                        X=self.X, y=self.y)

    # ---- Platt examine with second-choice heuristic + random fallbacks ----
    def _examine(self, i2: int, rng) -> int:
        p, C = self.p, self.p.penalty_factor
        y2, alph2, E2 = self.y[i2], self.alpha[i2], self.E[i2]
        r2 = E2 * y2
        if (r2 < -p.tolerance and alph2 < C) or (r2 > p.tolerance and alph2 > 0):
            nonbound = np.where((self.alpha > 0) & (self.alpha < C))[0]
            if len(nonbound) > 1:
                # second choice: maximize |E1 - E2|
                i1 = int(nonbound[np.argmax(np.abs(self.E[nonbound] - E2))])
                if self._step(i1, i2):
                    return 1
            # random sweep over non-bound, then over all
            for pool in (nonbound, np.arange(len(self.y))):
                if len(pool) == 0:
                    continue
                start = rng.integers(len(pool))
                for k in range(len(pool)):
                    i1 = int(pool[(start + k) % len(pool)])
                    if self._step(i1, i2):
                        return 1
        return 0

    def _step(self, i1: int, i2: int) -> bool:
        if i1 == i2:
            return False
        C, eps = self.p.penalty_factor, self.p.eps
        y1, y2 = self.y[i1], self.y[i2]
        alph1, alph2 = self.alpha[i1], self.alpha[i2]
        E1, E2 = self.E[i1], self.E[i2]
        s = y1 * y2
        if s > 0:
            L, H = max(0.0, alph1 + alph2 - C), min(C, alph1 + alph2)
        else:
            L, H = max(0.0, alph2 - alph1), min(C, C + alph2 - alph1)
        if L >= H:
            return False
        x1, x2 = self.X[i1], self.X[i2]
        k11, k12, k22 = x1 @ x1, x1 @ x2, x2 @ x2
        eta = k11 + k22 - 2.0 * k12
        if eta > 0:
            a2 = alph2 + y2 * (E1 - E2) / eta
            a2 = min(max(a2, L), H)
        else:
            # objective at both clip ends (Platt's degenerate-eta branch)
            f1 = y1 * (E1 + self.b) - alph1 * k11 - s * alph2 * k12
            f2 = y2 * (E2 + self.b) - s * alph1 * k12 - alph2 * k22
            L1 = alph1 + s * (alph2 - L)
            H1 = alph1 + s * (alph2 - H)
            Lobj = L1 * f1 + L * f2 + 0.5 * L1 * L1 * k11 + \
                0.5 * L * L * k22 + s * L * L1 * k12
            Hobj = H1 * f1 + H * f2 + 0.5 * H1 * H1 * k11 + \
                0.5 * H * H * k22 + s * H * H1 * k12
            if Lobj < Hobj - eps:
                a2 = L
            elif Lobj > Hobj + eps:
                a2 = H
            else:
                return False
        if abs(a2 - alph2) < eps * (a2 + alph2 + eps):
            return False
        a1 = alph1 + s * (alph2 - a2)
        # threshold update
        b1 = E1 + y1 * (a1 - alph1) * k11 + y2 * (a2 - alph2) * k12 + self.b
        b2 = E2 + y1 * (a1 - alph1) * k12 + y2 * (a2 - alph2) * k22 + self.b
        if 0 < a1 < C:
            b_new = b1
        elif 0 < a2 < C:
            b_new = b2
        else:
            b_new = 0.5 * (b1 + b2)
        # vectorized error-cache + weight refresh (the O(n d) inner product)
        dw = y1 * (a1 - alph1) * x1 + y2 * (a2 - alph2) * x2
        self.w += dw
        self.E += self.X @ dw - (b_new - self.b)
        self.b = b_new
        self.alpha[i1], self.alpha[i2] = a1, a2
        self.E[i1] = self.decision_one(i1) - self.y[i1]
        self.E[i2] = self.decision_one(i2) - self.y[i2]
        return True

    def decision_one(self, i: int) -> float:
        return self.X[i] @ self.w - self.b


# ---------------------------------------------------------------------------
# batched device prediction
# ---------------------------------------------------------------------------

@jax.jit
def _linear_decision(X, w, b):
    return X @ w - b


def decision_function(model: SVMModel, X: np.ndarray) -> np.ndarray:
    return np.asarray(_linear_decision(jnp.asarray(X, jnp.float32),
                                       jnp.asarray(model.weights, jnp.float32),
                                       jnp.float32(model.threshold)))


def predict(model: SVMModel, X: np.ndarray) -> np.ndarray:
    """±1 labels."""
    return np.where(decision_function(model, X) >= 0, 1.0, -1.0)


def _train_one(params: SMOParams, X: np.ndarray, y: np.ndarray) -> SVMModel:
    return SMOTrainer(params).train(X, y)


# ---------------------------------------------------------------------------
# device-batched group training (lock-step maximal-violating-pair SMO)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _batched_smo_kernel(C: float, tol: float, eps: float, max_iter: int):
    """One jitted program that trains G stacked SVMs lock-step.

    Pivot selection is Keerthi's deterministic maximal-violating-pair rule
    (i_up = argmin F over I_up, i_low = argmax F over I_low, stop when
    b_low - b_up <= 2 tol) instead of Platt's randomized fallback sweeps:
    every per-iteration quantity is then a masked argmin/argmax — exactly
    what vectorizes over the group axis.  The two-Lagrangian analytic step
    (incl. the degenerate-eta objective comparison) matches SMOTrainer._step.
    Converged / stalled groups freeze via masks; the loop ends when every
    group is done or at the iteration cap.  F_i = w.x_i - y_i (threshold-
    free gradient form); the final b is (b_up + b_low) / 2."""

    # membership margin: an alpha within MARGIN of a bound counts as AT
    # the bound for pivot-set membership (standard shrinking practice).
    # Without it, floating-point dust alphas (~ulp residue of earlier
    # updates) stay in I_up/I_low with no representable room to move and
    # MVP livelocks re-picking them (measured: stall at gap 1.78, dual 55
    # vs the margined run CONVERGING at dual 72.8 — beyond Platt serial's
    # 66.7, whose own stop rule is looser).  Unlike value-snapping this
    # never touches the alphas, so sum(alpha*y) = 0 stays exact.
    MARGIN = 1e-6

    def step(state):
        alpha, w, F, done, b_lo_hi, it, X, y, valid = state
        G, n, d = X.shape
        pos, neg = y > 0, y < 0
        up = valid & (((alpha < C - MARGIN) & pos)
                      | ((alpha > MARGIN) & neg))
        low = valid & (((alpha < C - MARGIN) & neg)
                       | ((alpha > MARGIN) & pos))
        inf = jnp.float32(np.inf)
        F_up = jnp.where(up, F, inf)
        F_low = jnp.where(low, F, -inf)
        i1 = jnp.argmin(F_up, axis=1)                   # (G,)
        i2 = jnp.argmax(F_low, axis=1)
        b_up = jnp.min(F_up, axis=1)
        b_low = jnp.max(F_low, axis=1)
        newly_done = b_low - b_up <= 2.0 * tol
        active = ~done & ~newly_done

        g_idx = jnp.arange(G)
        x1, x2 = X[g_idx, i1], X[g_idx, i2]             # (G,d)
        y1, y2 = y[g_idx, i1], y[g_idx, i2]
        a1o, a2o = alpha[g_idx, i1], alpha[g_idx, i2]
        F1, F2 = F[g_idx, i1], F[g_idx, i2]
        s = y1 * y2
        L = jnp.where(s > 0, jnp.maximum(0.0, a1o + a2o - C),
                      jnp.maximum(0.0, a2o - a1o))
        H = jnp.where(s > 0, jnp.minimum(C, a1o + a2o),
                      jnp.minimum(C, C + a2o - a1o))
        k11 = (x1 * x1).sum(-1)
        k12 = (x1 * x2).sum(-1)
        k22 = (x2 * x2).sum(-1)
        eta = k11 + k22 - 2.0 * k12
        a2_eta = jnp.clip(a2o + y2 * (F1 - F2) / jnp.maximum(eta, 1e-30),
                          L, H)
        # degenerate eta: objective at both clip ends (Platt; F here is
        # E + b of the serial form, which is exactly what f1/f2 use)
        f1 = y1 * F1 - a1o * k11 - s * a2o * k12
        f2 = y2 * F2 - s * a1o * k12 - a2o * k22
        L1 = a1o + s * (a2o - L)
        H1 = a1o + s * (a2o - H)
        Lobj = (L1 * f1 + L * f2 + 0.5 * L1 * L1 * k11
                + 0.5 * L * L * k22 + s * L * L1 * k12)
        Hobj = (H1 * f1 + H * f2 + 0.5 * H1 * H1 * k11
                + 0.5 * H * H * k22 + s * H * H1 * k12)
        a2_deg = jnp.where(Lobj < Hobj - eps, L,
                           jnp.where(Lobj > Hobj + eps, H, a2o))
        a2n = jnp.where(eta > 0, a2_eta, a2_deg)
        a1n = a1o + s * (a2o - a2n)
        # NOT Platt's relative step test: under MVP selection that test
        # freezes groups mid-descent (the serial loop escapes it by trying
        # other pairs; measured: dual 7.8 vs 66.7 on overlapping data).
        # Convergence is the duality gap above; here only exact-zero moves
        # (f32 ulp, or a clipped-empty [L,H]) mark a group stalled.
        progress = (H > L) & (a2n != a2o)
        change = active & progress
        c1 = jnp.where(change, y1 * (a1n - a1o), 0.0)
        c2 = jnp.where(change, y2 * (a2n - a2o), 0.0)
        dw = c1[:, None] * x1 + c2[:, None] * x2        # (G,d)
        w = w + dw
        # F recomputed FROM w (same einsum cost as the incremental
        # F += X@dw): thousands of incremental f32 updates drift the error
        # cache enough to corrupt the gap test and stop far from optimum
        F = jnp.einsum("gnd,gd->gn", X, w) - y
        alpha = alpha.at[g_idx, i1].set(
            jnp.where(change, a1n, a1o))
        alpha = alpha.at[g_idx, i2].set(
            jnp.where(change, a2n, alpha[g_idx, i2]))
        # a maximal-violating pair that cannot move (degenerate data)
        # would spin forever: freeze that group as stalled
        done = done | newly_done | (active & ~progress)
        b_lo_hi = jnp.where(done[:, None] & (b_lo_hi[:, 0:1] == inf),
                            jnp.stack([b_up, b_low], axis=1), b_lo_hi)
        return alpha, w, F, done, b_lo_hi, it + 1, X, y, valid

    def cond(state):
        done, it = state[3], state[5]
        return (~jnp.all(done)) & (it < max_iter)

    # the stacked label vector is DONATED: train_groups_batched builds a
    # fresh y per call and never reuses it, and the (G, n) f32 alpha
    # output is its exact shape/dtype twin, so XLA aliases the two
    # buffers instead of holding a defensive copy across the while_loop.
    # X and valid are deliberately NOT donated — no output matches their
    # shape/dtype, so their donation would be a no-op that only emits the
    # 'donated buffers were not usable' warning per compiled shape.
    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(X, y, valid):
        G, n, _ = X.shape
        alpha = jnp.zeros((G, n), jnp.float32)
        w = jnp.zeros((G, X.shape[2]), jnp.float32)
        F = -y  # w = 0 -> F_i = -y_i
        done = jnp.zeros((G,), bool)
        b_lo_hi = jnp.full((G, 2), np.inf, jnp.float32)
        state = (alpha, w, F, done, b_lo_hi,
                 jnp.asarray(0, jnp.int32), X, y, valid)
        alpha, w, F, done, b_lo_hi, it, _, _, _ = \
            jax.lax.while_loop(cond, step, state)
        # groups that hit the iteration cap: record their current bounds
        pos, neg = y > 0, y < 0
        up = valid & (((alpha < C - MARGIN) & pos)
                      | ((alpha > MARGIN) & neg))
        low = valid & (((alpha < C - MARGIN) & neg)
                       | ((alpha > MARGIN) & pos))
        b_up = jnp.min(jnp.where(up, F, np.inf), axis=1)
        b_low = jnp.max(jnp.where(low, F, -np.inf), axis=1)
        b_lo_hi = jnp.where(b_lo_hi[:, 0:1] == np.inf,
                            jnp.stack([b_up, b_low], axis=1), b_lo_hi)
        b = 0.5 * (b_lo_hi[:, 0] + b_lo_hi[:, 1])
        # degenerate group (an empty I_up or I_low, e.g. one class only):
        # bounds are +/-inf; the serial trainer returns b = 0 there
        b = jnp.where(jnp.isfinite(b), b, 0.0)
        return alpha, w, b, it

    return run


def train_groups_batched(groups: Dict[str, Tuple[np.ndarray, np.ndarray]],
                         params: SMOParams,
                         stats: Optional[dict] = None
                         ) -> Dict[str, SVMModel]:
    """All groups stacked and trained lock-step in ONE jitted while_loop —
    the device answer to the reference's per-mapper SMO partitions
    (SupportVectorMachine.java:70-85).  Groups pad to the widest row count
    (padded rows masked out of pivot selection), so per-iteration work is
    a handful of (G, n[, d]) fused element-wise/reduction kernels instead
    of G sequential python loops.

    The pivot heuristic is deterministic maximal-violating-pair, NOT
    Platt's randomized fallback sweeps (see _batched_smo_kernel): both
    optimize the same dual, so weights/threshold agree with SMOTrainer to
    optimization tolerance and predictions match, but alpha SETS (and
    support-vector output lines) can differ on degenerate margins — this
    is a different trainer, not a drop-in byte-identical replacement,
    which is why train_groups only selects it by explicit request."""
    if params.kernel_type != KERNEL_LINEAR:
        raise ValueError("batched SMO supports the linear kernel only")
    items = list(groups.items())
    if not items:
        return {}
    d = items[0][1][0].shape[1]
    if any(X.shape[1] != d for _, (X, y) in items):
        raise ValueError("batched SMO needs a common feature width")
    G = len(items)
    n_max = max(X.shape[0] for _, (X, _) in items)
    Xb = np.zeros((G, n_max, d), np.float32)
    yb = np.ones((G, n_max), np.float32)   # pad labels +1, masked anyway
    valid = np.zeros((G, n_max), bool)
    for gi, (_, (X, y)) in enumerate(items):
        n = X.shape[0]
        Xb[gi, :n] = X
        yb[gi, :n] = y
        valid[gi, :n] = True
    run = _batched_smo_kernel(params.penalty_factor, params.tolerance,
                              params.eps,
                              max_iter=params.max_sweeps * n_max)
    ctx = runtime_context()
    if (jax.process_count() == 1 and ctx.n_devices > 1
            and G % ctx.n_devices == 0):
        # groups are embarrassingly parallel: shard the group axis over
        # the mesh (every per-iteration op is per-group, so GSPMD's only
        # collective is the all-groups-done reduction in the loop cond).
        # Host numpy goes straight to the sharded placement — an
        # asarray-then-reshard would upload everything to device 0 first
        Xj, yj, vj = (ctx.shard_rows(a) for a in (Xb, yb, valid))
    else:
        from ..utils.tracing import note_h2d
        note_h2d(Xb.nbytes + yb.nbytes + valid.nbytes, transfers=3)
        Xj, yj, vj = jnp.asarray(Xb), jnp.asarray(yb), jnp.asarray(valid)
    from ..utils.tracing import fetch, note_dispatch
    note_dispatch()
    alpha, w, b, it = (fetch(v) for v in run(Xj, yj, vj))
    if stats is not None:
        # real lock-step iteration count (bench rooflines model work from
        # it rather than a hard-coded constant)
        stats["iterations"] = int(it)
    out = {}
    for gi, (g, (X, y)) in enumerate(items):
        n = X.shape[0]
        a = alpha[gi, :n].astype(np.float64)
        out[g] = SVMModel(weights=w[gi].astype(np.float64),
                          threshold=float(b[gi]),
                          sup_vec_idx=np.where(a > 1e-12)[0],
                          alphas=a, X=X.astype(np.float64),
                          y=y.astype(np.float64))
    return out


def train_groups_sharded(groups: Dict[str, Tuple[np.ndarray, np.ndarray]],
                         params: SMOParams,
                         reducer,
                         stats: Optional[dict] = None
                         ) -> Dict[str, SVMModel]:
    """Multi-host data-parallel group training: shard the GROUP axis
    across processes by the same ``shard_rows`` split the streaming
    ingest uses, train each shard's groups with the lock-step batched
    kernel locally, then ONE collective (``reducer.allgather`` of the
    stacked per-group (weights, threshold, alphas)) hands every process
    the identical full model dict.

    The group axis — the reference's per-mapper SVM partitions
    (SupportVectorMachine.java:70-85) — is the right parallel axis here,
    NOT the row axis: per-group row counts are small by construction
    (each mapper's partition), so a row-parallel SMO would pay a
    cross-host collective per pivot iteration for microseconds of local
    compute — the exact inversion of the one-collective-per-step rule the
    tree/KNN shards follow.  Sharding whole groups keeps every iteration
    local and the single result merge is the only wire traffic.

    Every process must pass the SAME ``groups`` dict (same keys, same
    order — the gather/partition job modes guarantee a global input
    view); results are bit-identical across processes and to an unsharded
    ``train_groups_batched`` run (each group's training sees exactly the
    same lock-step kernel on the same rows — pinned by
    tests/test_sharded_stream.py)."""
    items = list(groups.items())
    spec = reducer.spec
    from ..parallel.distributed import shard_rows as _split_rows
    lo, hi = _split_rows(len(items), spec.index, spec.count)
    local = dict(items[lo:hi])
    trained = train_groups_batched(local, params, stats=stats) \
        if local else {}
    payload = {g: (m.weights, m.threshold, m.alphas)
               for g, m in trained.items()}
    merged: Dict[str, SVMModel] = {}
    for part in reducer.allgather(payload):
        for g, (w, b, a) in part.items():
            X, y = groups[g]
            merged[g] = SVMModel(
                weights=np.asarray(w), threshold=float(b),
                sup_vec_idx=np.where(np.asarray(a) > 1e-12)[0],
                alphas=np.asarray(a), X=X.astype(np.float64),
                y=y.astype(np.float64))
    return {g: merged[g] for g, _ in items}


def train_groups(groups: Dict[str, Tuple[np.ndarray, np.ndarray]],
                 params: SMOParams,
                 workers: int = 0,
                 batched: bool = False) -> Dict[str, SVMModel]:
    """Per-group SVMs — the reference's per-mapper partitions
    (SupportVectorMachine.java:70-85), whose parallelism is PROCESS-level:
    Platt's heuristics make each group's loop inherently sequential (the
    second-choice pick and random fallbacks depend on the evolving error
    cache), so the scaling axis is many groups at once, not a vectorized
    step.

    ``workers`` > 1 trains groups in a spawn-mode process pool (fork after
    XLA backend init can deadlock); groups are independent and per-group
    seeding is unchanged, so results are bit-identical to the serial loop
    in any worker count.

    Measured bound (CPU host, 100 groups x 200 rows x 6 features, C=1.0):
    ~0.40 s/group serial (40 s total), and the 8-worker pool came out
    0.5x — SLOWER — because this container's sitecustomize imports jax at
    interpreter start (~2.3 s per spawned worker) and each worker re-pays
    it.  Hence 0 = auto stays SERIAL; pass ``workers`` explicitly when
    per-group work dwarfs worker spawn cost (thousands of rows per group,
    or an environment with a light interpreter start).

    ``batched=True`` routes to :func:`train_groups_batched` — ONE jitted
    lock-step program over all stacked groups (the r4-verdict device
    formulation).  Explicit opt-in because its deterministic pivot rule is
    a different (equivalent-optimum) trainer whose support-vector lines
    are not byte-identical to Platt serial."""
    if batched:
        return train_groups_batched(groups, params)
    items = list(groups.items())
    if workers == 0:
        workers = 1
    if workers <= 1:
        return {g: _train_one(params, X, y) for g, (X, y) in items}
    import multiprocessing as mp
    from concurrent.futures import ProcessPoolExecutor
    with ProcessPoolExecutor(max_workers=workers,
                             mp_context=mp.get_context("spawn")) as ex:
        futs = {g: ex.submit(_train_one, params, X, y)
                for g, (X, y) in items}
        return {g: f.result() for g, f in futs.items()}
