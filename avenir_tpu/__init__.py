"""avenir-tpu: a TPU-native classical-ML framework.

Re-implements the capabilities of the avenir toolkit (Hadoop/Spark/Storm;
see /root/reference) as an idiomatic JAX/XLA framework: CSV in / CSV out,
JSON schema metadata, properties-file configuration — but with sharded
device arrays instead of HDFS records, GSPMD collectives instead of the
shuffle, and jitted one-pass histogram/reduction kernels instead of
mapper/reducer pairs.

Layer map (mirrors SURVEY.md section 1, rebuilt TPU-first):

    L1 core      avenir_tpu.core      schema / config / columnar tables / metrics / artifacts
    L2 parallel  avenir_tpu.parallel  mesh + the five communication idioms over ICI/DCN
    L3 ops       avenir_tpu.ops       pure array kernels (histograms, distances, scans)
    L4 models    avenir_tpu.models    trainers/predictors (bayes, tree, knn, markov, ...)
       explore   avenir_tpu.explore   feature engineering & selection pack
       optimize  avenir_tpu.optimize  SA / GA stochastic optimization
       reinforce avenir_tpu.reinforce multi-arm bandits (batch + online serving)
       sequence  avenir_tpu.sequence  sequence mining
    L5 cli       avenir_tpu.cli       .properties-driven job runner (replaces hadoop jar ...)
"""

__version__ = "0.1.0"
