"""Pipeline compiler (TPU_NOTES §22): fuse multi-stage chunk jobs into
ONE cached XLA program per chunk with device-resident intermediates.

Three pieces:

* :mod:`.compiler` — :class:`Stage` (one stage of a fused per-chunk
  program: pure kernel + host ``prepare`` + donated carry + declared
  returns) and :class:`ChunkPipeline` (composes a stage list into one
  jitted/AOT-compiled per-chunk function, dispatched as ONE launch per
  chunk, with per-run cache tallies for the job counters).
* :mod:`.cache` — :class:`ProgramCache`, the Execution Templates
  control plane: lowered/compiled executables keyed by (stage graph,
  schema fingerprint, argument shapes/dtypes, mesh spec), process-global
  so repeated jobs re-trace nothing, optionally persisted across
  processes via ``jax.jit`` AOT serialization.
* :mod:`.flows` — prebuilt fused flows: :class:`PredictDriftFlow`
  (ensemble vote + drift-window absorb in one program — the combined
  ``predictDriftScore`` CLI job's core).

The streaming RF build's per-chunk encode(+baseline-absorb) path
(``models/tree.TreeBuilder.from_stream``) is built on the same layer.
"""

from .cache import (ProgramCache, mesh_fingerprint, program_cache,
                    schema_fingerprint)
from .compiler import ChunkPipeline, Stage

__all__ = ["Stage", "ChunkPipeline", "ProgramCache", "program_cache",
           "schema_fingerprint", "mesh_fingerprint"]
