"""ProgramCache: the Execution Templates control plane for fused chunk
programs (PAPERS.md — cache the staged program + buffer plan so repeated
jobs skip re-tracing/re-validation).

A fused per-chunk program is keyed by everything that determines its
lowered XLA form and NOTHING else:

  * the **stage graph fingerprint** — ordered ``name:version`` chain of
    the composed stages (the program's structure);
  * the **schema fingerprint** — sha256 over the canonical schema dict
    (stage constants like split thresholds or ensemble predicate
    tensors are runtime *arguments*, so two jobs over the same schema
    shape share one executable even when the learned values differ —
    that is the whole point of the template split);
  * the **argument signature** — flattened (shape, dtype) of every
    carry, constant, and per-chunk input;
  * the **mesh fingerprint** — device count, platform, axis names, and
    (sharded runs) the shard spec transport identity;
  * the **kernel backend** — the resolved ``kernel.backend`` selection
    (xla | pallas, TPU_NOTES §24): stage kernels may swap in pallas
    twins at trace time, so a backend flip must miss.

Changing any of the five MISSES (and compiles fresh); an identical
re-run HITS with zero retraces — pinned by tests/test_pipeline.py via
the cache's own counters.

The cache is process-global (:func:`program_cache`) so repeated job
invocations in one process skip re-tracing entirely, and — where the
backend allows — entries persist ACROSS processes through ``jax.jit``
AOT ``lower()/compile()`` + ``jax.experimental.serialize_executable``
into ``AVENIR_TPU_PROGRAM_CACHE_DIR`` (off by default; a backend or
pickle refusal degrades to in-memory with one warning, never an
error).  Telemetry: a compile records a ``pipeline.compile`` span, a
key served from cache records a ``pipeline.cache_hit`` instant.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import warnings
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from ..telemetry import instant, span

DEFAULT_MAXSIZE = 64
_PERSIST_ENV = "AVENIR_TPU_PROGRAM_CACHE_DIR"


def schema_fingerprint(schema) -> str:
    """sha256 over the canonical schema dict — the data-layout half of a
    program key (same schema => same encode/monitor shapes)."""
    payload = json.dumps(schema.to_dict(), sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def mesh_fingerprint(ctx, reducer=None) -> str:
    """The placement half of a program key: a compiled executable is
    specialized to its device set, and a sharded run's program must not
    be confused with a monolithic one (the shard count changes the
    collective schedule even though the per-chunk program is local)."""
    mesh = getattr(ctx, "mesh", None)
    axes = tuple(getattr(mesh, "axis_names", ()) or ())
    parts = [f"d{ctx.n_devices}", ctx.device_platform, "x".join(axes)]
    if reducer is not None and getattr(reducer, "spec", None) is not None:
        parts.append(reducer.fingerprint())
    return ":".join(parts)


def _arg_signature(tree) -> Tuple:
    """Flattened (path-free) (shape, dtype) signature of a pytree of
    arrays — the shape/dtype-set component of a program key."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (str(treedef),
            tuple((tuple(getattr(l, "shape", ())),
                   str(getattr(l, "dtype", type(l).__name__)))
                  for l in leaves))


class _Entry:
    __slots__ = ("compiled", "from_disk")

    def __init__(self, compiled, from_disk: bool = False):
        self.compiled = compiled
        self.from_disk = from_disk


class ProgramCache:
    """LRU cache of AOT-compiled fused chunk programs.

    ``get_or_compile(key, build, args)`` returns a compiled executable:
    a hit is a dict lookup; a miss calls ``build()`` for the jitted
    function, then ``lower(*args).compile()`` under a
    ``pipeline.compile`` span.  ``build`` must close over NO arrays —
    every tensor reaches the program as an argument, so a cached
    executable is valid for any caller whose key matches.

    Thread-safe (thread-simulated shard tests share the process-global
    instance); compiled executables themselves are safe to invoke
    concurrently."""

    def __init__(self, maxsize: int = DEFAULT_MAXSIZE,
                 persist_dir: Optional[str] = None):
        self.maxsize = int(maxsize)
        self.persist_dir = persist_dir if persist_dir is not None \
            else (os.environ.get(_PERSIST_ENV) or None)
        self._entries: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.retraces = 0   # true compiles (disk hits are misses, not retraces)
        self.disk_hits = 0
        self.disk_stores = 0
        self._disk_warned = False

    # ---- stats ----
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "retraces": self.retraces, "disk_hits": self.disk_hits,
                    "disk_stores": self.disk_stores,
                    "entries": len(self._entries)}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def invalidate(self, key: Tuple) -> None:
        with self._lock:
            self._entries.pop(key, None)

    # ---- the control plane ----
    def get_or_compile(self, key: Tuple, build: Callable[[], Any],
                       args: Tuple,
                       on_outcome: Optional[Callable[[str], None]] = None
                       ) -> Any:
        """The one entry: ``key`` hashable, ``build()`` -> a ``jax.jit``
        wrapper (donation flags and all), ``args`` the first chunk's
        concrete argument tuple (shapes/dtypes define the lowering).

        ``on_outcome`` (if given) is called once with how THIS call
        resolved — ``"hit"`` | ``"disk"`` | ``"compile"`` — which is how
        a per-run tally (ChunkPipeline's) stays correct when concurrent
        pipelines share the process-global cache: diffing the shared
        ``stats()`` around the call would absorb the other threads'
        resolutions into this caller's numbers."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                self.hits += 1
        if ent is not None:
            if on_outcome is not None:
                on_outcome("hit")
            instant("pipeline.cache_hit", cat="pipeline",
                    key=_short_key(key))
            return ent.compiled
        # miss: compile OUTSIDE the lock (compiles are seconds; two
        # threads racing the same key is one redundant compile, last
        # writer wins — same answer either way)
        compiled, from_disk = self._load_from_disk(key)
        if compiled is None:
            with span("pipeline.compile", cat="pipeline",
                      key=_short_key(key)):
                compiled = build().lower(*args).compile()
            self._store_to_disk(key, compiled)
        with self._lock:
            self.misses += 1
            if from_disk:
                self.disk_hits += 1
            else:
                self.retraces += 1
            self._entries[key] = _Entry(compiled, from_disk)
            self._entries.move_to_end(key)
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
        if on_outcome is not None:
            on_outcome("disk" if from_disk else "compile")
        return compiled

    # ---- optional cross-process persistence ----
    def _disk_path(self, key: Tuple) -> Optional[str]:
        if not self.persist_dir:
            return None
        h = hashlib.sha256(repr(key).encode()).hexdigest()[:24]
        return os.path.join(self.persist_dir, f"program-{h}.bin")

    def _load_from_disk(self, key: Tuple):
        path = self._disk_path(key)
        if path is None or not os.path.exists(path):
            return None, False
        try:
            import pickle
            from jax.experimental import serialize_executable as _se
            with open(path, "rb") as fh:
                payload, in_tree, out_tree = pickle.load(fh)
            compiled = _se.deserialize_and_load(payload, in_tree, out_tree)
            return compiled, True
        except Exception as exc:
            self._warn_disk("load", exc)
            return None, False

    def _store_to_disk(self, key: Tuple, compiled) -> None:
        path = self._disk_path(key)
        if path is None:
            return
        try:
            import pickle
            from jax.experimental import serialize_executable as _se
            payload, in_tree, out_tree = _se.serialize(compiled)
            os.makedirs(self.persist_dir, exist_ok=True)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "wb") as fh:
                pickle.dump((payload, in_tree, out_tree), fh)
            os.replace(tmp, path)
            with self._lock:
                self.disk_stores += 1
        except Exception as exc:
            self._warn_disk("store", exc)

    def _warn_disk(self, what: str, exc: BaseException) -> None:
        if not self._disk_warned:
            self._disk_warned = True
            warnings.warn(
                f"program cache disk {what} under {self.persist_dir!r} "
                f"unavailable ({type(exc).__name__}: {exc}); continuing "
                f"in-memory only", RuntimeWarning)


def _short_key(key: Tuple) -> str:
    return hashlib.sha256(repr(key).encode()).hexdigest()[:10]


_GLOBAL: Optional[ProgramCache] = None
_GLOBAL_LOCK = threading.Lock()


def program_cache() -> ProgramCache:
    """The process-global cache: repeated jobs in one process re-trace
    nothing (the warm-re-run contract)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ProgramCache()
        return _GLOBAL
