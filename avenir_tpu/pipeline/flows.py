"""Prebuilt fused flows on the pipeline compiler.

:class:`PredictDriftFlow` is the combined ``predict + driftScore`` core:
per window chunk, ONE compiled program runs the whole ensemble vote AND
the drift-monitor bin counting, with the predicted classes flowing
device-to-device into the monitor's class row (the unfused pair pays a
predict launch, a host label decode/re-encode hop, and an absorb launch
per window).  Outputs per window: the vote vector (decoded to labels on
host for the prediction part file) and the (R, B) window count matrix
(scored by the caller's :class:`~avenir_tpu.monitor.accumulator.
StreamDriftMonitor` through ``close_counts`` — the identical
scoring/decay/policy path as the unfused job, so reports are
bit-identical).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.tracing import fetch
from .cache import mesh_fingerprint, schema_fingerprint
from .compiler import ChunkPipeline, Stage


def _vote_kernel(carry, consts, inputs, upstream):
    """The ensemble vote as a pipeline stage: models/forest's exact
    fused vote body (one predicate-semantics implementation everywhere),
    all predicate tensors arriving as runtime arguments."""
    from ..models.forest import _ensemble_vote_body
    votes = _ensemble_vote_body(
        inputs["vals"], inputs["codes"], consts["lo"], consts["hi"],
        consts["num_r"], consts["cat_m"], consts["cat_r"],
        consts["cls_oh"], consts["wvec"], consts["min_odds"])
    return carry, {"votes": votes}


def _make_absorb_kernel(b_max: int):
    """Monitor-absorb stage kernel: splice the UPSTREAM votes into the
    class row of the host-encoded monitor codes (vote index -> class-row
    bin through a LUT argument — the device twin of
    ``Baseline.class_codes_for_labels``), then count every row's bins in
    one contraction.  ``b_max`` is static (tagged into the stage
    version so the program cache keys on it)."""
    def kernel(carry, consts, inputs, upstream):
        import jax.numpy as jnp
        from ..ops.histogram import feature_bin_counts
        votes = upstream["predict.votes"]                       # (n,)
        cls_bin = jnp.take(consts["vote_lut"],
                           jnp.clip(votes, 0,
                                    consts["vote_lut"].shape[0] - 1))
        codes = jnp.where(consts["class_col"][None, :],
                          cls_bin[:, None], inputs["mon_codes"])
        counts = feature_bin_counts(codes, b_max, inputs["mask"] > 0)
        return carry, {"counts": counts}
    return kernel


class PredictDriftFlow:
    """Fused (ensemble predict + drift-window absorb) over window-sized
    chunks.

    Eligibility mirrors the batch predict path's device gate exactly
    (``EnsembleModel.device_inputs`` semantics): the ensemble must stack
    (no degenerate member, f32-exact bounds, integer vote weights) and
    each window's values must round-trip float32.  ``run_window``
    returns None when a window fails the gate — the caller falls back to
    the unfused path for that window; results are identical either way
    (same vote kernel, same count arithmetic), only the launch count
    differs.

    Every window pads (mask-guarded, zero rows) to ``window_rows`` so
    the WHOLE stream — tail window included — runs one compiled
    program."""

    def __init__(self, ensemble, baseline, schema, window_rows: int,
                 ctx=None, cache=None):
        import jax.numpy as jnp
        from ..parallel.mesh import runtime_context
        self.ens = ensemble
        self.baseline = baseline
        self.window_rows = int(window_rows)
        self.ctx = ctx or runtime_context()
        # one padded shape serves every window (tail included), rounded
        # up to the mesh row alignment so the row sharding applies
        align = max(self.ctx.n_devices, 1)
        self._padded_rows = self.window_rows + (-self.window_rows) % align
        self.eligible = ensemble._stacked is not None
        self.pl: Optional[ChunkPipeline] = None
        if not self.eligible:
            return
        *consts, wvec, _kernel = ensemble._stacked
        lo, hi, num_r, cat_m, cat_r, cls_oh = consts
        # vote index -> class-row bin through THE shared label encoding
        # (serving hook, driftMonitor, and this flow must all bin a
        # predicted label identically); the trailing entry is the
        # min-odds veto — None on the wire — which the shared mapping
        # sends to the unknown bin
        lut = baseline.class_codes_for_labels(
            list(ensemble.classes) + [None])
        class_col = np.zeros((len(baseline.specs),), dtype=bool)
        class_col[baseline.class_row] = True
        b_max = int(baseline.n_bins_max)
        predict = Stage(
            name="predict", kernel=_vote_kernel, version="1",
            consts={"lo": lo, "hi": hi, "num_r": num_r, "cat_m": cat_m,
                    "cat_r": cat_r, "cls_oh": cls_oh, "wvec": wvec,
                    "min_odds": jnp.float32(ensemble.min_odds_ratio)},
            returns=("votes",))
        absorb = Stage(
            name="monitor", kernel=_make_absorb_kernel(b_max),
            version=f"1:b{b_max}",
            consts={"vote_lut": jnp.asarray(lut),
                    "class_col": jnp.asarray(class_col)},
            returns=("counts",))
        self.pl = ChunkPipeline(
            [predict, absorb], ctx=self.ctx,
            schema_fp=schema_fingerprint(schema),
            mesh_fp=mesh_fingerprint(self.ctx), cache=cache,
            name="predict-drift")

    def run_window(self, table
                   ) -> Optional[Tuple[List[Optional[str]], np.ndarray]]:
        """One fused window: (decoded labels, float64 (R, B) window
        counts), or None when this window is not device-eligible.
        Counts are integer-exact f32 sums — identical to the unfused
        accumulator's bucketed absorb."""
        if self.pl is None or table.n_rows == 0 \
                or table.n_rows > self.window_rows:
            return None
        from ..monitor.baseline import encode_monitor_codes, \
            resolve_spec_bounds
        m0 = self.ens.models[0].matrix
        vals, codes = m0.feature_arrays(table)
        if not m0._f32_safe(vals):
            return None
        n = table.n_rows
        pad = self._padded_rows - n
        resolve_spec_bounds(self.baseline.specs, table)
        mon = encode_monitor_codes(table, self.baseline.specs)
        mask = np.zeros((self._padded_rows,), dtype=np.float32)
        mask[:n] = 1.0
        host = {"vals": _pad_rows(vals.astype(np.float32), pad),
                "codes": _pad_rows(codes, pad),
                "mon_codes": _pad_rows(mon, pad),
                "mask": mask}
        outs = self.pl.run_chunk(self.pl.upload(host))
        votes = fetch(outs["predict.votes"])[:n]
        counts = fetch(outs["monitor.counts"], dtype=np.float64)
        return list(self.ens._lut[votes]), counts

    def export(self, counters) -> None:
        if self.pl is not None:
            self.pl.export(counters)

    def run_stats(self) -> Dict[str, int]:
        return self.pl.run_stats() if self.pl is not None else {}


def _pad_rows(a: np.ndarray, pad: int) -> np.ndarray:
    """Zero-pad along axis 0 (mask-guarded downstream)."""
    if pad <= 0:
        return a
    return np.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
