"""The pipeline compiler: compose per-chunk stages into ONE jitted XLA
program with device-resident intermediates (Flare's whole-pipeline native
compilation, PAPERS.md, applied to this framework's chunk streams).

A job declares an ordered list of :class:`Stage`\\ s over a chunk stream
(``encode -> transform -> model-update -> metrics -> monitor-absorb``);
:class:`ChunkPipeline` composes their kernels into one traced function

    fused(carries, consts, inputs) -> (new_carries, returns)

jitted with the carry tuple DONATED (every iterative accumulator —
baseline bin counts, window counts — updates its HBM buffer in place,
PR 5's donation discipline), lowered/compiled once per argument
signature through the process-global :class:`~.cache.ProgramCache`, and
dispatched as ONE launch per chunk.  Stage outputs flow device-to-device
inside the program (a later stage reads an earlier stage's outputs from
the ``upstream`` dict without any host hop); only the keys a stage
declares in ``returns`` leave the program, and they come back as device
arrays — the caller decides what (if anything) to read back.

Kernels must be PURE functions of their arguments: no captured arrays.
Stage constants (split thresholds, ensemble predicate tensors, vote
LUTs) are passed as runtime arguments every chunk — which is what lets
two jobs with the same stage graph + schema + shapes share one compiled
executable even when the learned values differ (the Execution Templates
split between staged program and parameters).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..telemetry import span
from ..utils.tracing import note_dispatch
from .cache import ProgramCache, _arg_signature, program_cache

PIPELINE_SITE = "pipeline.chunk"
# the online learning plane's fused serve+learn window runs through the
# same compiler but pins its OWN dispatch site (one `Dispatches` ledger
# row per served window) and span name — ISSUE 19
ONLINE_SITE = "online.window"


@dataclass
class Stage:
    """One stage of a fused per-chunk program.

    ``kernel(carry, consts, inputs, upstream) -> (carry, outputs)``:
      * ``carry``    — this stage's donated iterative state (pytree; ``()``
        for stateless stages), threaded chunk to chunk on device;
      * ``consts``   — this stage's device-resident constants (dict),
        uploaded once and passed as arguments every chunk;
      * ``inputs``   — the MERGED per-chunk input dict (all stages');
      * ``upstream`` — earlier stages' outputs, keyed ``"<stage>.<out>"``
        (device-to-device dataflow — no host hop between stages).

    ``prepare(block) -> dict`` is the stage's host-side encode, run on
    the staging thread; the driver pads/uploads what it returns.
    ``returns`` names the outputs the fused program hands back per chunk
    (still device arrays).  ``finish(final_carry)`` receives the carry
    after the stream ends (e.g. to install accumulated baseline counts
    back into their builder).  ``version`` bumps the stage's cache
    fingerprint when its kernel logic changes."""

    name: str
    kernel: Callable
    version: str = "1"
    prepare: Optional[Callable] = None
    carry_init: Optional[Callable[[], Any]] = None
    consts: Dict[str, Any] = dc_field(default_factory=dict)
    returns: Tuple[str, ...] = ()
    finish: Optional[Callable[[Any], None]] = None

    @property
    def fingerprint(self) -> str:
        return f"{self.name}:{self.version}"


class ChunkPipeline:
    """Drive a stage list over a chunk stream as one cached XLA program
    per chunk.

    The driver half (host prepare, padding, upload threading) stays with
    the caller — streaming trains already own a staging discipline
    (``core.table.stage_chunks``); this class owns the fused program:
    carry management, the ProgramCache key, the single dispatch, and the
    per-run hit/miss tallies the acceptance counters read."""

    def __init__(self, stages: List[Stage], ctx=None,
                 schema_fp: str = "", mesh_fp: str = "",
                 cache: Optional[ProgramCache] = None,
                 name: str = "pipeline", site: str = PIPELINE_SITE):
        if not stages:
            raise ValueError("ChunkPipeline needs at least one stage")
        names = [s.name for s in stages]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate stage names: {names}")
        from ..parallel.mesh import runtime_context
        from .cache import mesh_fingerprint
        self.stages = list(stages)
        self.ctx = ctx or runtime_context()
        self.name = name
        if site not in (PIPELINE_SITE, ONLINE_SITE):
            raise ValueError(f"unknown dispatch site {site!r}")
        self.site = site
        self.schema_fp = schema_fp
        self.mesh_fp = mesh_fp or mesh_fingerprint(self.ctx)
        self.cache = cache if cache is not None else program_cache()
        self.graph_fp = "|".join(s.fingerprint for s in self.stages)
        self._carries = tuple(
            s.carry_init() if s.carry_init is not None else ()
            for s in self.stages)
        self._consts = {s.name: dict(s.consts or {}) for s in self.stages}
        self._chunks = 0
        # per-RUN tallies (the process-global cache accumulates forever;
        # a warm re-run's "0 retraces" claim needs this run's view)
        self.hits = 0
        self.misses = 0
        self.retraces = 0
        self._finished = False

    # ---- host side ----
    def prepare(self, block) -> Dict[str, np.ndarray]:
        """Merged host-encode of one block across all stages (staging
        thread).  Colliding keys are refused — stages share inputs by
        having ONE stage produce them."""
        out: Dict[str, np.ndarray] = {}
        for s in self.stages:
            if s.prepare is None:
                continue
            d = s.prepare(block) or {}
            dup = set(d) & set(out)
            if dup:
                raise ValueError(f"stage {s.name!r} re-produces input "
                                 f"keys {sorted(dup)}")
            out.update(d)
        return out

    def upload(self, host_inputs: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Row-sharded device_put of every prepared input (staging
        thread; H2D bytes land in the ledger via the mesh helpers)."""
        return {k: self.ctx.shard_rows_streamed(v)
                for k, v in host_inputs.items()}

    # ---- the fused program ----
    def _build_fused(self):
        stages = self.stages

        def fused(carries, consts, inputs):
            upstream: Dict[str, Any] = {}
            new_carries = []
            for st, c in zip(stages, carries):
                nc, outs = st.kernel(c, consts.get(st.name, {}), inputs,
                                     upstream)
                new_carries.append(nc)
                for k, v in (outs or {}).items():
                    upstream[f"{st.name}.{k}"] = v
            rets = {f"{st.name}.{r}": upstream[f"{st.name}.{r}"]
                    for st in stages for r in st.returns}
            return tuple(new_carries), rets

        import jax
        return jax.jit(fused, donate_argnums=(0,))

    def _key(self, inputs) -> Tuple:
        # the backend axis (TPU_NOTES §24): stage kernels may branch on
        # the resolved kernel backend at trace time (e.g. the baseline
        # absorb's pallas twin), so the key must miss when the knob
        # changes — resolved per call, not cached at construction, so a
        # force_backend scope around a running pipeline is honored
        from ..ops.pallas.dispatch import resolve_backend
        return ("chunk-pipeline", self.graph_fp, self.schema_fp,
                self.mesh_fp,
                resolve_backend(self.ctx.device_platform,
                                self.ctx.n_devices),
                _arg_signature(self._carries),
                _arg_signature(self._consts),
                _arg_signature(inputs))

    def _tally(self, outcome: str) -> None:
        """Per-RUN cache accounting, fed this call's own resolution by
        the cache (never a delta of the shared process-global stats —
        concurrent pipelines would absorb each other's compiles and a
        warm shard could report Retraces>0)."""
        if outcome == "hit":
            self.hits += 1
        else:
            self.misses += 1
            if outcome == "compile":
                self.retraces += 1

    def run_chunk(self, inputs: Dict[str, Any]) -> Dict[str, Any]:
        """ONE dispatch: every stage advances on this chunk inside one
        compiled program; returns the declared outputs as device arrays
        (reading them back — if at all — is the caller's single stacked
        readback)."""
        if self._finished:
            raise RuntimeError("pipeline already finalized")
        key = self._key(inputs)
        compiled = self.cache.get_or_compile(key, self._build_fused,
                                             (self._carries, self._consts,
                                              inputs),
                                             on_outcome=self._tally)
        note_dispatch(1, site=self.site)
        # literal span names per site — the §27 taxonomy drift guard
        # scans call-site literals, so each site spells its own
        if self.site == ONLINE_SITE:
            cm = span("online.window", cat="online", chunk=self._chunks,
                      stages=len(self.stages))
        else:
            cm = span("pipeline.chunk", cat="pipeline", chunk=self._chunks,
                      stages=len(self.stages))
        with cm:
            self._carries, rets = compiled(self._carries, self._consts,
                                           inputs)
        self._chunks += 1
        return rets

    def finalize(self) -> None:
        """End of stream: hand each stage its final carry (idempotent)."""
        if self._finished:
            return
        self._finished = True
        for st, c in zip(self.stages, self._carries):
            if st.finish is not None:
                st.finish(c)

    # ---- carry access (the online plane's snapshot/restore hooks) ----
    @property
    def carries(self) -> Tuple[Any, ...]:
        """The per-stage carry tuple as it stands (device arrays)."""
        return self._carries

    def install_carries(self, carries: Tuple[Any, ...]) -> None:
        """Replace every stage's carry (snapshot restore / rollback).
        The replacement must match the current signature leaf for leaf —
        a mismatch would silently retrace, so it is refused here."""
        carries = tuple(carries)
        if len(carries) != len(self.stages):
            raise ValueError(f"expected {len(self.stages)} carries, "
                             f"got {len(carries)}")
        if _arg_signature(carries) != _arg_signature(self._carries):
            raise ValueError("carry signature mismatch: restored state "
                             "does not match the running pipeline's "
                             "shapes/dtypes")
        self._carries = carries

    # ---- accounting ----
    @property
    def chunks(self) -> int:
        return self._chunks

    def run_stats(self) -> Dict[str, int]:
        return {"chunks": self._chunks, "hits": self.hits,
                "misses": self.misses, "retraces": self.retraces}

    def export(self, counters, group: str = "ProgramCache") -> None:
        """Per-run cache tallies into the job Counters channel: a warm
        re-run of an identical job shows ``Retraces`` 0 / ``Hits`` ==
        chunk-key resolutions — THE acceptance counter."""
        counters.update_group(group, {
            "Chunks": self._chunks, "Hits": self.hits,
            "Misses": self.misses, "Retraces": self.retraces})
