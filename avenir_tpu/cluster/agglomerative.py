"""Graph-based agglomerative clustering over a precomputed entity-distance
store.

Reference: cluster/AgglomerativeGraphical.java (map-only greedy pass: each
entity joins the existing cluster that maximizes average edge weight, or seeds
a new cluster, :57-81) + cluster/EdgeWeightedCluster.java (incremental
average-edge-weight update, :33-55) + util/EntityDistanceMapFileAccessor.java
(Hadoop MapFile of per-entity distance lists, :42-89).

The algorithm is inherently sequential/greedy (cluster membership of entity i
depends on entities 0..i-1), so it stays host-side; the expensive part — the
all-pairs distances the store holds — is produced on-device by
ops.distance.DistanceComputer.  The store replaces the MapFile with a plain
dict keyed by entity id, serializable to the same ``key<d>ent<d>dist...`` text
lines.
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np


class EntityDistanceStore:
    """Random-access per-entity distance lists (MapFile equivalent)."""

    def __init__(self, data: Optional[Dict[str, Dict[str, float]]] = None):
        self.data: Dict[str, Dict[str, float]] = data or {}

    # ---- construction ----
    @classmethod
    def from_lines(cls, lines: Sequence[str], delim: str = ","
                   ) -> "EntityDistanceStore":
        """Each line: ``entity,other1,dist1,other2,dist2,...`` (the write
        format of EntityDistanceMapFileAccessor.write/read)."""
        data: Dict[str, Dict[str, float]] = {}
        for line in lines:
            line = line.strip()
            if not line:
                continue
            parts = line.split(delim)
            ent, rest = parts[0], parts[1:]
            data[ent] = {rest[i]: float(rest[i + 1])
                         for i in range(0, len(rest) - 1, 2)}
        return cls(data)

    @classmethod
    def from_matrix(cls, ids: Sequence[str], dist: np.ndarray
                    ) -> "EntityDistanceStore":
        data = {ids[i]: {ids[j]: float(dist[i, j])
                         for j in range(len(ids)) if j != i}
                for i in range(len(ids))}
        return cls(data)

    def to_lines(self, delim: str = ",") -> List[str]:
        lines = []
        for ent in sorted(self.data):
            flat: List[str] = [ent]
            for other, d in self.data[ent].items():
                flat += [other, f"{d:.6f}"]
            lines.append(delim.join(flat))
        return lines

    def read(self, key: str) -> Dict[str, float]:
        return self.data.get(key, {})


class EdgeWeightedCluster:
    """Reference cluster/EdgeWeightedCluster.java: running average edge weight
    over the clique induced by members; distances convert to weights as
    (distScale - dist) when the store holds distances."""

    def __init__(self, dist_scale: Optional[float] = None):
        self.id = uuid.uuid4().hex
        self.members: List[str] = []
        self.av_edge_weight = 0.0
        self.dist_scale = dist_scale

    def _weight(self, dist: float) -> float:
        return self.dist_scale - dist if self.dist_scale is not None else dist

    def try_membership(self, entity: str, store: EntityDistanceStore) -> float:
        """Average edge weight if ``entity`` were added (reference
        EdgeWeightedCluster.java:33-55)."""
        weight_sum = 0.0
        for member in self.members:
            d = store.read(member).get(entity)
            if d is None:
                d = store.read(entity).get(member)
            if d is not None:
                weight_sum += self._weight(d)
        k = len(self.members)
        num_edges = (k * (k - 1)) // 2
        return (self.av_edge_weight * num_edges + weight_sum) / (num_edges + k) \
            if (num_edges + k) > 0 else 0.0

    def add(self, entity: str, new_av_edge_weight: float) -> None:
        self.members.append(entity)
        self.av_edge_weight = new_av_edge_weight

    def to_line(self, delim: str = ",") -> str:
        return delim.join([self.id] + self.members +
                          [f"{self.av_edge_weight:.6f}"])


def agglomerative_cluster(entity_ids: Sequence[str],
                          store: EntityDistanceStore,
                          min_av_edge_weight: float,
                          dist_scale: Optional[float] = None
                          ) -> List[EdgeWeightedCluster]:
    """Greedy single pass (reference AgglomerativeGraphical.GraphMapper.map):
    join the best-improving cluster if it clears the threshold, else seed a
    new singleton cluster (the reference seeds an *empty* cluster and drops
    the entity — an apparent bug we do not reproduce)."""
    clusters: List[EdgeWeightedCluster] = []
    for ent in entity_ids:
        best, best_w = None, -np.inf
        for c in clusters:
            w = c.try_membership(ent, store)
            if w > best_w:
                best_w, best = w, c
        if best is not None and best_w > min_av_edge_weight:
            best.add(ent, best_w)
        else:
            c = EdgeWeightedCluster(dist_scale)
            c.add(ent, 0.0)
            clusters.append(c)
    return clusters
