"""K-means clustering over mixed-type records, multiple cluster groups in
parallel.

Reference: cluster/KmeansCluster.java — one MR pass per Lloyd iteration; the
mapper assigns every record to the nearest centroid of every *active* cluster
group via chombo ``InterRecordDistance`` (mixed numeric/categorical distance,
cluster/KmeansCluster.java:116,162), the reducer recomputes each centroid
(numeric attrs -> mean, categorical attrs -> histogram mode,
cluster/KmeansCluster.java:262-282) and emits
``group,centroid...,movement,status,avError,count`` (:284-294).  Cluster-file
state between iterations is the checkpoint (``ClusterGroup`` re-reads it and
marks clusters stopped once movement < threshold, cluster/ClusterGroup.java:17-29).

TPU design: one jitted pass per iteration.  Rows are encoded once into a
range-normalized numeric matrix + categorical code matrix; per group the
(n, K) distance matrix is one broadcastified reduction, assignment is argmin,
and the centroid update is two one-hot contractions (counts/sums on the MXU):
``assign_onehot.T @ num_values`` for numeric means and
``assign_onehot.T @ cat_onehot`` for per-attribute histograms whose argmax is
the mode.  Groups are stacked and vmapped so many cluster groups (the
reference's parallelism axis) run in one program; rows shard over the mesh
with a ``psum`` over per-shard partial sums.

Note: the reference reducer divides numeric sums by ``count`` accumulated per
*field* (cluster/KmeansCluster.java:244-258 increments once per field per
record), an off-by-recSize bug; we implement the intended per-record mean.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.schema import FeatureSchema
from ..core.table import ColumnarTable
from ..core.artifacts import ArtifactStore

NULL = "null"
STATUS_ACTIVE = "active"
STATUS_STOPPED = "stopped"


# ---------------------------------------------------------------------------
# host-side state: cluster file round-trip (the checkpoint contract)
# ---------------------------------------------------------------------------

@dataclass
class Cluster:
    """One centroid: full record-width string items (non-facet attrs NULL)."""
    items: List[str]
    movement: float
    status: str
    av_error: float = 0.0
    count: int = 0


@dataclass
class ClusterGroup:
    """Reference cluster/ClusterGroup.java: clusters become stopped when their
    movement drops below the threshold; the group is active while any cluster
    still is."""
    name: str
    clusters: List[Cluster]
    movement_threshold: float

    def apply_threshold(self) -> None:
        for c in self.clusters:
            if c.movement < self.movement_threshold:
                c.status = STATUS_STOPPED

    @property
    def active(self) -> bool:
        return any(c.status == STATUS_ACTIVE for c in self.clusters)


def parse_cluster_lines(lines: Sequence[str], num_attributes: int,
                        movement_threshold: float, delim: str = ","
                        ) -> List[ClusterGroup]:
    """Parse ``group,<numAttributes centroid items>,movement,status[,avError,count]``
    (format of cluster/KmeansCluster.java:123-144 in, :284-294 out)."""
    groups: Dict[str, ClusterGroup] = {}
    order: List[str] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        parts = line.split(delim)
        name = parts[0]
        items = parts[1:1 + num_attributes]
        rest = parts[1 + num_attributes:]
        movement = float(rest[0]) if rest else float("inf")
        status = rest[1] if len(rest) > 1 else STATUS_ACTIVE
        av_error = float(rest[2]) if len(rest) > 2 else 0.0
        count = int(rest[3]) if len(rest) > 3 else 0
        if name not in groups:
            groups[name] = ClusterGroup(name, [], movement_threshold)
            order.append(name)
        groups[name].clusters.append(Cluster(items, movement, status,
                                             av_error, count))
    out = [groups[n] for n in order]
    for g in out:
        g.apply_threshold()
    return out


def format_cluster_lines(groups: Sequence[ClusterGroup], delim: str = ",",
                         precision: int = 3) -> List[str]:
    lines = []
    for g in groups:
        for c in g.clusters:
            lines.append(delim.join(
                [g.name] + list(c.items) +
                [f"{c.movement:.{precision}f}", c.status,
                 f"{c.av_error:.{precision}f}", str(c.count)]))
    return lines


# ---------------------------------------------------------------------------
# the jitted Lloyd iteration
# ---------------------------------------------------------------------------

class KMeansEngine:
    """Mixed-type Lloyd's updates for stacked cluster groups.

    Distance semantics follow ops.distance.DistanceComputer (chombo
    InterRecordDistance): numeric attrs contribute ((a-b)/range)^2, categorical
    attrs 0/1 mismatch; record distance = sqrt(mean over facet attrs).
    """

    def __init__(self, schema: FeatureSchema, attr_ordinals: Sequence[int],
                 metric: str = "euclidean"):
        self.schema = schema
        self.attr_ordinals = list(attr_ordinals)
        fields = [schema.find_field_by_ordinal(o) for o in self.attr_ordinals]
        self.num_fields = [f for f in fields if f.is_numeric]
        self.cat_fields = [f for f in fields if f.is_categorical]
        bad = [f.ordinal for f in fields
               if not (f.is_numeric or f.is_categorical)]
        if bad:
            raise ValueError(f"only numeric/categorical attrs allowed, got "
                             f"ordinals {bad}")
        self.n_attrs = len(self.num_fields) + len(self.cat_fields)
        self.metric = metric
        self.ranges = np.array(
            [max(float(f.max) - float(f.min), 1e-12)
             if f.max is not None and f.min is not None else 1.0
             for f in self.num_fields], dtype=np.float32)
        self.cards = [len(f.cardinality or []) for f in self.cat_fields]
        self._partials = jax.jit(jax.vmap(self._partials_impl,
                                          in_axes=(None, None, None,
                                                   0, 0, 0)))
        self._finalize = jax.jit(jax.vmap(self._finalize_impl))

    # ---- encoding -------------------------------------------------------
    def encode_table(self, table: ColumnarTable) -> Tuple[np.ndarray, np.ndarray]:
        n = table.n_rows
        num = (np.stack([table.columns[f.ordinal] for f in self.num_fields],
                        axis=1).astype(np.float32)
               if self.num_fields else np.zeros((n, 0), np.float32))
        cat = (np.stack([table.columns[f.ordinal] for f in self.cat_fields],
                        axis=1).astype(np.int32)
               if self.cat_fields else np.zeros((n, 0), np.int32))
        return num, cat

    def encode_groups(self, groups: Sequence[ClusterGroup]
                      ) -> Dict[str, np.ndarray]:
        G = len(groups)
        K = max((len(g.clusters) for g in groups), default=1)
        cent_num = np.zeros((G, K, len(self.num_fields)), np.float32)
        cent_cat = np.zeros((G, K, len(self.cat_fields)), np.int32)
        valid = np.zeros((G, K), bool)
        for gi, g in enumerate(groups):
            for ki, c in enumerate(g.clusters):
                valid[gi, ki] = True
                for fi, f in enumerate(self.num_fields):
                    cent_num[gi, ki, fi] = float(c.items[f.ordinal])
                for fi, f in enumerate(self.cat_fields):
                    cent_cat[gi, ki, fi] = f.cat_code(c.items[f.ordinal])
        return {"cent_num": cent_num, "cent_cat": cent_cat, "valid": valid}

    # ---- kernel ---------------------------------------------------------
    def _distances(self, num, cat, cent_num, cent_cat, valid):
        """num (n,Fn) raw, cat (n,Fc) codes; centroids (K,Fn)/(K,Fc).
        Returns (n,K) distances with invalid clusters at +inf."""
        ranges = jnp.asarray(self.ranges)
        nn = num / ranges if self.num_fields else num
        cn = cent_num / ranges if self.num_fields else cent_num
        sq = ((nn[:, None, :] - cn[None, :, :]) ** 2).sum(-1)      # (n,K)
        mismatch = (cat[:, None, :] != cent_cat[None, :, :]).sum(-1)
        total = sq + mismatch.astype(jnp.float32)
        mean = total / max(self.n_attrs, 1)
        d = jnp.sqrt(jnp.maximum(mean, 0.0))
        return jnp.where(valid[None, :], d, jnp.inf)

    def _partials_impl(self, num, cat, row_valid, cent_num, cent_cat, valid):
        """Per-shard Lloyd sums for one group: assignment counts, numeric
        sums, categorical histograms, squared-distance sums.  These are the
        job's ONLY row-dependent terms, and they are plain sums — under
        multi-host each process computes them over its local shard and an
        all-reduce makes them global (the reference reducer's shuffle,
        cluster/KmeansCluster.java:162)."""
        d = self._distances(num, cat, cent_num, cent_cat, valid)   # (n,K)
        assign = jnp.argmin(d, axis=1)
        K = cent_num.shape[0]
        onehot = jax.nn.one_hot(assign, K, dtype=jnp.float32)
        onehot = onehot * row_valid[:, None]
        counts = onehot.sum(0)                                     # (K,)
        sum_num = onehot.T @ num                                   # (K,Fn)
        # categorical histograms via one-hot contraction, padded to the
        # max cardinality so the partial is ONE dense array
        maxcard = max(self.cards, default=0)
        hists = []
        for fi, card in enumerate(self.cards):
            codes_oh = jax.nn.one_hot(cat[:, fi], card, dtype=jnp.float32)
            h = onehot.T @ codes_oh                                # (K,card)
            hists.append(jnp.pad(h, ((0, 0), (0, maxcard - card))))
        cat_hist = (jnp.stack(hists, axis=1) if hists
                    else jnp.zeros((K, 0, 0), jnp.float32))
        dmin = jnp.min(jnp.where(valid[None, :], d, jnp.inf), axis=1)
        sum_sq = onehot.T @ (dmin * dmin * row_valid)
        return counts, sum_num, cat_hist, sum_sq

    def _finalize_impl(self, counts, sum_num, cat_hist, sum_sq,
                       cent_num, cent_cat):
        """Global sums -> new centroids + movement + stats for one group.
        Pure function of the (all-reduced) partials: every process derives
        the identical model."""
        K = cent_num.shape[0]
        safe = jnp.maximum(counts, 1.0)
        new_num = sum_num / safe[:, None]                          # (K,Fn)
        new_cat_cols = []
        for fi, card in enumerate(self.cards):
            new_cat_cols.append(
                jnp.argmax(cat_hist[:, fi, :card], axis=1).astype(jnp.int32))
        new_cat = (jnp.stack(new_cat_cols, axis=1) if new_cat_cols
                   else jnp.zeros_like(cent_cat))
        # empty clusters keep their old centroid
        empty = counts < 0.5
        new_num = jnp.where(empty[:, None], cent_num, new_num)
        new_cat = jnp.where(empty[:, None], cent_cat, new_cat)
        av_error = sum_sq / safe
        # movement = distance(old centroid, new centroid), same semantics
        ranges = jnp.asarray(self.ranges)
        mv_sq = (((cent_num - new_num) / ranges) ** 2).sum(-1) \
            if self.num_fields else jnp.zeros(K)
        mv_cat = (cent_cat != new_cat).sum(-1).astype(jnp.float32)
        movement = jnp.sqrt((mv_sq + mv_cat) / max(self.n_attrs, 1))
        movement = jnp.where(empty, 0.0, movement)
        return new_num, new_cat, movement, av_error

    def iterate(self, num: np.ndarray, cat: np.ndarray, row_valid: np.ndarray,
                enc: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """One Lloyd update for all groups (vmapped over G): local partial
        sums -> cross-process all-reduce (identity single-process) ->
        finalize.  Centroids are bit-identical across processes because
        every process finalizes the same reduced sums."""
        from ..parallel.distributed import (all_reduce_host_array,
                                           is_multiprocess)
        counts, sum_num, cat_hist, sum_sq = self._partials(
            jnp.asarray(num), jnp.asarray(cat),
            jnp.asarray(row_valid, dtype=jnp.float32),
            jnp.asarray(enc["cent_num"]), jnp.asarray(enc["cent_cat"]),
            jnp.asarray(enc["valid"]))
        counts, sum_num, cat_hist, sum_sq = (
            np.asarray(x) for x in (counts, sum_num, cat_hist, sum_sq))
        if is_multiprocess():
            # ONE packed collective per Lloyd iteration, not four: this is
            # the training hot loop and each all-reduce is a full barrier
            parts = [counts, sum_num, cat_hist, sum_sq]
            flat = all_reduce_host_array(
                np.concatenate([p.ravel() for p in parts]))
            splits = np.cumsum([p.size for p in parts])[:-1]
            counts, sum_num, cat_hist, sum_sq = (
                seg.reshape(p.shape) for seg, p in
                zip(np.split(flat, splits), parts))
        new_num, new_cat, movement, av_error = self._finalize(
            jnp.asarray(counts), jnp.asarray(sum_num),
            jnp.asarray(cat_hist), jnp.asarray(sum_sq),
            jnp.asarray(enc["cent_num"]), jnp.asarray(enc["cent_cat"]))
        return {"cent_num": np.asarray(new_num), "cent_cat": np.asarray(new_cat),
                "movement": np.asarray(movement),
                "av_error": np.asarray(av_error),
                "counts": np.asarray(counts)}

    # ---- host-side round trip ------------------------------------------
    def update_groups(self, groups: Sequence[ClusterGroup],
                      res: Dict[str, np.ndarray],
                      active_idx: Sequence[int],
                      precision: int = 3) -> None:
        """Write kernel results back into the (full) group list; only groups
        listed in active_idx were part of the kernel batch."""
        for bi, gi in enumerate(active_idx):
            g = groups[gi]
            for ki, c in enumerate(g.clusters):
                items = [NULL] * self.schema.num_columns
                for fi, f in enumerate(self.num_fields):
                    v = float(res["cent_num"][bi, ki, fi])
                    items[f.ordinal] = (f"{v:.{precision}f}" if f.is_double
                                        else str(int(round(v))))
                for fi, f in enumerate(self.cat_fields):
                    code = int(res["cent_cat"][bi, ki, fi])
                    items[f.ordinal] = (f.cardinality or [NULL])[code]
                c.items = items
                c.movement = float(res["movement"][bi, ki])
                c.av_error = float(res["av_error"][bi, ki])
                c.count = int(res["counts"][bi, ki])
            g.apply_threshold()

    def assign(self, table: ColumnarTable, group: ClusterGroup) -> np.ndarray:
        """Nearest-cluster index per row for one group (prediction path)."""
        num, cat = self.encode_table(table)
        enc = self.encode_groups([group])
        d = self._distances(jnp.asarray(num), jnp.asarray(cat),
                            jnp.asarray(enc["cent_num"][0]),
                            jnp.asarray(enc["cent_cat"][0]),
                            jnp.asarray(enc["valid"][0]))
        return np.asarray(jnp.argmin(d, axis=1))


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def kmeans_one_pass(table: ColumnarTable, groups: List[ClusterGroup],
                    engine: KMeansEngine, precision: int = 3,
                    encoded: Optional[Tuple[np.ndarray, np.ndarray,
                                            np.ndarray]] = None) -> None:
    """One reference job run (= one MR pass): update every active group in
    place; stopped groups carry forward unchanged.  ``encoded`` lets driver
    loops hoist the loop-invariant row encoding/upload."""
    active_idx = [i for i, g in enumerate(groups) if g.active]
    if not active_idx:
        return
    if encoded is None:
        num, cat = engine.encode_table(table)
        row_valid = np.ones(table.n_rows, np.float32)
    else:
        num, cat, row_valid = encoded
    enc = engine.encode_groups([groups[i] for i in active_idx])
    res = engine.iterate(num, cat, row_valid, enc)
    engine.update_groups(groups, res, active_idx, precision)


def run_kmeans(table: ColumnarTable, groups: List[ClusterGroup],
               engine: KMeansEngine, max_iter: int = 100,
               store: Optional[ArtifactStore] = None,
               precision: int = 3) -> Tuple[List[ClusterGroup], int]:
    """Iterate to convergence (the reference's external driver loop re-running
    the job on the rotated cluster file).  If ``store`` is given, each
    iteration's cluster file is written as ``centroids_iter_<i>.csv`` plus the
    rolling ``centroids.csv`` — resuming = re-parsing the latest file."""
    num, cat = engine.encode_table(table)
    # upload the loop-invariant row data ONCE: iterate()'s jnp.asarray is
    # a no-op on an already-device array, so hoisting the device_put here
    # removes a full data transfer from every Lloyd iteration (the
    # dominant per-iteration cost on the tunneled link)
    encoded = (jnp.asarray(num), jnp.asarray(cat),
               jnp.asarray(np.ones(table.n_rows, np.float32)))
    it = 0
    for it in range(1, max_iter + 1):
        if not any(g.active for g in groups):
            it -= 1
            break
        kmeans_one_pass(table, groups, engine, precision, encoded=encoded)
        if store is not None:
            lines = format_cluster_lines(groups, precision=precision)
            store.write_lines(f"centroids_iter_{it}.csv", lines)
            store.write_lines("centroids.csv", lines)
    return groups, it


def init_groups(table: ColumnarTable, engine: KMeansEngine,
                group_sizes: Dict[str, int], movement_threshold: float,
                seed: Optional[int] = None) -> List[ClusterGroup]:
    """Random distinct-record initialization (the reference supplies the
    initial cluster file externally; this is the convenience path)."""
    rng = np.random.default_rng(seed)
    groups = []
    for name, k in group_sizes.items():
        picks = rng.choice(table.n_rows, size=k, replace=False)
        clusters = []
        for r in picks:
            items = [NULL] * table.schema.num_columns
            for f in engine.num_fields:
                v = float(table.columns[f.ordinal][r])
                items[f.ordinal] = (f"{v:.6f}" if f.is_double
                                    else str(int(round(v))))
            for f in engine.cat_fields:
                code = int(table.columns[f.ordinal][r])
                items[f.ordinal] = (f.cardinality or [NULL])[max(code, 0)]
            clusters.append(Cluster(items, float("inf"), STATUS_ACTIVE))
        groups.append(ClusterGroup(name, clusters, movement_threshold))
    return groups
