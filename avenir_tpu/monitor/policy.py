"""Threshold policy over drift reports: warn/alert levels, debounce,
structured alerts, and the serving guardrail actions.

Per statistic there is a warn and an alert threshold (defaults follow the
industry PSI bands: 0.1 warn / 0.25 alert, with matching bands for the
other divergences).  A level must hold for ``consecutive`` windows of the
same (window kind, scope, statistic) before its record emits — one noisy
window is not drift.  Emitted records are structured
(:class:`AlertRecord`), counted through the core/metrics.Counters
channel, logged through utils/tracing.get_logger, and optionally handed
to an action callback — the serving guardrails:

  * :func:`refresh_action` — re-probe the registry for a newer intact
    version (``PredictionService.refresh``): the retrain loop published
    a fix, pick it up.
  * :func:`degrade_action` — ``PredictionService.mark_degraded``: keep
    answering but flag the model so operators (and the counter dump)
    see it.
  * :func:`retrain_action` — hand the alert to a
    ``control.RetrainController`` (queue append, never an inline
    retrain): the confirmed-drift -> retrain -> validate -> swap loop.

Delayed-label model quality rides the same policy:
:class:`AccuracyTracker` folds (predicted, actual) label pairs through
``ConfusionMatrix.report_batch`` per window and reports the integer
accuracy percent as the ``accuracy`` statistic (inverted comparison —
LOW accuracy alerts).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.metrics import ConfusionMatrix, Counters
from ..utils.tracing import get_logger
from .drift import DriftReport, STATS

# PSI's classic 0.1/0.25 bands; the others scaled to comparable
# sensitivity on the same synthetic shifts (tests/test_monitor.py pins a
# mean-shifted numeric + reweighted categorical firing and a
# same-distribution stream staying quiet under these defaults)
DEFAULT_WARN = {"psi": 0.10, "kl": 0.10, "js": 0.02, "ks": 0.10,
                "chi2": 0.05}
DEFAULT_ALERT = {"psi": 0.25, "kl": 0.50, "js": 0.10, "ks": 0.25,
                 "chi2": 0.20}

WARN = "warn"
ALERT = "alert"
ACCURACY_STAT = "accuracy"


@dataclass
class AlertRecord:
    """One structured finding: a statistic held a level long enough."""
    window_index: int
    window_kind: str            # window | longterm | quality
    scope: str                  # feature name | __prediction__ | __model__
    stat: str
    value: float
    threshold: float
    level: str                  # warn | alert
    streak: int                 # consecutive windows at >= this level
    n_rows: int

    def to_json(self) -> str:
        return json.dumps(self.__dict__, sort_keys=True)


class DriftPolicy:
    """Stateful thresholding over a report stream."""

    def __init__(self, warn: Optional[Dict[str, float]] = None,
                 alert: Optional[Dict[str, float]] = None,
                 consecutive: int = 2,
                 counters: Optional[Counters] = None,
                 on_alert: Optional[Callable[[AlertRecord], None]] = None,
                 accuracy_warn: int = 0, accuracy_alert: int = 0,
                 debug_on: bool = False):
        if consecutive < 1:
            raise ValueError(f"consecutive must be >= 1, got {consecutive}")
        self.warn = dict(DEFAULT_WARN)
        self.warn.update(warn or {})
        self.alert = dict(DEFAULT_ALERT)
        self.alert.update(alert or {})
        self.consecutive = int(consecutive)
        self.counters = counters if counters is not None else Counters()
        self.on_alert = on_alert
        self.accuracy_warn = int(accuracy_warn)
        self.accuracy_alert = int(accuracy_alert)
        self._log = get_logger("avenir_tpu.monitor", debug_on)
        # (window_kind, scope, stat) -> consecutive counts per level
        self._warn_streak: Dict[Tuple[str, str, str], int] = {}
        self._alert_streak: Dict[Tuple[str, str, str], int] = {}
        self.alerts: List[AlertRecord] = []

    # ---- drift reports ----
    def observe(self, report: DriftReport) -> List[AlertRecord]:
        """Threshold every (row, statistic) of one report; returns the
        records that cleared debounce this window (also retained in
        ``self.alerts`` and counted)."""
        fired: List[AlertRecord] = []
        for row in report.rows:
            for stat in STATS:
                if not row.applicable(stat):
                    continue
                value = row.stats[stat]
                key = (report.kind, row.scope, stat)
                fired.extend(self._step(
                    key, value, value >= self.alert[stat],
                    value >= self.warn[stat],
                    report, self.warn[stat], self.alert[stat]))
        return fired

    # ---- delayed-label quality ----
    def observe_accuracy(self, window_index: int, accuracy: int,
                         n_rows: int = 0) -> List[AlertRecord]:
        """Inverted thresholding: accuracy BELOW the bar for
        ``consecutive`` windows fires.  Disabled until accuracy_warn /
        accuracy_alert are set (> 0)."""
        if self.accuracy_warn <= 0 and self.accuracy_alert <= 0:
            return []
        report = DriftReport(index=window_index, kind="quality",
                             n_rows=n_rows)
        key = ("quality", "__model__", ACCURACY_STAT)
        return self._step(
            key, float(accuracy),
            self.accuracy_alert > 0 and accuracy < self.accuracy_alert,
            self.accuracy_warn > 0 and accuracy < self.accuracy_warn,
            report, float(self.accuracy_warn), float(self.accuracy_alert))

    # ---- shared streak machinery ----
    def _step(self, key, value: float, is_alert: bool, is_warn: bool,
              report: DriftReport, warn_th: float, alert_th: float
              ) -> List[AlertRecord]:
        self._warn_streak[key] = self._warn_streak.get(key, 0) + 1 \
            if is_warn else 0
        self._alert_streak[key] = self._alert_streak.get(key, 0) + 1 \
            if is_alert else 0
        fired: List[AlertRecord] = []
        if self._alert_streak[key] >= self.consecutive:
            fired.append(self._emit(key, value, ALERT, alert_th,
                                    self._alert_streak[key], report))
        elif self._warn_streak[key] >= self.consecutive:
            fired.append(self._emit(key, value, WARN, warn_th,
                                    self._warn_streak[key], report))
        return fired

    def _emit(self, key, value: float, level: str, threshold: float,
              streak: int, report: DriftReport) -> AlertRecord:
        kind, scope, stat = key
        rec = AlertRecord(window_index=report.index, window_kind=kind,
                          scope=scope, stat=stat, value=float(value),
                          threshold=float(threshold), level=level,
                          streak=streak, n_rows=report.n_rows)
        self.alerts.append(rec)
        self.counters.increment(
            "DriftMonitor", "Alerts" if level == ALERT else "Warnings")
        log = self._log.warning if level == ALERT else self._log.info
        log("drift %s: %s %s=%.4g (threshold %.4g, %d consecutive "
            "windows)", level, scope, stat, value, threshold, streak)
        if level == ALERT and self.on_alert is not None:
            self.on_alert(rec)
        return rec


# --------------------------------------------------------------------------
# serving guardrail actions
# --------------------------------------------------------------------------

def refresh_action(service, counters: Optional[Counters] = None
                   ) -> Callable[[AlertRecord], None]:
    """On alert, re-probe the registry for a newer intact model version
    (hot-swap if one exists) — the 'a retrain already landed, pick it
    up' guardrail."""
    def act(rec: AlertRecord) -> None:
        swapped = service.refresh()
        if counters is not None:
            counters.increment("DriftMonitor", "RefreshProbes")
            if swapped:
                counters.increment("DriftMonitor", "RefreshSwaps")
    return act


def degrade_action(service, counters: Optional[Counters] = None
                   ) -> Callable[[AlertRecord], None]:
    """On alert, mark the serving model degraded (it keeps answering;
    operators and the counter dump see the flag).  ``service`` may be a
    single ``PredictionService`` or a ``ServingFleet`` (fleet-scope
    ``mark_degraded`` flags every worker; the PR 12 parking rules keep
    the last active worker serving)."""
    def act(rec: AlertRecord) -> None:
        service.mark_degraded(f"{rec.scope} {rec.stat}={rec.value:.4g} "
                              f">= {rec.threshold:.4g}")
        if counters is not None:
            counters.increment("DriftMonitor", "Degradations")
    return act


def retrain_action(controller, counters: Optional[Counters] = None
                   ) -> Callable[[AlertRecord], None]:
    """On alert, hand the record to the retrain controller's
    control-plane intake (``RetrainController.submit_alert``) — the
    policy -> controller wiring that closes the loop.  The handoff is a
    queue append: a retrain NEVER runs inline on the serving/monitor
    thread (the controller must stay off the data path; its own loop —
    ``run_pending``/``start()`` — picks the alert up)."""
    def act(rec: AlertRecord) -> None:
        controller.submit_alert(rec)
        if counters is not None:
            counters.increment("DriftMonitor", "RetrainRequests")
    return act


# --------------------------------------------------------------------------
# delayed-label accuracy
# --------------------------------------------------------------------------

class AccuracyTracker:
    """Windowed model-quality tracking from delayed labels.

    Outcomes arrive as (predicted label, actual label) pairs — possibly
    long after the prediction was served.  Every ``window`` outcomes the
    tracker folds the batch through ``ConfusionMatrix.report_batch``
    (vectorized, the reference's integer-percent semantics) and reports
    the window accuracy to the policy."""

    def __init__(self, pos_class: str, neg_class: str, policy: DriftPolicy,
                 window: int = 512):
        if window < 1:
            # record() drains by 'len(buffer) >= window'; zero would
            # spin forever on the first labeled batch
            raise ValueError(f"window must be >= 1, got {window}")
        self.pos_class = pos_class
        self.neg_class = neg_class
        self.policy = policy
        self.window = int(window)
        self._pred: List[str] = []
        self._actual: List[str] = []
        self._index = 0

    def record(self, pred_labels, actual_labels) -> List[AlertRecord]:
        self._pred.extend(pred_labels)
        self._actual.extend(actual_labels)
        fired: List[AlertRecord] = []
        while len(self._pred) >= self.window:
            fired.extend(self._close(self.window))
        return fired

    def close(self) -> List[AlertRecord]:
        """Score whatever partial window remains."""
        if not self._pred:
            return []
        return self._close(len(self._pred))

    def _close(self, n: int) -> List[AlertRecord]:
        pred = np.asarray(self._pred[:n])
        actual = np.asarray(self._actual[:n])
        del self._pred[:n], self._actual[:n]
        cm = ConfusionMatrix(self.neg_class, self.pos_class)
        cm.report_batch(pred == self.pos_class, actual == self.pos_class,
                        actual == self.neg_class)
        self.policy.counters.increment("DriftMonitor", "LabeledOutcomes", n)
        fired = self.policy.observe_accuracy(self._index, cm.accuracy(),
                                             n_rows=n)
        self._index += 1
        return fired
