"""Drift scoring: one jitted kernel, every monitored distribution at once.

A finalized window is a (R, B) count matrix in the same stacked layout as
the baseline (monitor/baseline.py): numeric features, categorical
features, and the prediction-class distribution as the last row.  Scoring
is therefore ONE vectorized device pass over the whole matrix — per-row
loops would launch R kernels per window and drown the actual math in
dispatch overhead (TPU_NOTES §17).

Statistics per row (all computed over the row's valid bins; the pad bins
out to B_max carry identical clamped values on both sides and contribute
exactly zero):

  * ``psi``  — population stability index, Σ (q̃-p̃)·ln(q̃/p̃) with the
    standard ε-floor (no renormalize): empty bins clamp to ``eps`` so
    the log stays finite, the industry PSI convention.
  * ``kl``   — KL(q̃ ‖ p̃), same ε-floored distributions.
  * ``js``   — Jensen–Shannon divergence (nats, bounded by ln 2).
  * ``ks``   — binned Kolmogorov–Smirnov statistic max|CDF_p - CDF_q|
    over the UNclamped distributions (meaningful for ordered bins:
    numeric rows only — the policy ignores it elsewhere).
  * ``chi2`` — chi-square DISTANCE Σ (q-p)²/p over the bins the
    baseline actually populated (the classic zero-expected-count
    exclusion: dividing a stray window token by the ε floor would turn
    ONE unknown value in a 2k-row window into an alert-level score;
    genuinely new-category mass still registers through psi/kl/js,
    which ε-floor instead of excluding).  This is the classic statistic
    divided by the window count, so thresholds do not scale with window
    size; the raw statistic is ``chi2 * n_window``.

Every statistic is pinned against a pure-numpy oracle in
tests/test_monitor.py, including empty-bin ε handling and
all-mass-in-one-bin extremes.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional

import numpy as np

from .baseline import Baseline, CLASS, NUMERIC, PREDICTION_SCOPE

STATS = ("psi", "kl", "js", "ks", "chi2")
DEFAULT_EPS = 1e-6

# which statistics the policy treats as meaningful per row kind: KS needs
# ordered bins; chi-square is the categorical/prior test of the reference
# literature (psi/kl/js apply everywhere)
STAT_KINDS = {
    "psi": ("numeric", "categorical", "class"),
    "kl": ("numeric", "categorical", "class"),
    "js": ("numeric", "categorical", "class"),
    "ks": ("numeric",),
    "chi2": ("categorical", "class"),
}


@dataclass
class RowScore:
    """One monitored row's drift scores for one window."""
    scope: str                  # feature name, or __prediction__
    kind: str                   # numeric | categorical | class
    stats: Dict[str, float]

    def applicable(self, stat: str) -> bool:
        return self.kind in STAT_KINDS[stat]


@dataclass
class DriftReport:
    """All rows of one scored window."""
    index: int
    kind: str                   # window | longterm
    n_rows: int
    rows: List[RowScore] = dc_field(default_factory=list)

    def row(self, scope: str) -> RowScore:
        for r in self.rows:
            if r.scope == scope:
                return r
        raise KeyError(f"no scored row {scope!r}")

    def max_stat(self, stat: str) -> float:
        vals = [r.stats[stat] for r in self.rows if r.applicable(stat)]
        return max(vals) if vals else 0.0


def _score_kernel(p, q_counts, valid, eps):
    """The traced core: (R,B) baseline probs + window counts -> (R,5).
    Also usable as the numpy oracle shape-for-shape (the tests run an
    independently written oracle, not this function)."""
    import jax.numpy as jnp
    totals = q_counts.sum(axis=1, keepdims=True)
    q = jnp.where(valid, q_counts / jnp.maximum(totals, 1.0), 0.0)
    # ε-floored twins for the log statistics; invalid bins pin both sides
    # to 1.0 so every term there is exactly (1-1)*log(1/1) = 0
    pc = jnp.where(valid, jnp.maximum(p, eps), 1.0)
    qc = jnp.where(valid, jnp.maximum(q, eps), 1.0)
    log_ratio = jnp.log(qc) - jnp.log(pc)
    psi = ((qc - pc) * log_ratio).sum(axis=1)
    kl = (qc * log_ratio).sum(axis=1)
    m = 0.5 * (pc + qc)
    js = 0.5 * (pc * (jnp.log(pc) - jnp.log(m))).sum(axis=1) + \
        0.5 * (qc * (jnp.log(qc) - jnp.log(m))).sum(axis=1)
    ks = jnp.abs(jnp.cumsum(p - q, axis=1)).max(axis=1)
    # zero-expected-count exclusion: only bins with baseline support
    # contribute (see module docstring — ε denominators would make one
    # stray unknown token an alert)
    chi2 = (jnp.where(valid & (p > 0), (q - p) ** 2, 0.0) / pc
            ).sum(axis=1)
    return jnp.stack([psi, kl, js, ks, chi2], axis=1)


class DriftScorer:
    """Scores stacked window count matrices against one baseline.

    The baseline's probability matrix, valid-bin mask, and the jitted
    kernel are built once; every window then costs a single device
    launch + one (R, 5) readback."""

    def __init__(self, baseline: Baseline, eps: float = DEFAULT_EPS):
        import jax
        import jax.numpy as jnp
        self.baseline = baseline
        self.eps = float(eps)
        r, b = baseline.counts.shape
        valid = np.zeros((r, b), dtype=bool)
        for i, s in enumerate(baseline.specs):
            valid[i, :s.n_bins] = True
        self._valid = jnp.asarray(valid)
        self._p = jnp.asarray(baseline.probabilities().astype(np.float32))
        eps_f = jnp.float32(self.eps)
        self._kernel = jax.jit(
            lambda q: _score_kernel(self._p, q, self._valid, eps_f))

    def score_counts(self, window_counts: np.ndarray, n_rows: int,
                     index: int = 0, kind: str = "window") -> DriftReport:
        """Score one finalized (R, B) window count matrix."""
        import jax.numpy as jnp
        if window_counts.shape != self.baseline.counts.shape:
            raise ValueError(
                f"window shape {window_counts.shape} does not match "
                f"baseline {self.baseline.counts.shape}")
        from ..utils.tracing import note_dispatch
        note_dispatch(site="drift.score")
        mat = np.asarray(self._kernel(
            jnp.asarray(window_counts, jnp.float32)))
        report = DriftReport(index=index, kind=kind, n_rows=int(n_rows))
        for i, s in enumerate(self.baseline.specs):
            scope = PREDICTION_SCOPE if s.kind == CLASS else s.name
            row_kind = NUMERIC if s.kind == NUMERIC else s.kind
            report.rows.append(RowScore(
                scope=scope, kind=row_kind,
                stats={name: float(mat[i, j])
                       for j, name in enumerate(STATS)}))
        return report

    def score_table(self, table, index: int = 0,
                    class_codes: Optional[np.ndarray] = None) -> DriftReport:
        """Convenience one-shot: encode + count + score a table as a
        single window (jobs with in-memory windows)."""
        import jax.numpy as jnp
        from ..ops.histogram import feature_bin_counts
        from .baseline import encode_monitor_codes
        codes = encode_monitor_codes(table, self.baseline.specs,
                                     class_codes=class_codes)
        counts = np.asarray(feature_bin_counts(
            jnp.asarray(codes), self.baseline.n_bins_max), dtype=np.float64)
        return self.score_counts(counts, table.n_rows, index=index)
