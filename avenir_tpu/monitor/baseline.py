"""Training-time feature baselines: the reference distribution a served
model carries with it.

A baseline is one stacked fixed-bin count matrix over every monitored
"row" — each numeric feature (schema ``bucketWidth`` binning when the
field has one, fixed ``n_bins`` over [min, max] otherwise), each
categorical feature (frequency table keyed by the schema's cardinality,
plus one trailing bin for unknown values), and the training class/label
distribution (prior-probability drift's reference).  Counting happens
device-side per ``ColumnarTable`` chunk through the same one-hot
contraction every reducer in this framework uses
(``ops/histogram.feature_bin_counts``); ``finalize()`` is the only host
sync and also derives per-numeric-feature quantiles from the cumulative
histogram (``stats/histogram.Histogram`` — the host histogram utility).

Baselines publish into a model's registry version as a
``baseline.json`` + ``baseline.npz`` sidecar pair through
``ModelRegistry.add_sidecar`` (tmp-then-rename per file, meta.json
manifest updated last), so every served model version carries its own
reference distribution and the intactness probe covers it.
"""

from __future__ import annotations

import io as _io
import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.schema import FeatureSchema
from ..core.table import ColumnarTable
from ..stats.histogram import Histogram

BASELINE_JSON = "baseline.json"
BASELINE_NPZ = "baseline.npz"
FORMAT_VERSION = 1

DEFAULT_NUM_BINS = 32
QUANTILE_QS = (1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0)

NUMERIC = "numeric"
CATEGORICAL = "categorical"
CLASS = "class"
PREDICTION_SCOPE = "__prediction__"


@dataclass
class RowSpec:
    """One monitored distribution: a feature column or the class/label
    stream.  ``lo``/``width`` define the bin edges for numeric rows
    (``bin b`` covers ``[lo + b*width, lo + (b+1)*width)``; values
    outside clamp to the edge bins); categorical/class rows bin by
    vocabulary code with the LAST bin reserved for unknown (-1) codes."""

    name: str
    kind: str                    # numeric | categorical | class
    ordinal: int                 # schema ordinal; -1 for the class row
    n_bins: int
    lo: float = 0.0
    width: float = 1.0
    labels: Optional[List[str]] = None   # categorical/class bin names

    def to_dict(self) -> Dict:
        d = {"name": self.name, "kind": self.kind, "ordinal": self.ordinal,
             "n_bins": self.n_bins, "lo": self.lo, "width": self.width}
        if self.labels is not None:
            d["labels"] = list(self.labels)
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "RowSpec":
        return cls(name=d["name"], kind=d["kind"], ordinal=int(d["ordinal"]),
                   n_bins=int(d["n_bins"]), lo=float(d["lo"]),
                   width=float(d["width"]), labels=d.get("labels"))


def monitor_specs(schema: FeatureSchema,
                  n_bins: int = DEFAULT_NUM_BINS) -> List[RowSpec]:
    """The monitored rows of a schema: every feature field plus the class
    distribution (always last — scorer and policy key on that).  Numeric
    fields without min/max can't define fixed bins up front; they get
    ``n_bins = 0`` here and are resolved against the first data chunk by
    :class:`BaselineBuilder` (resolve_spec_bounds)."""
    specs: List[RowSpec] = []
    for f in schema.feature_fields:
        if f.is_categorical:
            card = list(f.cardinality or [])
            specs.append(RowSpec(name=f.name, kind=CATEGORICAL,
                                 ordinal=f.ordinal, n_bins=len(card) + 1,
                                 labels=card + ["__unknown__"]))
        elif f.bucket_width is not None and f.min is not None \
                and f.max is not None:
            # the schema's own binning (value // bucketWidth - offset):
            # bin codes come precomputed from the native parse cache
            specs.append(RowSpec(name=f.name, kind=NUMERIC,
                                 ordinal=f.ordinal, n_bins=f.num_bins,
                                 lo=f.bin_offset * f.bucket_width,
                                 width=float(f.bucket_width)))
        elif f.min is not None and f.max is not None:
            lo, hi = float(f.min), float(f.max)
            width = (hi - lo) / n_bins if hi > lo else 1.0
            specs.append(RowSpec(name=f.name, kind=NUMERIC,
                                 ordinal=f.ordinal, n_bins=n_bins,
                                 lo=lo, width=width))
        else:
            specs.append(RowSpec(name=f.name, kind=NUMERIC,
                                 ordinal=f.ordinal, n_bins=0))
    cf = schema.class_attr_field
    card = list(cf.cardinality or [])
    specs.append(RowSpec(name=cf.name, kind=CLASS, ordinal=cf.ordinal,
                         n_bins=len(card) + 1,
                         labels=card + ["__unknown__"]))
    return specs


def resolve_spec_bounds(specs: Sequence[RowSpec], table: ColumnarTable,
                        n_bins: int = DEFAULT_NUM_BINS) -> None:
    """Fill the (lo, width) of unbounded numeric specs (schema without
    min/max) from the first observed chunk's value range, widened by one
    bin each side so near-boundary values of later chunks still land in
    real bins.  Mutates the specs in place; no-op once resolved."""
    for s in specs:
        if s.kind == NUMERIC and s.n_bins == 0:
            col = np.asarray(table.columns[s.ordinal], dtype=np.float64)
            lo = float(col.min()) if col.size else 0.0
            hi = float(col.max()) if col.size else 1.0
            width = (hi - lo) / max(n_bins - 2, 1) if hi > lo else 1.0
            s.lo, s.width, s.n_bins = lo - width, width, n_bins


def encode_monitor_codes(table: ColumnarTable, specs: Sequence[RowSpec],
                         class_codes: Optional[np.ndarray] = None
                         ) -> np.ndarray:
    """(n, R) int32 bin codes, one column per monitored row, values
    clamped into each row's bin alphabet (out-of-range numerics clamp to
    edge bins, unknown categorical codes take the trailing unknown bin).
    ``class_codes`` overrides the table's class column — the serving
    path monitors the PREDICTED label stream, not ground truth."""
    n = table.n_rows
    out = np.empty((n, len(specs)), dtype=np.int32)
    for j, s in enumerate(specs):
        if s.kind == NUMERIC:
            if s.n_bins == 0:
                raise ValueError(
                    f"numeric field {s.name!r} has unresolved bin bounds; "
                    f"call resolve_spec_bounds on the first chunk")
            f = table.schema.find_field_by_ordinal(s.ordinal)
            if f.bucket_width is not None and f.min is not None \
                    and f.max is not None:
                codes = np.asarray(table.binned_codes(s.ordinal))
            else:
                col = np.asarray(table.columns[s.ordinal], dtype=np.float64)
                codes = np.floor((col - s.lo) / s.width).astype(np.int64)
            out[:, j] = np.clip(codes, 0, s.n_bins - 1)
        else:  # categorical / class: code -1 (unknown) -> trailing bin
            if s.kind == CLASS and class_codes is not None:
                codes = np.asarray(class_codes)
            else:
                codes = np.asarray(table.columns[s.ordinal])
            out[:, j] = np.where(codes < 0, s.n_bins - 1,
                                 np.clip(codes, 0, s.n_bins - 1))
    return out


@dataclass
class Baseline:
    """Finalized reference profile: stacked per-row bin counts (float64
    host copy; exact — device accumulation is f32, exact below 2^24 per
    bin) plus per-numeric-row quantiles derived from the histograms."""

    specs: List[RowSpec]
    counts: np.ndarray          # (R, B_max) float64
    n_rows: int
    quantile_qs: Tuple[float, ...] = QUANTILE_QS
    quantiles: Optional[np.ndarray] = None   # (R, Q) float64, nan non-numeric

    @property
    def n_bins_max(self) -> int:
        return self.counts.shape[1]

    def row_index(self, name: str) -> int:
        for i, s in enumerate(self.specs):
            if s.name == name:
                return i
        raise KeyError(f"no monitored row named {name!r}")

    @property
    def class_row(self) -> int:
        return len(self.specs) - 1

    def class_codes_for_labels(self, labels) -> np.ndarray:
        """Map predicted class labels onto the class row's bin codes
        (unknown/ambiguous labels take the trailing unknown bin) — THE
        label encoding shared by the serving hook and the driftMonitor
        job, so prediction-prior drift scores identically in both."""
        spec = self.specs[self.class_row]
        code = {lab: i for i, lab in enumerate(spec.labels or [])}
        unknown = spec.n_bins - 1
        return np.fromiter((code.get(lab, unknown) for lab in labels),
                           dtype=np.int32, count=len(labels))

    def probabilities(self) -> np.ndarray:
        """(R, B) per-row normalized distribution (zero-total rows stay
        all-zero — the scorer guards)."""
        totals = self.counts.sum(axis=1, keepdims=True)
        return np.divide(self.counts, np.maximum(totals, 1.0))

    # ---- sidecar serialization ----
    def to_sidecar(self) -> Dict[str, bytes]:
        """The registry sidecar pair: JSON spec + NPZ payload, as bytes
        (ModelRegistry.add_sidecar writes them tmp-then-rename)."""
        meta = {
            "format_version": FORMAT_VERSION,
            "n_rows": self.n_rows,
            "quantile_qs": list(self.quantile_qs),
            "rows": [s.to_dict() for s in self.specs],
        }
        buf = _io.BytesIO()
        arrays = {"counts": np.asarray(self.counts, np.float64)}
        if self.quantiles is not None:
            arrays["quantiles"] = np.asarray(self.quantiles, np.float64)
        np.savez(buf, **arrays)
        return {BASELINE_JSON: json.dumps(meta, indent=2).encode(),
                BASELINE_NPZ: buf.getvalue()}

    @classmethod
    def from_sidecar(cls, meta_bytes: bytes, npz_bytes: bytes) -> "Baseline":
        meta = json.loads(meta_bytes.decode())
        with np.load(_io.BytesIO(npz_bytes)) as z:
            counts = z["counts"]
            quantiles = z["quantiles"] if "quantiles" in z.files else None
        return cls(specs=[RowSpec.from_dict(d) for d in meta["rows"]],
                   counts=counts, n_rows=int(meta["n_rows"]),
                   quantile_qs=tuple(meta["quantile_qs"]),
                   quantiles=quantiles)


def _require_bounded_numerics(schema: FeatureSchema) -> None:
    """Multi-process guard: bins must be schema-pinned on every numeric
    feature or each shard resolves different edges and the partial-count
    sum is meaningless."""
    unbounded = [f.name for f in schema.feature_fields
                 if f.is_numeric and (f.min is None or f.max is None)]
    if unbounded:
        raise ValueError(
            f"multi-process baseline needs schema min/max on every "
            f"numeric feature (bins must agree across shards); missing "
            f"on: {unbounded}")


class BaselineBuilder:
    """Accumulate the baseline device-side from ColumnarTable chunks.

    ``update(chunk)`` encodes the chunk's monitor codes host-side (a few
    clips over already-encoded columns) and adds their bin counts on
    device in one ``feature_bin_counts`` contraction; nothing syncs until
    ``finalize()``.  Streaming trains tee their block iterator through
    :func:`tee_blocks` so the baseline rides the same single pass as the
    model."""

    def __init__(self, schema: FeatureSchema,
                 n_bins: int = DEFAULT_NUM_BINS):
        self.schema = schema
        self.n_bins = n_bins
        self.specs = monitor_specs(schema, n_bins)
        self._counts = None          # device (R, B_max) f32, lazy
        self._n = 0
        # fail at construction, not after the training pass: a
        # multi-process (or row-range-sharded) baseline needs every
        # numeric feature's bins pinned by the schema, or each shard
        # resolves different edges and allreduce_partials sums apples
        # with oranges
        from ..parallel.distributed import is_multiprocess, shard_spec
        if is_multiprocess() or shard_spec().active:
            _require_bounded_numerics(schema)

    def _ensure_state(self):
        import jax.numpy as jnp
        if self._counts is None:
            b_max = max(s.n_bins for s in self.specs)
            self._counts = jnp.zeros((len(self.specs), b_max),
                                     dtype=jnp.float32)

    def update(self, table: ColumnarTable,
               mask: Optional[np.ndarray] = None) -> "BaselineBuilder":
        import jax.numpy as jnp
        from ..ops.histogram import feature_bin_counts
        from ..ops.pallas.dispatch import (note_backend, pallas_interpret,
                                           resolve_backend)
        from ..utils.tracing import note_dispatch
        resolve_spec_bounds(self.specs, table, self.n_bins)
        self._ensure_state()
        codes = encode_monitor_codes(table, self.specs)
        m = jnp.asarray(mask) if mask is not None else None
        backend = resolve_backend()
        note_dispatch(site="baseline.absorb")
        note_backend("baseline.absorb", backend)
        if backend == "pallas":
            # the VMEM-resident pallas twin (ops/pallas/histogram.
            # bin_counts) — bit-identical 0/1 sums, one launch
            from ..ops.pallas.histogram import bin_counts
            self._counts = self._counts + bin_counts(
                jnp.asarray(codes), self._counts.shape[1], m,
                interpret=pallas_interpret())
        else:
            self._counts = self._counts + feature_bin_counts(
                jnp.asarray(codes), self._counts.shape[1], m)
        self._n += table.n_rows if mask is None else int(np.sum(mask))
        return self

    def as_stage(self):
        """This builder as a fused-pipeline stage (TPU_NOTES §22): the
        monitor-code encode stays host-side on the staging thread (the
        float64 clip/floor arithmetic is the bit-identity anchor shared
        with :meth:`update`), the bin counting joins the chunk's ONE
        fused launch, and the (R, B) count matrix lives as a DONATED
        device carry updated in place per chunk.  ``finish`` installs
        the final carry back here, so :meth:`finalize` (and
        :func:`allreduce_partials`) work unchanged.  Counts are
        integer-exact f32 sums, so fused and tee'd baselines finalize
        byte-identically (tests/test_pipeline.py)."""
        from ..pipeline.compiler import Stage
        # unresolved numeric specs (schema without min/max) resolve to
        # exactly ``self.n_bins`` bins on the first chunk, so the carry
        # width is known BEFORE the stream starts — same b_max the tee
        # path's lazy _ensure_state computes after resolution
        b_max = max([s.n_bins for s in self.specs]
                    + ([self.n_bins] if any(
                        s.kind == NUMERIC and s.n_bins == 0
                        for s in self.specs) else []))
        builder = self

        def prepare(table):
            resolve_spec_bounds(builder.specs, table, builder.n_bins)
            builder._n += table.n_rows
            return {"mon_codes": encode_monitor_codes(table, builder.specs)}

        def kernel(carry, consts, inputs, upstream):
            # trace-time backend branch: safe because ChunkPipeline's
            # ProgramCache key carries a backend axis (TPU_NOTES §24) —
            # a program traced under one backend never serves the other
            from ..ops.pallas.dispatch import (pallas_interpret,
                                               resolve_backend)
            if resolve_backend() == "pallas":
                from ..ops.pallas.histogram import bin_counts
                return carry + bin_counts(
                    inputs["mon_codes"], b_max, inputs["mask"] > 0,
                    interpret=pallas_interpret()), {}
            from ..ops.histogram import feature_bin_counts
            return carry + feature_bin_counts(
                inputs["mon_codes"], b_max, inputs["mask"] > 0), {}

        def carry_init():
            import jax.numpy as jnp
            if builder._counts is not None:
                # a pre-seeded builder (the retrain controller's resumed
                # build re-profiles the already-consumed head via
                # update() before fusing the tail) carries its counts
                # INTO the stage — finish() would otherwise DISCARD the
                # head with the final carry.  Copy: the carry is donated
                # and must not alias a buffer the builder still holds.
                return jnp.array(builder._counts, jnp.float32, copy=True)
            return jnp.zeros((len(builder.specs), b_max), jnp.float32)

        def finish(carry):
            builder._counts = carry

        # b_max is traced STATICALLY into the kernel, so it is part of
        # the stage fingerprint (the ProgramCache must miss when a
        # different bin budget produces the same array shapes elsewhere)
        return Stage(name="monitor-absorb", kernel=kernel,
                     version=f"1:b{b_max}", prepare=prepare,
                     carry_init=carry_init, finish=finish)

    def finalize(self) -> Baseline:
        """Host sync: pull the device counts once, derive quantiles."""
        self._ensure_state()
        counts = np.asarray(self._counts, dtype=np.float64)
        quantiles = np.full((len(self.specs), len(QUANTILE_QS)), np.nan)
        for i, s in enumerate(self.specs):
            if s.kind != NUMERIC or counts[i, :s.n_bins].sum() <= 0:
                continue
            h = Histogram(s.lo, s.width, counts[i, :s.n_bins])
            quantiles[i] = [h.percentile(q) for q in QUANTILE_QS]
        return Baseline(specs=[RowSpec.from_dict(s.to_dict())
                               for s in self.specs],
                        counts=counts, n_rows=self._n, quantiles=quantiles)


def tee_blocks(blocks, builder: BaselineBuilder):
    """Pass-through generator: every block updates the baseline builder
    on its way to the training consumer — the baseline costs no second
    pass over a streamed ingest."""
    for b in blocks:
        builder.update(b)
        yield b


def compute_baseline(table: ColumnarTable,
                     n_bins: int = DEFAULT_NUM_BINS) -> Baseline:
    """One-shot baseline from a fully loaded table."""
    return BaselineBuilder(table.schema, n_bins).update(table).finalize()


def allreduce_partials(builder: BaselineBuilder,
                       reducer=None) -> BaselineBuilder:
    """Under multi-process, sum the per-shard partial counts host-side so
    every process finalizes the identical GLOBAL baseline (the sharded
    training jobs' counter-reduction discipline; the matrices are small —
    R x B_max floats).  Single-process: no-op.

    The summing is correct because dist='sharded' jobs feed each process
    ITS OWN input shard (cli/run._apply_dist_mode refuses identical
    inputs; MeshContext.shard_rows treats each host's array as the
    process-local block of the global dataset), so every builder holds a
    disjoint partial.  Unbounded numeric fields must carry schema
    min/max here — per-shard lazy bin resolution could disagree across
    processes (BaselineBuilder resolves them from the first local
    chunk)."""
    from ..parallel.distributed import allgather_object, is_multiprocess
    if reducer is not None and reducer.spec.active:
        # row-range-sharded streaming build: partials combine through the
        # build's own collective transport (works on the
        # jax.distributed-free lane too)
        gather = reducer.allgather
    elif is_multiprocess():
        gather = allgather_object
    else:
        return builder
    _require_bounded_numerics(builder.schema)
    import jax.numpy as jnp
    builder._ensure_state()
    parts = gather(
        (np.asarray(builder._counts, np.float64), builder._n))
    builder._counts = jnp.asarray(
        np.sum([c for c, _ in parts], axis=0).astype(np.float32))
    builder._n = int(sum(n for _, n in parts))
    return builder


# --------------------------------------------------------------------------
# registry integration
# --------------------------------------------------------------------------

def publish_baseline(registry, name: str, version: int,
                     baseline: Baseline) -> None:
    """Attach the baseline sidecar pair to a committed registry version
    (tmp-then-rename per file; the version's meta.json manifest is
    updated last so a crash mid-write leaves the version intact and
    baseline-less, never torn)."""
    registry.add_sidecar(name, version, baseline.to_sidecar())


def load_baseline(registry, name: str,
                  version: Optional[int] = None) -> Baseline:
    """Read a version's baseline sidecar (newest intact version when
    ``version`` is None).  Raises FileNotFoundError when the version
    carries no baseline."""
    if version is None:
        version = registry.latest_version(name)
        if version is None:
            raise FileNotFoundError(
                f"no intact versions of model {name!r} in "
                f"{registry.base_dir!r}")
    meta_b = registry.read_sidecar(name, version, BASELINE_JSON)
    npz_b = registry.read_sidecar(name, version, BASELINE_NPZ)
    return Baseline.from_sidecar(meta_b, npz_b)
