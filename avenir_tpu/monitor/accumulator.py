"""Streaming window accumulators: absorb rows on device, sync once per
window.

Layout is the baseline's stacked (R, B_max) count matrix (TPU_NOTES §17):
absorbing a row block is one ``feature_bin_counts`` one-hot contraction
added into the pre-allocated device matrix — a scatter-add per block, not
per row — and ``finalize()`` is the only host readback.  Incoming blocks
pad up to power-of-two row buckets (mask-guarded, the serving layer's
shape discipline) so the per-instance jit compiles O(log max-block)
variants instead of one per batch size.

Windows:

  * tumbling — close after ``window_rows`` rows (and/or ``window_s``
    seconds); each closed window scores independently against the
    baseline.
  * exponential-decay long window — after each tumbling close,
    ``long = decay * long + window`` (host-side on the just-synced
    snapshot: two small (R, B) arrays, no extra device traffic).  The
    long window catches slow drifts whose per-window scores never clear
    the warn bar.

``ServingMonitor`` is the :class:`PredictionService` hook: per-micro-batch
cost is two list extends (the <5% serve_forest overhead budget —
benchmarked by the ``monitor_drift`` bench point); encoding and the
device scatter-add amortize over ``flush_rows`` requests, scoring over
``window_rows``.
"""

from __future__ import annotations

import queue
import threading
import time
import warnings
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..core.metrics import Counters
from ..core.table import ColumnarTable, encode_rows
from .baseline import Baseline, encode_monitor_codes
from .drift import DriftReport, DriftScorer

DEFAULT_BLOCK_BUCKETS = (64, 256, 1024, 4096)


@dataclass
class WindowSnapshot:
    """One finalized window: host counts + bookkeeping."""
    index: int
    counts: np.ndarray          # (R, B_max) float64
    n_rows: int
    t_start: float
    t_end: float


class DriftAccumulator:
    """Pre-allocated device bin matrix + bucketed scatter-add absorb."""

    def __init__(self, baseline: Baseline,
                 buckets: Sequence[int] = DEFAULT_BLOCK_BUCKETS):
        import jax
        import jax.numpy as jnp
        self.baseline = baseline
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        r, b = baseline.counts.shape
        self._shape = (r, b)
        self._zero = jnp.zeros((r, b), dtype=jnp.float32)
        self._counts = self._zero
        self._n = 0

        from ..ops.histogram import feature_bin_counts

        def update(counts, codes, mask):
            return counts + feature_bin_counts(codes, b, mask)
        self._update = jax.jit(update)

    @property
    def n_rows(self) -> int:
        return self._n

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def absorb_codes(self, codes: np.ndarray) -> None:
        """Add one (n, R) int32 code block.  Blocks beyond the top bucket
        split; smaller blocks pad (mask-guarded) to the bucket size so
        the jit never sees a fresh shape."""
        import jax.numpy as jnp
        n = codes.shape[0]
        if n == 0:
            return
        from ..utils.tracing import note_dispatch
        top = self.buckets[-1]
        for s in range(0, n, top):
            chunk = codes[s:s + top]
            m = chunk.shape[0]
            b = self._bucket(m)
            if m < b:
                chunk = np.concatenate(
                    [chunk, np.zeros((b - m, chunk.shape[1]), chunk.dtype)])
            mask = np.zeros((b,), dtype=bool)
            mask[:m] = True
            note_dispatch(site="monitor.absorb")
            self._counts = self._update(self._counts, jnp.asarray(chunk),
                                        jnp.asarray(mask))
        self._n += n

    def absorb_table(self, table: ColumnarTable,
                     class_codes: Optional[np.ndarray] = None) -> None:
        self.absorb_codes(encode_monitor_codes(
            table, self.baseline.specs, class_codes=class_codes))

    def warm(self) -> "DriftAccumulator":
        """Pre-compile the absorb jit for every bucket shape WITHOUT
        touching the accumulated state (all-False mask; result
        discarded) — a first live flush must not pay a compile on the
        serving path."""
        import jax.numpy as jnp
        r = self._shape[0]
        for b in self.buckets:
            self._update(self._zero,
                         jnp.zeros((b, r), dtype=jnp.int32),
                         jnp.zeros((b,), dtype=bool))
        return self

    def finalize(self) -> "tuple[np.ndarray, int]":
        """THE host sync: read the device matrix back, reset the
        accumulator (tumbling semantics).  Returns (counts, n_rows)."""
        counts = np.asarray(self._counts, dtype=np.float64)
        n = self._n
        self._counts = self._zero
        self._n = 0
        return counts, n


class StreamDriftMonitor:
    """Tumbling + exponential-decay windows over a code/table stream,
    scored on close and fed to an optional policy.

    ``observe_*`` absorbs rows, closing (and scoring) a window every
    ``window_rows`` rows or ``window_s`` seconds; each close also decays
    the long window and scores it as kind='longterm'.  Reports retain in
    ``self.reports`` (bounded), alerts accumulate via the policy."""

    def __init__(self, baseline: Baseline, scorer: Optional[DriftScorer]
                 = None, policy=None, window_rows: int = 4096,
                 window_s: Optional[float] = None, decay: float = 0.9,
                 counters: Optional[Counters] = None,
                 keep_reports: int = 256,
                 buckets: Sequence[int] = DEFAULT_BLOCK_BUCKETS):
        if not 0.0 <= decay < 1.0:
            raise ValueError(f"decay must be in [0, 1), got {decay}")
        if window_rows < 1:
            # observe_codes fills windows by remaining room; a
            # non-positive size would never make progress
            raise ValueError(f"window_rows must be >= 1, got {window_rows}")
        self.baseline = baseline
        self.scorer = scorer or DriftScorer(baseline)
        self.policy = policy
        self.window_rows = int(window_rows)
        self.window_s = window_s
        self.decay = float(decay)
        self.counters = counters if counters is not None else Counters()
        self.keep_reports = keep_reports
        self.acc = DriftAccumulator(baseline, buckets=buckets)
        self._long_counts = np.zeros_like(baseline.counts)
        self._long_n = 0.0
        self._window_start = time.monotonic()
        self._index = 0
        self.reports: List[DriftReport] = []

    def warm(self) -> "StreamDriftMonitor":
        """Compile the absorb buckets and the scoring kernel off the
        live path (scores a baseline-shaped dummy directly through the
        scorer — no window, no policy, no report)."""
        self.acc.warm()
        self.scorer.score_counts(np.zeros_like(self.baseline.counts), 0)
        return self

    # ---- ingestion ----
    def observe_codes(self, codes: np.ndarray) -> None:
        n = codes.shape[0]
        s = 0
        while s < n:
            room = self.window_rows - self.acc.n_rows
            take = min(room, n - s)
            self.acc.absorb_codes(codes[s:s + take])
            s += take
            if self.acc.n_rows >= self.window_rows:
                self.close_window()
        if self.window_s is not None and self.acc.n_rows > 0 and \
                time.monotonic() - self._window_start >= self.window_s:
            self.close_window()

    def observe_table(self, table: ColumnarTable,
                      class_codes: Optional[np.ndarray] = None) -> None:
        self.observe_codes(encode_monitor_codes(
            table, self.baseline.specs, class_codes=class_codes))

    # ---- window close ----
    def close_window(self, force: bool = False) -> Optional[DriftReport]:
        """Finalize the current tumbling window (no-op when empty unless
        ``force``), score it, decay-merge it into the long window and
        score that too.  Returns the tumbling report."""
        if self.acc.n_rows == 0 and not force:
            return None
        counts, n = self.acc.finalize()
        return self._close(counts, n)

    def close_counts(self, counts: np.ndarray, n: int
                     ) -> Optional[DriftReport]:
        """Close one EXTERNALLY-accumulated window — the fused pipeline's
        per-chunk (R, B) count matrix (pipeline.flows.PredictDriftFlow)
        enters here and then rides the IDENTICAL scoring / long-window
        decay / policy path as :meth:`close_window`, which is what makes
        the fused job's reports bit-identical to the unfused ones.
        Refuses while the internal accumulator holds rows (interleaving
        the two absorb paths would split a window's counts)."""
        if self.acc.n_rows:
            raise ValueError(
                f"close_counts with {self.acc.n_rows} internally "
                f"accumulated rows pending — one window must use one "
                f"absorb path")
        if n == 0:
            return None
        return self._close(np.asarray(counts, dtype=np.float64), int(n))

    def _close(self, counts: np.ndarray, n: int) -> DriftReport:
        now = time.monotonic()
        report = self.scorer.score_counts(counts, n, index=self._index,
                                          kind="window")
        self._remember(report)
        # exponential-decay long window rides the just-synced host copy
        self._long_counts = self.decay * self._long_counts + counts
        self._long_n = self.decay * self._long_n + n
        long_report = self.scorer.score_counts(
            self._long_counts, int(self._long_n), index=self._index,
            kind="longterm")
        self._remember(long_report)
        self.counters.increment("DriftMonitor", "WindowsScored")
        self.counters.increment("DriftMonitor", "RowsSeen", n)
        if self.policy is not None:
            self.policy.observe(report)
            self.policy.observe(long_report)
        self._index += 1
        self._window_start = now
        return report

    def _remember(self, report: DriftReport) -> None:
        self.reports.append(report)
        if len(self.reports) > self.keep_reports:
            del self.reports[:len(self.reports) - self.keep_reports]


class ServingMonitor:
    """The PredictionService hook: record served (row, predicted-label)
    pairs, score them against the model's training baseline.

    ``record_batch`` runs on the serving worker thread, so it only
    buffers (two list extends — the <5% overhead budget); every
    ``flush_rows`` requests the buffer hands off to a daemon monitor
    thread that encodes once and scatter-adds once on device, so even
    the amortized encode/score cost stays off the request path
    (``async_flush=False`` keeps everything synchronous — deterministic
    for tests and batch jobs).  Predicted labels map to class codes
    through the baseline's class-row vocabulary (ambiguous/unknown
    labels land in the trailing unknown bin).  Monitoring must never
    take serving down: any failure inside a flush is caught, counted,
    and warned."""

    def __init__(self, baseline: Baseline, schema,
                 policy=None, window_rows: int = 1024,
                 flush_rows: int = 256, decay: float = 0.9,
                 window_s: Optional[float] = None,
                 counters: Optional[Counters] = None,
                 async_flush: bool = True):
        self.schema = schema
        self.counters = counters if counters is not None else Counters()
        self.stream = StreamDriftMonitor(
            baseline, policy=policy, window_rows=window_rows,
            window_s=window_s, decay=decay, counters=self.counters)
        self.flush_rows = int(flush_rows)
        self._rows: List[List[str]] = []
        self._labels: List[str] = []
        self.async_flush = async_flush
        self._pending: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None

    @property
    def reports(self) -> List[DriftReport]:
        return self.stream.reports

    def warm(self) -> "ServingMonitor":
        """Pre-compile the absorb buckets and scoring kernel so the
        first live flush never compiles on (or in competition with) the
        serving path."""
        self.stream.warm()
        return self

    def record_batch(self, rows: List[List[str]],
                     labels: List[str]) -> None:
        """Request-path entry: O(1) per row (buffer only)."""
        self._rows.extend(rows)
        self._labels.extend(labels)
        if len(self._rows) >= self.flush_rows:
            self.flush()

    def flush(self) -> None:
        """Hand the buffer to the monitor thread (or absorb inline when
        ``async_flush=False``)."""
        if not self._rows:
            return
        rows, labels = self._rows, self._labels
        self._rows, self._labels = [], []
        if self.async_flush:
            self._pending.put((rows, labels))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._drain, daemon=True,
                    name="avenir-monitor-flush")
                self._thread.start()
        else:
            self._absorb(rows, labels)

    def _drain(self) -> None:
        while True:
            item = self._pending.get()
            if item is None:
                return
            self._absorb(*item)

    def _absorb(self, rows: List[List[str]], labels: List[str]) -> None:
        try:
            table = encode_rows(rows, self.schema)
            codes = self.stream.baseline.class_codes_for_labels(labels)
            self.stream.observe_table(table, class_codes=codes)
        except Exception as exc:
            self.counters.increment("DriftMonitor", "RecordErrors",
                                    len(rows))
            warnings.warn(
                f"monitor: dropping {len(rows)} recorded rows "
                f"({type(exc).__name__}: {exc}) — serving unaffected",
                RuntimeWarning)

    def close(self) -> Optional[DriftReport]:
        """Flush the buffer, drain the monitor thread, and score
        whatever partial window remains."""
        self.flush()
        if self._thread is not None:
            self._pending.put(None)
            self._thread.join(timeout=60)
            self._thread = None
        return self.stream.close_window()
