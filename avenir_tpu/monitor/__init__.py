"""Drift & model-quality monitoring: the observability half of serving.

PR 1–3 built ingest, training, and the online `PredictionService`;
nothing watched whether live traffic still looks like training data — a
stale or drifting model serves silently until a human notices.  This
package closes that loop:

  * :mod:`.baseline`    — training-time feature/class profiles computed
    device-side from ``ColumnarTable`` chunks, published as a
    ``baseline.json`` + ``baseline.npz`` sidecar inside the model's
    registry version (every served model carries its own reference
    distribution);
  * :mod:`.accumulator` — tumbling + exponential-decay window
    accumulators (device scatter-adds, one host sync per window) and the
    ``ServingMonitor`` PredictionService hook;
  * :mod:`.drift`       — ONE jitted kernel scoring a finalized window
    against the baseline across all features at once: PSI, KL,
    Jensen–Shannon, binned KS (numerics), chi-square (categoricals), and
    the same scores on the prediction-class distribution (prior drift);
  * :mod:`.policy`      — warn/alert thresholds with consecutive-window
    debounce, structured alert records through the Counters channel, and
    the serving guardrails (registry re-probe / degrade flag / retrain
    controller handoff), plus delayed-label accuracy via
    ``ConfusionMatrix.report_batch``.

CLI: the ``driftMonitor`` job (``dm.*`` keys) scores a CSV stream or a
RESP queue against a registry baseline; ``randomForestBuilder`` publishes
a baseline next to the model with ``dtb.baseline.publish=true``.
"""

from .baseline import (BASELINE_JSON, BASELINE_NPZ, Baseline,
                       BaselineBuilder, PREDICTION_SCOPE, RowSpec,
                       compute_baseline, load_baseline, monitor_specs,
                       publish_baseline, tee_blocks)
from .accumulator import (DriftAccumulator, ServingMonitor,
                          StreamDriftMonitor)
from .drift import STATS, DriftReport, DriftScorer, RowScore
from .policy import (AccuracyTracker, AlertRecord, DriftPolicy,
                     DEFAULT_ALERT, DEFAULT_WARN, degrade_action,
                     refresh_action, retrain_action)

__all__ = [
    "BASELINE_JSON", "BASELINE_NPZ", "Baseline", "BaselineBuilder",
    "PREDICTION_SCOPE", "RowSpec", "compute_baseline", "load_baseline",
    "monitor_specs", "publish_baseline", "tee_blocks", "DriftAccumulator",
    "ServingMonitor", "StreamDriftMonitor", "STATS", "DriftReport",
    "DriftScorer", "RowScore", "AccuracyTracker", "AlertRecord",
    "DriftPolicy", "DEFAULT_ALERT", "DEFAULT_WARN", "degrade_action",
    "refresh_action", "retrain_action",
]
