"""Samplers (reference python/lib/sampler.py): Gaussian and non-parametric
rejection samplers and the Metropolis sampler over a histogram target.

TPU-first redesign: the reference draws one value per python-loop iteration;
here each sampler draws a whole batch per jitted call.  Rejection sampling is
vectorized as propose-everywhere + mask + gather (a fixed oversampling factor
with a host retry loop for the rare shortfall), and the Metropolis sampler
runs N independent chains in parallel (vmap-free — the chains are just a
batch axis), with a lax.scan over steps."""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import Histogram


# -------------------- rejection samplers --------------------

@partial(jax.jit, static_argnames=("n_draw",))
def _gauss_reject_batch(key, mean, std, n_draw: int):
    """Candidates over [mean±3σ] × [0, 1.05 fmax], accept y < f(x)
    (sampler.py:33-53 GaussianRejectSampler, batched)."""
    kx, ky = jax.random.split(key)
    xmin, xmax = mean - 3.0 * std, mean + 3.0 * std
    fmax = 1.0 / (jnp.sqrt(2.0 * jnp.pi) * std)
    x = jax.random.uniform(kx, (n_draw,), minval=xmin, maxval=xmax)
    y = jax.random.uniform(ky, (n_draw,), minval=0.0, maxval=1.05 * fmax)
    f = fmax * jnp.exp(-((x - mean) ** 2) / (2.0 * std * std))
    return x, y < f


def gaussian_reject_sample(key, mean: float, std: float, n: int) -> np.ndarray:
    """n samples from N(mean, std) truncated to ±3σ via rejection sampling."""
    out = np.empty((0,), dtype=np.float64)
    # acceptance rate is ~1/(1.05*3*sqrt(2/pi)) ≈ 0.38; oversample 3x
    while len(out) < n:
        key, sub = jax.random.split(key)
        x, ok = _gauss_reject_batch(sub, float(mean), float(std), 3 * n)
        out = np.concatenate([out, np.asarray(x)[np.asarray(ok)]])
    return out[:n]


@partial(jax.jit, static_argnames=("n_draw",))
def _nonparam_reject_batch(key, xmin, bin_width, values, n_draw: int):
    kx, ky = jax.random.split(key)
    n_bins = values.shape[0]
    xmax = xmin + bin_width * (n_bins - 1)
    fmax = values.max()
    x = jax.random.uniform(kx, (n_draw,), minval=xmin, maxval=xmax + bin_width)
    y = jax.random.uniform(ky, (n_draw,), minval=0.0, maxval=fmax)
    k = jnp.clip(((x - xmin) / bin_width).astype(jnp.int32), 0, n_bins - 1)
    return x, y < values[k]


def nonparam_reject_sample(key, xmin: float, bin_width: float,
                           values: Sequence[float], n: int) -> np.ndarray:
    """n samples from the piecewise-constant density given by per-bin weights
    (sampler.py:58-83 NonParamRejectSampler, batched; continuous within
    bins rather than integer-valued)."""
    vals = jnp.asarray(np.asarray(values, dtype=np.float64))
    out = np.empty((0,), dtype=np.float64)
    while len(out) < n:
        key, sub = jax.random.split(key)
        x, ok = _nonparam_reject_batch(sub, float(xmin), float(bin_width),
                                       vals, 4 * n)
        out = np.concatenate([out, np.asarray(x)[np.asarray(ok)]])
    return out[:n]


def weighted_indices(key, weights: Sequence[float], n: int) -> np.ndarray:
    """Sample n record indices with probability proportional to weight
    (python/lib/weighted_rec_sampler.py sample()): the Gumbel-top-1 trick
    per draw — one (n, len(w)) argmax on device, no rejection loop."""
    w = jnp.asarray(np.asarray(weights, dtype=np.float64))
    logw = jnp.log(jnp.maximum(w, 1e-300))
    g = jax.random.gumbel(key, (n, w.shape[0]))
    return np.asarray(jnp.argmax(logw[None, :] + g, axis=1))


# -------------------- Metropolis sampler --------------------

class MetropolisSampler:
    """Metropolis chains over a histogram target (sampler.py:86-157
    MetropolitanSampler): proposal = current + N(0, prop_std) (optionally a
    mixture with a wider global proposal), clamp to the target's support,
    accept with min(1, f(next)/f(cur)).

    Runs ``n_chains`` independent chains as a batch; ``sample()`` advances
    every chain one Metropolis transition, ``sub_sample(skip)`` advances
    ``skip`` full transitions and returns the last state (standard thinning;
    the reference's subSample re-proposes from the unchanged current sample
    and keeps only the last proposal, which is a no-op loop — fixed here)."""

    def __init__(self, prop_std: float, xmin: float, bin_width: float,
                 values: Sequence[float], n_chains: int = 1, seed: int = 0):
        self.hist = Histogram.create_initialized(xmin, bin_width, values)
        self.prop_std = float(prop_std)
        self.n_chains = n_chains
        self.key = jax.random.PRNGKey(seed)
        self.mixture_threshold: Optional[float] = None
        self.global_prop_std: Optional[float] = None
        self._vals = jnp.asarray(self.hist.bins)
        self._xmin = float(xmin)
        self._bw = float(bin_width)
        self._xmax = float(self.hist.xmax)
        self.initialize()

    def initialize(self) -> None:
        self.key, sub = jax.random.split(self.key)
        self.cur = jnp.asarray(jax.random.uniform(
            sub, (self.n_chains,), minval=self._xmin, maxval=self._xmax))
        self.trans_count = 0

    def set_global_proposal(self, global_std: float, threshold: float) -> None:
        """Mixture proposal (sampler.py:110-114): with prob threshold use the
        local proposal, else the wider global one."""
        self.global_prop_std = float(global_std)
        self.mixture_threshold = float(threshold)

    def sample(self) -> np.ndarray:
        return self.sub_sample(1)

    def sub_sample(self, skip: int) -> np.ndarray:
        self.key, sub = jax.random.split(self.key)
        mix = self.mixture_threshold is not None
        self.cur, n_acc = _metropolis_step(
            sub, self.cur, self._vals, self._xmin, self._bw, self._xmax,
            self.prop_std,
            self.global_prop_std if mix else 0.0,
            self.mixture_threshold if mix else 1.0,
            skip, mix)
        self.trans_count += int(n_acc)
        return np.asarray(self.cur)

    def run(self, steps: int, skip: int = 1) -> np.ndarray:
        """(steps, n_chains) trace."""
        return np.stack([self.sub_sample(skip) for _ in range(steps)])


@partial(jax.jit, static_argnames=("skip", "mixture"))
def _metropolis_step(key, cur, vals, xmin, bw, xmax, prop_std,
                     global_std, threshold, skip: int, mixture: bool):
    """``skip`` full Metropolis transitions (propose + accept each), i.e.
    standard thinning; acceptance count accumulates across all of them."""
    def density(x):
        k = jnp.clip(((x - xmin) / bw).astype(jnp.int32), 0, vals.shape[0] - 1)
        return vals[k]

    def transition(carry, k):
        x, n_acc = carry
        kp, km, ka = jax.random.split(k, 3)
        eps = jax.random.normal(kp, x.shape) * prop_std
        if mixture:
            eps_g = jax.random.normal(km, x.shape) * global_std
            use_local = jax.random.uniform(
                jax.random.fold_in(km, 1), x.shape) < threshold
            eps = jnp.where(use_local, eps, eps_g)
        nxt = jnp.clip(x + eps, xmin, xmax)
        ratio = density(nxt) / jnp.maximum(density(x), 1e-300)
        accept = jax.random.uniform(ka, x.shape) < jnp.minimum(ratio, 1.0)
        return (jnp.where(accept, nxt, x), n_acc + accept.sum()), None

    keys = jax.random.split(key, skip)
    (cur, n_acc), _ = jax.lax.scan(transition, (cur, jnp.zeros((), jnp.int32)),
                                   keys)
    return cur, n_acc
