"""Host-side histogram utility (reference python/lib/stats.py Histogram and
the chombo HistogramStat surface the bandit learners use): fixed-width bins
over [min, min + binWidth*k], with normalize / cumulative distribution /
percentile / density lookup.  Vectorized over numpy; small and host-side by
design — device-side counting is ops/histogram.py."""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


class Histogram:
    def __init__(self, xmin: float, bin_width: float, bins: np.ndarray):
        self.xmin = float(xmin)
        self.bin_width = float(bin_width)
        self.bins = np.asarray(bins, dtype=np.float64)
        self.normalized = False

    # ---- constructors (stats.py:18,33) ----
    @classmethod
    def create_initialized(cls, xmin: float, bin_width: float,
                           values: Sequence[float]) -> "Histogram":
        return cls(xmin, bin_width, np.asarray(values, dtype=np.float64))

    @classmethod
    def create_uninitialized(cls, xmin: float, xmax: float,
                             bin_width: float) -> "Histogram":
        n = int((xmax - xmin) / bin_width) + 1
        return cls(xmin, bin_width, np.zeros((n,), dtype=np.float64))

    @property
    def xmax(self) -> float:
        return self.xmin + self.bin_width * (len(self.bins) - 1)

    # ---- accumulation (stats.py:44) ----
    def add(self, value: float) -> None:
        self.add_many([value])

    def add_many(self, values: Sequence[float]) -> None:
        idx = ((np.asarray(values, dtype=np.float64) - self.xmin)
               / self.bin_width).astype(np.int64)
        idx = np.clip(idx, 0, len(self.bins) - 1)
        np.add.at(self.bins, idx, 1.0)

    # ---- distribution views (stats.py:52-87) ----
    def normalize(self) -> None:
        total = self.bins.sum()
        if total > 0:
            self.bins = self.bins / total
        self.normalized = True

    def cum_distr(self) -> np.ndarray:
        c = np.cumsum(self.bins)
        return c / c[-1] if c[-1] > 0 else c

    def percentile(self, percent: float) -> float:
        """Smallest bin upper edge whose cumulative share >= percent/100.

        Explicit edge behavior (pinned by tests/test_stats.py):
        ``percent`` clamps into [0, 100]; an EMPTY histogram (no mass at
        all) returns ``xmin`` — there is no distribution to locate a
        quantile in, and raising would turn a quiet stream into a
        crashed monitor.  The result is always a bin UPPER edge, so with
        all mass in the last bin it is ``xmin + bin_width*len(bins)`` —
        up to one bin width past ``xmax``, because ``xmax`` is the last
        bin's LEFT edge (create_uninitialized's bins-cover-[min, max]
        convention).  Callers whose bins tile the range exactly (e.g.
        monitor baselines) get exact range-top quantiles; do NOT clamp
        to xmax here — that would under-report every top-bin quantile
        by a full bin width for them.  Works on unnormalized bins
        (cum_distr normalizes internally)."""
        cum = self.cum_distr()
        if cum[-1] <= 0.0:
            return self.xmin
        percent = min(max(percent, 0.0), 100.0)
        k = int(np.searchsorted(cum, percent / 100.0))
        k = min(k, len(self.bins) - 1)
        return self.xmin + self.bin_width * (k + 1)

    def value(self, x: float) -> float:
        """Content of the bin containing x: the raw COUNT before
        :meth:`normalize`, the probability share after (callers needing
        density divide by bin_width).  Out-of-range x on either side
        returns 0.0 — never a clamped edge bin (``int()`` truncates
        toward zero, so the sub-xmin guard is explicit)."""
        if x < self.xmin:
            return 0.0
        k = int((x - self.xmin) / self.bin_width)
        if k >= len(self.bins):
            return 0.0
        return float(self.bins[k])

    def cum_value(self, x: float) -> float:
        """Cumulative share at x (always normalized, whether or not
        :meth:`normalize` ran — cum_distr divides by the total).  Below
        xmin: 0.0; at/above the top edge: the full share (1.0, or 0.0
        for an empty histogram — an empty cumulative is 0 everywhere,
        not NaN)."""
        if x < self.xmin:
            return 0.0
        k = min(int((x - self.xmin) / self.bin_width), len(self.bins) - 1)
        return float(self.cum_distr()[k])

    def get_min_max(self) -> Tuple[float, float]:
        return self.xmin, self.xmax

    def bounded_value(self, x: float) -> float:
        return min(max(x, self.xmin), self.xmax)
