"""MCMC convergence diagnostics (reference python/lib/mcconverge.py):
Geweke z-scores over a burn-in sweep and Raftery-Lewis burn-in/sample-size
estimation.  The reference's Raftery-Lewis code is python-2 pseudocode with
typos (np.qeros, undefined vars); this is the corrected standard method —
binarize the chain at a quantile threshold, fit the 2-state transition
matrix, and derive sizes from its mixing rate."""

from __future__ import annotations

import math
from statistics import NormalDist
from typing import List, Sequence, Tuple

import numpy as np


class GewekeConvergence:
    """Modified Geweke z-score for each candidate burn-in size
    (mcconverge.py:13-37): compare the mean of the first 10% window after
    burn-in against the last 50% window, scaled by their standard errors.
    |z| < ~2 indicates the post-burn-in chain is stationary."""

    def __init__(self, burn_in_sizes: Sequence[int],
                 window_a: float = 0.1, window_b: float = 0.5):
        self.burn_in_sizes = list(burn_in_sizes)
        self.window_a = window_a
        self.window_b = window_b
        self.zscores: List[Tuple[int, int, float]] = []

    def calculate_zscore(self, data: Sequence[float]) -> List[Tuple[int, int, float]]:
        x = np.asarray(data, dtype=np.float64)
        n = len(x)
        self.zscores = []  # one chain per call; no cross-chain mixing
        for bi in self.burn_in_sizes:
            if bi >= n:
                continue
            a = x[bi: bi + int((n - bi) * self.window_a)]
            b = x[n - int((n - bi) * self.window_b):]
            if len(a) < 2 or len(b) < 2:
                continue
            se = math.sqrt(a.var() / len(a) + b.var() / len(b))
            z = (a.mean() - b.mean()) / se if se > 0 else 0.0
            self.zscores.append((n, bi, float(z)))
        return self.zscores

    def get_zscores(self) -> List[Tuple[int, int, float]]:
        return self.zscores


class RafteryLewisConvergence:
    """Raftery-Lewis run-length control (mcconverge.py:40-87).

    Parameters mirror the reference: k = thinning_interval,
    s = percent_value_prob (probability the quantile estimate is within r),
    r = percent_value_conf_interval (tolerance), e = trans_prob_conf_limit
    (how close the binarized chain must be to stationarity at burn-in end).
    """

    def __init__(self, thinning_interval: int, percent_value_prob: float,
                 percent_value_conf_interval: float,
                 trans_prob_conf_limit: float, quantile: float = 0.025):
        self.thinning_interval = thinning_interval
        self.percent_value_prob = percent_value_prob
        self.percent_value_conf_interval = percent_value_conf_interval
        self.trans_prob_conf_limit = trans_prob_conf_limit
        self.quantile = quantile

    def find_sample_size(self, data: Sequence[float]) -> Tuple[float, float]:
        """(burn_in_size, sample_size) in un-thinned iterations."""
        x = np.asarray(data, dtype=np.float64)
        u = np.quantile(x, self.quantile)
        z = (x < u).astype(np.int64)

        # 2x2 transition counts of the binarized chain
        tr = np.zeros((2, 2), dtype=np.float64)
        np.add.at(tr, (z[:-1], z[1:]), 1.0)
        row0, row1 = tr[0].sum(), tr[1].sum()
        if row0 == 0 or row1 == 0:
            return 0.0, float(len(x))
        alpha = tr[0, 1] / row0            # P(0 -> 1)
        beta = tr[1, 0] / row1             # P(1 -> 0)
        if alpha <= 0 or beta <= 0 or alpha + beta >= 1:
            return 0.0, float(len(x))

        lam = 1.0 - alpha - beta           # second eigenvalue: mixing rate
        burn_in = (math.log(self.trans_prob_conf_limit * (alpha + beta)
                            / max(alpha, beta)) / math.log(abs(lam)))
        burn_in *= self.thinning_interval

        phi = NormalDist().inv_cdf(0.5 * (1.0 + self.percent_value_prob))
        n = (alpha * beta * (2.0 - alpha - beta) / (alpha + beta) ** 3
             / (self.percent_value_conf_interval / phi) ** 2)
        n *= self.thinning_interval
        return max(burn_in, 0.0), n
