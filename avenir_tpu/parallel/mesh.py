"""Device mesh management: the execution substrate replacing the Hadoop/Spark
cluster (SURVEY.md §2.10).

Everything distributed in this framework runs over one `jax.sharding.Mesh`:

  * ``data`` axis — row parallelism: the analog of HDFS-block map parallelism.
    Batches are sharded over it; reductions psum across it (the shuffle).
  * ``chain`` axis (optional, folded into data by default) — independent-chain
    fan-out for optimizers/bandits (the analog of Spark mapPartitions).

Multi-host/multi-slice: the mesh is built from `jax.devices()`, which under
jax.distributed spans hosts; collectives ride ICI within a slice and DCN across
slices with no code change here.  On CPU the same code paths are exercised with
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (test conftest).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
# model-parallel serving axis: the ensemble vote shards its MEMBER (tree)
# dimension over this axis (serving/predictor.py), not the row dimension
TREE_AXIS = "tree"


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the first n) devices."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def tree_mesh(n_shards: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D ``tree``-axis mesh for model-parallel serving: the stacked
    member tensors shard over it (one tree slice per chip), rows and the
    merged (n, K) tally replicate.  Distinct axis name ON PURPOSE — a
    serving core traced over this mesh can never silently reuse a
    ``data``-axis program (mesh_fingerprint keys the caches)."""
    return make_mesh(n_devices=n_shards, axis_name=TREE_AXIS,
                     devices=devices)


def worker_device(index: int, devices: Optional[Sequence] = None):
    """Round-robin device for fleet worker ``index`` — the placement map
    that stops every worker of a one-host fleet binding chip 0
    (serving/fleet.py ``device_map="round_robin"``)."""
    devs = list(devices if devices is not None else jax.devices())
    return devs[index % len(devs)]


_default_mesh: Optional[Mesh] = None


def default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None or len(_default_mesh.devices.flat) != len(jax.devices()):
        _default_mesh = make_mesh()
    return _default_mesh


def local_context() -> "MeshContext":
    """A MeshContext over THIS process's addressable devices only — the
    execution substrate of the sharded streaming builds: each process keeps
    its row-range shard device-resident locally and the only cross-process
    traffic is the explicit one-collective-per-level reduce
    (parallel.collectives.AllReducer).  Single-process this is just the
    default 1-D mesh, so the same builder code serves both."""
    return MeshContext(make_mesh(devices=jax.local_devices()),
                       process_local=True)


class MeshContext:
    """Convenience wrapper bundling a mesh with sharding helpers.

    This is the runtime handle every job gets (the analog of the Hadoop
    ``Configuration`` + cluster connection in reference job drivers, e.g.
    tree/DecisionTreeBuilder.java:70-94).

    Works over a 1-D data mesh (the default) or the multi-host hybrid
    (hosts, data) mesh from ``distributed.make_hybrid_mesh`` — rows shard
    over ALL axes, reductions psum over all axes, so job code is portable
    between the two.
    """

    def __init__(self, mesh: Optional[Mesh] = None,
                 process_local: bool = False):
        """``process_local=True`` marks a mesh built over THIS process's
        addressable devices only (``local_context()``): the placement
        helpers then never route through the multi-host global-array
        ingest (``from_process_local``) even when ``jax.process_count() >
        1`` — the sharded streaming builds keep their shard's arrays
        process-local and synchronize through explicit per-level
        collectives instead (parallel.collectives.AllReducer)."""
        self.mesh = mesh if mesh is not None else default_mesh()
        self.process_local = process_local
        axes = tuple(self.mesh.axis_names)
        # single string for a 1-D mesh (back-compat), tuple for hybrid —
        # both forms are accepted by PartitionSpec and lax.psum
        self.axis = axes[0] if len(axes) == 1 else axes

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def device_platform(self) -> str:
        """Platform string of the mesh's devices ("cpu", "tpu", ...) —
        lets wire-format choices trade host work against link bytes only
        where a real (slow) host->device link exists."""
        return self.mesh.devices.flat[0].platform

    def row_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.axis))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_rows(self, arr) -> jax.Array:
        """Place an array row-sharded over the mesh.  Row count must be a
        multiple of the mesh size (use ColumnarTable.pad_to_multiple first).
        Multi-process: ``arr`` is this process's equalized local block and
        the result is the global row-sharded array (multi-host ingest).

        All MeshContext placement helpers record their bytes into the
        active :class:`utils.tracing.TransferLedger` (host arrays only —
        re-placing an array already on device moves no link bytes)."""
        _note_upload(arr)
        if jax.process_count() > 1 and not self.process_local:
            from .distributed import from_process_local
            return from_process_local(np.asarray(arr), self.mesh)
        return jax.device_put(arr, self.row_sharding())

    def replicate(self, arr) -> jax.Array:
        _note_upload(arr)
        return jax.device_put(arr, self.replicated_sharding())

    def shard_rows_streamed(self, arr, chunk_bytes: int = 64 << 20
                            ) -> jax.Array:
        """``shard_rows`` with the host->device transfer cut into row
        chunks, for deep-scale uploads over the tunneled link (TPU_NOTES
        §5, §7): each chunk is its own transfer, so a mid-upload stall is
        visible at chunk granularity (set AVENIR_TPU_UPLOAD_PROGRESS=1 for
        a stderr heartbeat) instead of one opaque multi-minute device_put,
        and the watchdog pattern around a failed run re-pays at most the
        chunks already sent.  The chunks are reassembled ON DEVICE by one
        jitted concatenate (transient 2x memory for the array).

        Small arrays and multi-process runs take the plain shard_rows
        path (multi-host ingest must build the global array in one
        make_array call)."""
        arr = np.asarray(arr)
        if ((jax.process_count() > 1 and not self.process_local)
                or arr.ndim == 0
                or arr.nbytes <= chunk_bytes
                or arr.shape[0] < 2 * self.n_devices
                or arr.shape[0] % self.n_devices != 0):
            # same contract as shard_rows: row count is pre-padded to the
            # mesh (ColumnarTable.pad_to_multiple)
            return self.shard_rows(arr)
        row_bytes = max(arr.nbytes // arr.shape[0], 1)
        rows = max((chunk_bytes // row_bytes) // self.n_devices,
                   1) * self.n_devices
        import os as _os
        import sys as _sys
        progress = _os.environ.get("AVENIR_TPU_UPLOAD_PROGRESS") == "1"
        parts = []
        n = arr.shape[0]
        for s in range(0, n, rows):
            e = min(s + rows, n)
            _note_upload(arr[s:e])
            # tail chunks may not divide the mesh; ship them replicated-
            # free via plain device_put and let the concat reshard
            parts.append(jax.device_put(arr[s:e], self.row_sharding())
                         if (e - s) % self.n_devices == 0
                         else jax.device_put(arr[s:e]))
            if progress:
                print(f"[upload] {e}/{n} rows "
                      f"({100 * e // n}%)", file=_sys.stderr)
        return _concat_jit(len(parts), self.row_sharding())(parts)

    def zeros_rows(self, shape, dtype=np.float32) -> jax.Array:
        """Row-sharded zeros materialized ON DEVICE — no host transfer (a
        (100M, T) node-id init would otherwise ship gigabytes through the
        host link).  ``shape[0]`` follows the shard_rows contract: it is the
        per-process local row count, so multi-process runs produce a global
        array of process_count * shape[0] rows (matching what shard_rows
        returns for same-shaped local blocks)."""
        if jax.process_count() > 1 and not self.process_local:
            shape = (shape[0] * jax.process_count(),) + tuple(shape[1:])
        return _zeros_jit(tuple(shape), np.dtype(dtype), self.row_sharding())()

    def shard_table(self, padded, arrays: dict) -> dict:
        """Shard a dict of per-row arrays (all first-dim n_rows)."""
        return {k: self.shard_rows(v) for k, v in arrays.items()}


def _note_upload(arr) -> None:
    """Ledger hook for the placement helpers: a HOST array crossing to the
    device records its bytes + one transfer; an array that is already a
    jax.Array is a reshard, not a link transfer.  Bytes are the host
    array's (replication fan-out to N devices is a runtime detail below
    the accounting altitude)."""
    if isinstance(arr, jax.Array):
        return
    from ..utils.tracing import note_h2d
    a = np.asarray(arr)
    note_h2d(a.nbytes)


@functools.lru_cache(maxsize=None)
def _zeros_jit(shape, dtype, sharding):
    return jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sharding)


@functools.lru_cache(maxsize=None)
def _concat_jit(n_parts, sharding):
    return jax.jit(lambda parts: jnp.concatenate(parts, axis=0),
                   out_shardings=sharding)


# ---------------------------------------------------------------------------
# process-wide runtime context: set once by the CLI (distributed mode builds
# it over the hybrid mesh); everything else picks it up lazily
# ---------------------------------------------------------------------------

_runtime_ctx: Optional[MeshContext] = None
_runtime_ctx_explicit = False


def set_runtime_context(ctx: Optional[MeshContext]) -> None:
    global _runtime_ctx, _runtime_ctx_explicit
    _runtime_ctx = ctx
    _runtime_ctx_explicit = ctx is not None


def runtime_context() -> MeshContext:
    """The process-global MeshContext.  Defaults to a 1-D mesh over all
    devices; ``cli.run`` replaces it with a hybrid-mesh context under
    -Ddistributed.mode= / AVENIR_TPU_DISTRIBUTED=1 (and resets it after the
    job).  The lazy default is rebuilt when the backend's device count
    changes (e.g. a -Dplatform= switch between in-process runs), matching
    default_mesh()'s staleness rule; an explicitly-set context is never
    second-guessed."""
    global _runtime_ctx
    if _runtime_ctx is None or (
            not _runtime_ctx_explicit
            and _runtime_ctx.n_devices != len(jax.devices())):
        _runtime_ctx = MeshContext()
    return _runtime_ctx
