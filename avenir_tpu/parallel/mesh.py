"""Device mesh management: the execution substrate replacing the Hadoop/Spark
cluster (SURVEY.md §2.10).

Everything distributed in this framework runs over one `jax.sharding.Mesh`:

  * ``data`` axis — row parallelism: the analog of HDFS-block map parallelism.
    Batches are sharded over it; reductions psum across it (the shuffle).
  * ``chain`` axis (optional, folded into data by default) — independent-chain
    fan-out for optimizers/bandits (the analog of Spark mapPartitions).

Multi-host/multi-slice: the mesh is built from `jax.devices()`, which under
jax.distributed spans hosts; collectives ride ICI within a slice and DCN across
slices with no code change here.  On CPU the same code paths are exercised with
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (test conftest).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the first n) devices."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


_default_mesh: Optional[Mesh] = None


def default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None or len(_default_mesh.devices.flat) != len(jax.devices()):
        _default_mesh = make_mesh()
    return _default_mesh


class MeshContext:
    """Convenience wrapper bundling a mesh with sharding helpers.

    This is the runtime handle every job gets (the analog of the Hadoop
    ``Configuration`` + cluster connection in reference job drivers, e.g.
    tree/DecisionTreeBuilder.java:70-94).

    Works over a 1-D data mesh (the default) or the multi-host hybrid
    (hosts, data) mesh from ``distributed.make_hybrid_mesh`` — rows shard
    over ALL axes, reductions psum over all axes, so job code is portable
    between the two.
    """

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else default_mesh()
        axes = tuple(self.mesh.axis_names)
        # single string for a 1-D mesh (back-compat), tuple for hybrid —
        # both forms are accepted by PartitionSpec and lax.psum
        self.axis = axes[0] if len(axes) == 1 else axes

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def row_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.axis))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_rows(self, arr) -> jax.Array:
        """Place an array row-sharded over the mesh.  Row count must be a
        multiple of the mesh size (use ColumnarTable.pad_to_multiple first).
        Multi-process: ``arr`` is this process's equalized local block and
        the result is the global row-sharded array (multi-host ingest)."""
        if jax.process_count() > 1:
            from .distributed import from_process_local
            return from_process_local(np.asarray(arr), self.mesh)
        return jax.device_put(arr, self.row_sharding())

    def replicate(self, arr) -> jax.Array:
        return jax.device_put(arr, self.replicated_sharding())

    def zeros_rows(self, shape, dtype=np.float32) -> jax.Array:
        """Row-sharded zeros materialized ON DEVICE — no host transfer (a
        (100M, T) node-id init would otherwise ship gigabytes through the
        host link).  ``shape[0]`` follows the shard_rows contract: it is the
        per-process local row count, so multi-process runs produce a global
        array of process_count * shape[0] rows (matching what shard_rows
        returns for same-shaped local blocks)."""
        if jax.process_count() > 1:
            shape = (shape[0] * jax.process_count(),) + tuple(shape[1:])
        return _zeros_jit(tuple(shape), np.dtype(dtype), self.row_sharding())()

    def shard_table(self, padded, arrays: dict) -> dict:
        """Shard a dict of per-row arrays (all first-dim n_rows)."""
        return {k: self.shard_rows(v) for k, v in arrays.items()}


@functools.lru_cache(maxsize=None)
def _zeros_jit(shape, dtype, sharding):
    return jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sharding)


# ---------------------------------------------------------------------------
# process-wide runtime context: set once by the CLI (distributed mode builds
# it over the hybrid mesh); everything else picks it up lazily
# ---------------------------------------------------------------------------

_runtime_ctx: Optional[MeshContext] = None
_runtime_ctx_explicit = False


def set_runtime_context(ctx: Optional[MeshContext]) -> None:
    global _runtime_ctx, _runtime_ctx_explicit
    _runtime_ctx = ctx
    _runtime_ctx_explicit = ctx is not None


def runtime_context() -> MeshContext:
    """The process-global MeshContext.  Defaults to a 1-D mesh over all
    devices; ``cli.run`` replaces it with a hybrid-mesh context under
    -Ddistributed.mode= / AVENIR_TPU_DISTRIBUTED=1 (and resets it after the
    job).  The lazy default is rebuilt when the backend's device count
    changes (e.g. a -Dplatform= switch between in-process runs), matching
    default_mesh()'s staleness rule; an explicitly-set context is never
    second-guessed."""
    global _runtime_ctx
    if _runtime_ctx is None or (
            not _runtime_ctx_explicit
            and _runtime_ctx.n_devices != len(jax.devices())):
        _runtime_ctx = MeshContext()
    return _runtime_ctx
