"""The five communication idioms of the reference, TPU-native.

SURVEY.md §2.10 inventories every distributed mechanism the reference uses and
its TPU equivalent.  This module is that equivalence table as code:

  | reference mechanism                  | here                                |
  |--------------------------------------|-------------------------------------|
  | map over HDFS blocks                 | row-sharded arrays + jit (GSPMD)    |
  | shuffle groupBy -> reducer           | keyed_reduce / one-hot contraction  |
  | combiner (map-side pre-aggregation)  | automatic: per-shard partial sums   |
  |                                      | before the psum XLA inserts         |
  | broadcast of model/callback          | replicated arrays                   |
  | counters / accumulators              | counter_sum (psum'd scalar dict)    |
  | mapPartitions independent chains     | chain_fanout (shard_map)            |

Two styles are provided on purpose:

  * **GSPMD style** (preferred): write plain jnp math over row-sharded inputs
    and let XLA insert the collectives.  ``sharded_jit_reduce`` wraps that
    pattern: in_shardings=P('data') for batch args, out replicated.
  * **explicit style**: ``shard_map``-based wrappers for when the layout must
    be pinned (independent chains with per-device state, psum'd counters).

Where each idiom runs in production:

  * shard (map):        every model — ``MeshContext.shard_rows`` feeds the
                        tree/forest/bayes/KNN kernels
  * keyed reduce:       ``keyed_reduce`` in the eventTimeDistribution job;
                        the tree/bayes histograms are its one-hot-matmul
                        specialization inside their fused kernels
  * replicate:          split winners / child tables / model constants
                        (forest level loop, PathMatrix device consts)
  * scalar aggregate:   job counters all-reduce across processes in
                        ``cli.run`` (distributed.all_reduce_counters);
                        ``counter_sum`` is the in-program psum variant for
                        metrics that must not leave the device
  * chain fan-out:      SA/GA shard their independent chains/islands as a
                        leading array axis under GSPMD (optimize/annealing,
                        optimize/genetic) — the preferred form of this
                        idiom; ``chain_fanout`` is the explicit shard_map
                        alternative for per-device host state
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..telemetry import instant, span
from .mesh import MeshContext


# --------------------------------------------------------------------------
# idiom 1+2+4: sharded map + keyed reduce + scalar aggregate, GSPMD style
# --------------------------------------------------------------------------

def sharded_jit_reduce(fn: Callable, ctx: MeshContext,
                       n_batch_args: int = 1, donate: bool = False,
                       carry_args: tuple = ()):
    """jit ``fn(batch_arg0, ..., *replicated_args)`` with the first
    ``n_batch_args`` arguments row-sharded over the data axis and everything
    else replicated; outputs replicated.  XLA turns any full reduction inside
    into per-shard partials + all-reduce (the combiner+shuffle of the
    reference, e.g. MutualInformation.java:243's combiner, for free).

    ``donate=True`` donates every index in ``carry_args`` — a replicated
    running accumulator the caller rebinds each chunk, e.g.
    ``acc = red(oh, keys, acc)`` in the eventTimeDistribution job.  The
    carry's output twin has identical shape/dtype/sharding, so XLA
    updates the accumulator IN PLACE instead of the defensive copy it
    otherwise makes per dispatch.  The BATCH args are deliberately NOT
    donated: a reduction's replicated output can never alias a
    row-sharded batch input, so batch donation buys nothing on this jax
    (unusable donations aren't even freed early) and would only emit a
    'donated buffers were not usable' warning per compiled shape.
    Contract: the caller must place the carry with the matching sharding
    (``ctx.replicate``) and must NOT reuse it after the call — its
    buffer is invalidated, which tests/test_transfers.py pins so a jax
    upgrade cannot silently regress the API to copying again."""
    row = NamedSharding(ctx.mesh, P(ctx.axis))
    rep = NamedSharding(ctx.mesh, P())
    jitted_cache: Dict[int, Callable] = {}

    @functools.wraps(fn)
    def call(*args):
        jitted = jitted_cache.get(len(args))
        if jitted is None:
            in_sh = tuple(row if i < n_batch_args else rep for i in range(len(args)))
            jitted = jax.jit(
                fn, in_shardings=in_sh, out_shardings=rep,
                donate_argnums=tuple(carry_args) if donate else ())
            jitted_cache[len(args)] = jitted
        return jitted(*args)

    return call


def keyed_reduce(values: jnp.ndarray, keys: jnp.ndarray, num_keys: int,
                 mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """The shuffle: sum ``values`` (n, ...) into ``num_keys`` groups by key
    (n,) int32.  Invalid/padded rows carry mask=False.  Dense one-hot matmul
    formulation so XLA tiles it onto the MXU instead of scatter-adds.

    Equivalent of every reducer-side 'sum values per Tuple key' in the
    reference (e.g. bayesian/BayesianDistribution.java:273-281)."""
    onehot = jax.nn.one_hot(keys, num_keys, dtype=values.dtype)  # (n, k)
    if mask is not None:
        onehot = onehot * mask.astype(values.dtype)[:, None]
    # (k, n) @ (n, ...) -> (k, ...)
    return jnp.tensordot(onehot, values, axes=[[0], [0]])


def keyed_count(keys: jnp.ndarray, num_keys: int,
                mask: Optional[jnp.ndarray] = None,
                dtype=jnp.float32) -> jnp.ndarray:
    """Histogram of keys: the degenerate keyed_reduce with values=1."""
    onehot = jax.nn.one_hot(keys, num_keys, dtype=dtype)
    if mask is not None:
        onehot = onehot * mask.astype(dtype)[:, None]
    return onehot.sum(axis=0)


# --------------------------------------------------------------------------
# idiom 3: broadcast
# --------------------------------------------------------------------------

def replicate(ctx: MeshContext, tree):
    """Broadcast of a read-only model (SimulatedAnnealing.scala:85)."""
    return jax.tree_util.tree_map(ctx.replicate, tree)


# --------------------------------------------------------------------------
# idiom 4 explicit: counters
# --------------------------------------------------------------------------

def counter_sum(ctx: MeshContext, fn: Callable):
    """Wrap a per-shard fn returning a dict of scalar metrics; returns the
    psum across shards (Hadoop counters / Spark accumulators)."""
    def inner(*args):
        out = fn(*args)
        return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, ctx.axis), out)

    return shard_map(inner, mesh=ctx.mesh,
                     in_specs=P(ctx.axis), out_specs=P())


# --------------------------------------------------------------------------
# idiom 5: independent-chain fan-out (mapPartitions)
# --------------------------------------------------------------------------

def chain_fanout(ctx: MeshContext, step_fn: Callable,
                 state_specs: Any = None) -> Callable:
    """Run independent per-chain computations with chains sharded over the
    mesh: the analog of Spark mapPartitions running one SA/GA chain per
    executor (SimulatedAnnealing.scala:109, GeneticAlgorithm.scala:69).

    ``step_fn(state_tree)`` maps a pytree whose leaves have leading dim =
    total chains (divisible by mesh size) to a pytree of the same leading dim.
    Inside, each device sees only its chains; there is no cross-chain
    communication, so no collectives are emitted at all."""
    spec = P(ctx.axis) if state_specs is None else state_specs
    return jax.jit(shard_map(step_fn, mesh=ctx.mesh, in_specs=spec,
                             out_specs=spec))


# --------------------------------------------------------------------------
# segment top-k (secondary-sort replacement)
# --------------------------------------------------------------------------

def grouped_top_k(scores: jnp.ndarray, k: int, largest: bool = True):
    """Per-row top-k of a (groups, candidates) score matrix: replaces the
    reference's secondary sort (values arriving rank-sorted per key,
    knn/NearestNeighbor.java:80-81) with lax.top_k.
    Returns (values, indices), each (groups, k)."""
    s = scores if largest else -scores
    vals, idx = jax.lax.top_k(s, k)
    return (vals if largest else -vals), idx


# --------------------------------------------------------------------------
# cross-process all-reduce: the ONE collective per step of the sharded
# streaming pipelines (TF's parameter-aggregation design, PAPERS.md)
# --------------------------------------------------------------------------

class AllReducer:
    """One-collective-per-step aggregation of same-shaped per-process
    partials — the synchronization primitive of the sharded streaming
    builds (each host trains on its row-range shard; the ONLY cross-host
    traffic is one reduce of the stacked statistics per level/chunk/call).

    Transports, chosen at construction:

      * ``local``  — shard count 1: every op is the identity.  The call
        SITE still records into the ledger's ``Collectives`` group, so the
        one-all-reduce-per-level discipline is pinnable by a single-process
        test (the count is the number of synchronization points the
        algorithm pays, whatever the pod size).
      * ``jax``    — a joined ``jax.distributed`` run: ``sum`` rides a
        one-device-per-process mesh through ``sharded_jit_reduce`` (the
        stacked (P, ...) partials array is process-sharded; one jitted
        reduction with a DONATED device-resident accumulator carry — no
        defensive copy, no host round trip), falling back to the exact
        pickle-transport ``all_reduce_host_array`` off-mesh (uneven device
        sets, dtypes x64 would canonicalize away).  ``allgather`` is
        ``allgather_object``.
      * ``file``   — the jax.distributed-free lane (AVENIR_TPU_ALLREDUCE_DIR,
        or an explicit ``transport_dir``): plain processes/threads
        rendezvous through a step-indexed file barrier.  Exists because
        data-parallel CORRECTNESS (bit-identical models, split-point
        arithmetic, resume) is a property of the algorithm, not of the
        collective fabric — CI pins it without needing a coordinator.
        The first exchange runs a run-identity handshake
        (``_ensure_handshake``) so a transport dir reused across
        sequential runs cannot serve one run's leftover partials to the
        next; a dir shared by two CONCURRENT runs is still operator
        error (the handshake turns it into a loud timeout, not silence).

    Steps are strictly ordered per instance ``name``; every participant
    must construct the same reducers in the same order and call the same
    sequence of ops (lock-step is the contract, exactly as with a real
    collective)."""

    def __init__(self, spec=None, name: str = "reduce",
                 transport_dir: Optional[str] = None,
                 timeout_s: Optional[float] = None,
                 heartbeat_s: Optional[float] = None):
        from .distributed import shard_spec
        import os
        self.spec = spec if spec is not None else shard_spec()
        self.name = name
        # AVENIR_TPU_ALLREDUCE_TIMEOUT_S: how long a live shard waits for
        # a dead peer before failing its step (the file transport's
        # liveness bound; a crashed shard must not hang the others past
        # it — they fail loudly and the operator resumes the whole set)
        self.timeout_s = float(
            timeout_s if timeout_s is not None
            else os.environ.get("AVENIR_TPU_ALLREDUCE_TIMEOUT_S", 300.0))
        # stall heartbeat (AVENIR_TPU_STALL_HEARTBEAT_S): well BEFORE the
        # hard timeout, a wait that exceeds this emits a structured
        # ``allreduce.stall`` telemetry event + warning NAMING the shards
        # whose partials are missing — a stalled shard becomes a
        # diagnosable event instead of a silent hang (<=0 disables)
        self.heartbeat_s = float(
            heartbeat_s if heartbeat_s is not None
            else os.environ.get("AVENIR_TPU_STALL_HEARTBEAT_S",
                                min(self.timeout_s / 4.0, 15.0)))
        self.dir = transport_dir or os.environ.get(
            "AVENIR_TPU_ALLREDUCE_DIR")
        if self.spec.count == 1:
            self.transport = "local"
        elif self.dir:
            self.transport = "file"
            os.makedirs(self.dir, exist_ok=True)
        else:
            from .distributed import is_multiprocess
            if not is_multiprocess():
                raise ValueError(
                    f"shard count {self.spec.count} > 1 but neither "
                    f"jax.distributed is joined nor "
                    f"AVENIR_TPU_ALLREDUCE_DIR is set — partials would "
                    f"silently never combine")
            self.transport = "jax"
        self._step = 0
        self._proc_ctx = None      # lazily-built one-device-per-process mesh
        import uuid
        self._nonce = uuid.uuid4().hex   # this run's identity on the wire
        self._peers = None         # idx -> nonce, set by _ensure_handshake

    def fingerprint(self) -> str:
        """Topology identity for the pipeline ProgramCache key (TPU_NOTES
        §22): shard count + transport, NOT the shard index — every shard
        of one run compiles the identical per-chunk program, while a run
        under a different process count must miss (its collective
        schedule differs even though the local program body matches)."""
        return f"shards{self.spec.count}:{self.transport}"

    # ---- stall detection (the heartbeat half of the observability
    # contract: a dead peer is NAMED long before the hard timeout) ----
    def _emit_stall(self, phase: str, step: int, missing,
                    waited_s: float, on_thread=None) -> None:
        """One structured stall record: an ``allreduce.stall`` telemetry
        instant (when a tracer is installed) + a warning.  ``missing`` is
        the shard indices whose partials have not appeared (None when the
        transport cannot see per-peer progress, e.g. inside a device
        collective).  ``on_thread`` pins the trace event to the BLOCKED
        caller's lane when the emitter is a watchdog Timer thread."""
        import warnings
        missing_list = None if missing is None else sorted(missing)
        instant("allreduce.stall", cat="collective", on_thread=on_thread,
                reducer=self.name,
                transport=self.transport, phase=phase, step=int(step),
                shard=self.spec.index, count=self.spec.count,
                missing_shards=missing_list,
                waited_s=round(float(waited_s), 3),
                timeout_s=self.timeout_s)
        who = ("an unknown peer (transport cannot see per-shard progress)"
               if missing_list is None else
               f"shard(s) {missing_list}")
        warnings.warn(
            f"AllReducer[{self.name}] stall at {phase} step {step}: shard "
            f"{self.spec.index}/{self.spec.count} has waited "
            f"{waited_s:.1f}s for {who} (heartbeat {self.heartbeat_s}s, "
            f"hard timeout {self.timeout_s}s)", RuntimeWarning)

    def _watchdog(self, phase: str):
        """Context manager arming a one-shot stall timer around a
        transport call that blocks opaquely (the jax.distributed device
        psum / pickle allgather): if the collective has not completed
        within ``heartbeat_s`` a stall event fires — the transport cannot
        name the missing shard, but the operator learns WHICH collective
        wedged and when."""
        import contextlib

        @contextlib.contextmanager
        def arm():
            if self.transport != "jax" or self.heartbeat_s <= 0:
                yield
                return
            # the jax transport never goes through _file_exchange, so the
            # op ordinal is counted here — a stall report must say WHICH
            # collective of the run wedged, not "step 0" every time
            step = self._step
            self._step += 1
            done = threading.Event()
            t0 = time.monotonic()
            # the Timer fires on its own ephemeral thread — the stall
            # marker must land on the lane of the thread that is BLOCKED
            # in the collective, not a one-event Thread-N lane per stall
            caller = threading.current_thread()

            def bark():
                if not done.is_set():
                    self._emit_stall(phase, step, None,
                                     time.monotonic() - t0,
                                     on_thread=caller)
            timer = threading.Timer(self.heartbeat_s, bark)
            timer.daemon = True
            timer.start()
            try:
                yield
            finally:
                done.set()
                timer.cancel()
        return arm()

    def _probe_missing(self, stem: str):
        """Which peers have NOT yet produced a readable, current-run
        payload file for ``stem`` — the stall event's missing-shard set."""
        import pickle
        missing = []
        for j in range(self.spec.count):
            if j == self.spec.index:
                continue
            try:
                with open(self._fpath(stem, j), "rb") as fh:
                    if self._peers is not None and \
                            pickle.load(fh) != self._peers[j]:
                        missing.append(j)
            except (OSError, EOFError, pickle.UnpicklingError):
                missing.append(j)
        return missing

    # ---- public ops (each is ONE collective) ----
    def sum(self, arr: np.ndarray) -> np.ndarray:
        """Element-wise sum of a same-shaped per-process partial, exact in
        the input dtype.  One collective."""
        from ..utils.tracing import note_allreduce
        arr = np.asarray(arr)
        note_allreduce(arr.nbytes)
        with span("allreduce.sum", cat="collective", reducer=self.name,
                  transport=self.transport, nbytes=int(arr.nbytes),
                  shard=self.spec.index):
            if self.transport == "local":
                return arr
            if self.transport == "file":
                parts = self._file_exchange(arr)
                out = parts[0].copy()
                for p in parts[1:]:
                    out += p
                return out
            with self._watchdog("sum"):
                return self._jax_sum(arr)

    def allgather(self, obj):
        """Per-process list of ``obj`` in shard order.  One collective.
        The payload is pickled exactly once — the ledger byte count and
        the transport share the same buffer (KNN merges allgather
        multi-MB top-k lists per test chunk; serializing twice would
        double the host cost of the per-chunk collective)."""
        from ..utils.tracing import note_allreduce
        import pickle
        if self.transport == "local":
            note_allreduce(0)
            with span("allreduce.allgather", cat="collective",
                      reducer=self.name, transport=self.transport,
                      shard=self.spec.index):
                return [obj]
        buf = pickle.dumps(obj)
        note_allreduce(len(buf))
        with span("allreduce.allgather", cat="collective",
                  reducer=self.name, transport=self.transport,
                  nbytes=len(buf), shard=self.spec.index):
            if self.transport == "file":
                return self._file_exchange(obj, pickled=buf)
            from .distributed import allgather_object
            with self._watchdog("allgather"):
                return [pickle.loads(b) for b in allgather_object(buf)]

    def merge_topk(self, nd: np.ndarray, ni: np.ndarray, k: int):
        """Merge per-shard running nearest-k lists — the lock-step KNN
        collective: each shard contributes its (n_test, k_local) best
        (distance, GLOBAL train index) lists; every shard returns the
        identical global best-k.  One collective per call.

        Ties resolve to the lowest global train index: within a shard the
        fused scan already orders ties that way, shards concatenate in
        ascending index-range order, and the stable sort preserves it —
        exactly the single-host full-matrix argsort semantics."""
        with span("allreduce.merge_topk", cat="collective",
                  reducer=self.name, shard=self.spec.index, k=int(k)):
            return self._merge_topk(nd, ni, k)

    def _merge_topk(self, nd: np.ndarray, ni: np.ndarray, k: int):
        parts = self.allgather((np.asarray(nd), np.asarray(ni)))
        if len(parts) == 1:
            return nd, ni
        d_cat = np.concatenate([p[0] for p in parts], axis=1)
        i_cat = np.concatenate([p[1] for p in parts], axis=1)
        order = np.argsort(d_cat, axis=1, kind="stable")
        kk = min(k, d_cat.shape[1])
        take = order[:, :kk]
        return (np.take_along_axis(d_cat, take, axis=1),
                np.take_along_axis(i_cat, take, axis=1))

    # ---- jax transport ----
    def _jax_sum(self, arr: np.ndarray) -> np.ndarray:
        """Transport choice must be PROCESS-INDEPENDENT: a collective is
        a rendezvous, so every process must issue the same one in the
        same order — deciding from local data (e.g. this shard's max)
        would desync processes whose partials straddle the bound.  Hence:
        dtype alone picks the path (int32/float32 ride the device psum;
        int64 and anything x64-canonicalization would narrow take the
        exact pickle transport), and callers who want the device path for
        integer payloads narrow to int32 themselves from a globally
        agreed bound (``TreeBuilder._reduce_counts`` derives one from the
        global row count).  The device-path exception fallback is for
        conditions identical on every process (device-set shape, backend
        layout refusals) — the same try fails everywhere or nowhere."""
        from .distributed import all_reduce_host_array
        if arr.dtype not in (np.int32, np.float32):
            return all_reduce_host_array(arr)
        try:
            return self._jax_sum_device(arr)
        except Exception:
            # off-mesh (uneven devices per process, backend refusing the
            # layout): the exact host path is always available
            return all_reduce_host_array(arr)

    def _jax_sum_device(self, arr: np.ndarray) -> np.ndarray:
        import jax as _jax
        from jax.sharding import Mesh
        import numpy as _np
        if self._proc_ctx is None:
            by_proc: dict = {}
            for d in _jax.devices():
                by_proc.setdefault(getattr(d, "process_index", 0),
                                   []).append(d)
            if len(by_proc) != self.spec.count:
                raise RuntimeError("device set does not span every process")
            devs = [by_proc[p][0] for p in sorted(by_proc)]
            self._proc_ctx = MeshContext(Mesh(_np.array(devs), ("procs",)))
        ctx = self._proc_ctx
        red = _proc_sum_jit(ctx, arr.shape, arr.dtype.str)
        sharding = NamedSharding(ctx.mesh, P("procs"))
        parts = _jax.make_array_from_process_local_data(sharding, arr[None])
        from ..parallel.mesh import _zeros_jit
        acc = _zeros_jit(arr.shape, _np.dtype(arr.dtype),
                         NamedSharding(ctx.mesh, P()))()
        return np.asarray(red(parts, acc))

    # ---- file transport ----
    def _fpath(self, stem: str, idx: int) -> str:
        import os
        return os.path.join(self.dir, f"{self.name}-{stem}.{idx}.part")

    def _fwrite(self, path: str, head, body: bytes = b""):
        import os
        import pickle
        tmp = f"{path}.tmp-{os.getpid()}-{id(self)}"
        with open(tmp, "wb") as fh:
            fh.write(pickle.dumps(head))
            fh.write(body)
        os.replace(tmp, path)

    def _fread_wait(self, path: str, deadline: float, what: str,
                    missing_shard: Optional[int] = None,
                    phase: str = "handshake"):
        import pickle
        t_start = time.monotonic()
        hb_next = t_start + self.heartbeat_s if self.heartbeat_s > 0 \
            else None
        while True:
            try:
                with open(path, "rb") as fh:
                    return pickle.load(fh)
            except (OSError, EOFError, pickle.UnpicklingError):
                now = time.monotonic()
                if hb_next is not None and now >= hb_next and \
                        missing_shard is not None:
                    self._emit_stall(phase, self._step, [missing_shard],
                                     now - t_start)
                    hb_next = now + self.heartbeat_s
                if now > deadline:
                    raise RuntimeError(
                        f"AllReducer[{self.name}]: {what} never appeared "
                        f"at {path!r} within {self.timeout_s}s")
                time.sleep(0.005)

    def _ensure_handshake(self):
        """Run-identity handshake, lazily before the first exchange.

        A reused transport dir can hold a previous run's leftovers (the
        rolling reap keeps each shard's last two step files; a crash
        keeps everything) — without identity on the wire, a later run
        could read them as current-step payloads and silently sum a dead
        run's partials.  Each participant sweeps ITS OWN leftovers first
        (only it ever writes files carrying its index, so the sweep
        cannot race a live peer), announces a fresh per-run nonce, and
        blocks until every peer has echoed THAT nonce back — so nobody
        enters the payload exchange while any peer's view of it predates
        this run.  If our announce-read raced a peer's sweep we adopt
        the fresh nonce from its echo file and republish ours, which is
        what unblocks the peer in turn.  Payload files are tagged with
        the writer's nonce, and a reader treats a stale tag exactly like
        a missing file: leftovers can delay a step, never poison it."""
        import glob
        import os
        import time
        if self._peers is not None:
            return
        i = self.spec.index
        for f in glob.glob(os.path.join(self.dir,
                                        f"{self.name}-*.{i}.part")):
            try:
                os.remove(f)
            except OSError:
                pass
        self._fwrite(self._fpath("hello-a", i), self._nonce)
        deadline = time.monotonic() + self.timeout_s
        self._peers = {
            j: self._fread_wait(self._fpath("hello-a", j), deadline,
                                f"shard {j}'s announce", missing_shard=j)
            for j in range(self.spec.count)}
        self._fwrite(self._fpath("hello-b", i),
                     (self._nonce, dict(self._peers)))
        for j in range(self.spec.count):
            # own heartbeat for the ack spin: a READABLE hello-b echoing
            # a stale nonce (peer crashed after echoing a prior run)
            # returns from _fread_wait instantly, so ITS heartbeat never
            # fires — without this the wait is silent to the hard timeout
            t_ack = time.monotonic()
            hb_next = t_ack + self.heartbeat_s if self.heartbeat_s > 0 \
                else None
            while True:
                nonce_j, echo = self._fread_wait(
                    self._fpath("hello-b", j), deadline,
                    f"shard {j}'s acknowledgment", missing_shard=j)
                if nonce_j != self._peers[j]:
                    self._peers[j] = nonce_j
                    self._fwrite(self._fpath("hello-b", i),
                                 (self._nonce, dict(self._peers)))
                if echo.get(self.spec.index) == self._nonce:
                    break
                now = time.monotonic()
                if hb_next is not None and now >= hb_next:
                    self._emit_stall("handshake", self._step, [j],
                                     now - t_ack)
                    hb_next = now + self.heartbeat_s
                if now > deadline:
                    raise RuntimeError(
                        f"AllReducer[{self.name}] handshake: shard {j} "
                        f"never acknowledged this run within "
                        f"{self.timeout_s}s (peer died, or {self.dir!r} "
                        f"is shared with another live run)")
                time.sleep(0.005)

    def _file_exchange(self, obj, pickled: Optional[bytes] = None):
        """Step-barrier exchange: write this shard's nonce-tagged pickled
        payload (tmp-then-rename, so a visible file is always complete),
        wait for every peer's file of the same step, read them in shard
        order.  A participant entering step s has, by construction, read
        every peer's step-(s-1) file — so each process reaps its OWN
        step-(s-2) file, keeping the directory O(count) files."""
        import os
        import pickle
        import time
        self._ensure_handshake()
        step = self._step
        self._step += 1
        stem = f"{step:06d}"
        self._fwrite(self._fpath(stem, self.spec.index), self._nonce,
                     pickled if pickled is not None else pickle.dumps(obj))
        if step >= 2:
            try:
                os.remove(self._fpath(f"{step - 2:06d}", self.spec.index))
            except OSError:
                pass
        parts = []
        t_start = time.monotonic()
        deadline = t_start + self.timeout_s
        hb_next = t_start + self.heartbeat_s if self.heartbeat_s > 0 \
            else None
        for idx in range(self.spec.count):
            p = self._fpath(stem, idx)
            while True:
                try:
                    with open(p, "rb") as fh:
                        if pickle.load(fh) != self._peers[idx]:
                            # a previous run's leftover in a reused dir:
                            # stale == missing, keep waiting for this run's
                            raise EOFError("stale payload")
                        parts.append(pickle.load(fh))
                    break
                except (OSError, EOFError, pickle.UnpicklingError):
                    now = time.monotonic()
                    if hb_next is not None and now >= hb_next:
                        # name EVERY peer still missing at this instant,
                        # not just the one this loop happens to be on —
                        # the operator needs the full set of suspects
                        self._emit_stall("exchange", step,
                                         self._probe_missing(stem),
                                         now - t_start)
                        hb_next = now + self.heartbeat_s
                    if now > deadline:
                        raise RuntimeError(
                            f"AllReducer[{self.name}] step {step}: shard "
                            f"{idx} never produced {p!r} within "
                            f"{self.timeout_s}s (peer died or fell out of "
                            f"lock-step)")
                    time.sleep(0.005)
        return parts


@functools.lru_cache(maxsize=None)
def _proc_sum_jit(ctx: MeshContext, shape, dtype_str: str):
    """The device collective of ``AllReducer._jax_sum_device``: sum the
    process-sharded (P, ...) partials into a replicated result, with the
    zero-initialized accumulator DONATED (its output twin has identical
    shape/dtype/sharding, so XLA reduces into the buffer in place)."""
    return sharded_jit_reduce(lambda parts, acc: acc + parts.sum(axis=0),
                              ctx, n_batch_args=1, donate=True,
                              carry_args=(1,))
