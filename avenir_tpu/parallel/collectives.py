"""The five communication idioms of the reference, TPU-native.

SURVEY.md §2.10 inventories every distributed mechanism the reference uses and
its TPU equivalent.  This module is that equivalence table as code:

  | reference mechanism                  | here                                |
  |--------------------------------------|-------------------------------------|
  | map over HDFS blocks                 | row-sharded arrays + jit (GSPMD)    |
  | shuffle groupBy -> reducer           | keyed_reduce / one-hot contraction  |
  | combiner (map-side pre-aggregation)  | automatic: per-shard partial sums   |
  |                                      | before the psum XLA inserts         |
  | broadcast of model/callback          | replicated arrays                   |
  | counters / accumulators              | counter_sum (psum'd scalar dict)    |
  | mapPartitions independent chains     | chain_fanout (shard_map)            |

Two styles are provided on purpose:

  * **GSPMD style** (preferred): write plain jnp math over row-sharded inputs
    and let XLA insert the collectives.  ``sharded_jit_reduce`` wraps that
    pattern: in_shardings=P('data') for batch args, out replicated.
  * **explicit style**: ``shard_map``-based wrappers for when the layout must
    be pinned (independent chains with per-device state, psum'd counters).

Where each idiom runs in production:

  * shard (map):        every model — ``MeshContext.shard_rows`` feeds the
                        tree/forest/bayes/KNN kernels
  * keyed reduce:       ``keyed_reduce`` in the eventTimeDistribution job;
                        the tree/bayes histograms are its one-hot-matmul
                        specialization inside their fused kernels
  * replicate:          split winners / child tables / model constants
                        (forest level loop, PathMatrix device consts)
  * scalar aggregate:   job counters all-reduce across processes in
                        ``cli.run`` (distributed.all_reduce_counters);
                        ``counter_sum`` is the in-program psum variant for
                        metrics that must not leave the device
  * chain fan-out:      SA/GA shard their independent chains/islands as a
                        leading array axis under GSPMD (optimize/annealing,
                        optimize/genetic) — the preferred form of this
                        idiom; ``chain_fanout`` is the explicit shard_map
                        alternative for per-device host state
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from .mesh import MeshContext


# --------------------------------------------------------------------------
# idiom 1+2+4: sharded map + keyed reduce + scalar aggregate, GSPMD style
# --------------------------------------------------------------------------

def sharded_jit_reduce(fn: Callable, ctx: MeshContext,
                       n_batch_args: int = 1, donate: bool = False,
                       carry_args: tuple = ()):
    """jit ``fn(batch_arg0, ..., *replicated_args)`` with the first
    ``n_batch_args`` arguments row-sharded over the data axis and everything
    else replicated; outputs replicated.  XLA turns any full reduction inside
    into per-shard partials + all-reduce (the combiner+shuffle of the
    reference, e.g. MutualInformation.java:243's combiner, for free).

    ``donate=True`` donates every index in ``carry_args`` — a replicated
    running accumulator the caller rebinds each chunk, e.g.
    ``acc = red(oh, keys, acc)`` in the eventTimeDistribution job.  The
    carry's output twin has identical shape/dtype/sharding, so XLA
    updates the accumulator IN PLACE instead of the defensive copy it
    otherwise makes per dispatch.  The BATCH args are deliberately NOT
    donated: a reduction's replicated output can never alias a
    row-sharded batch input, so batch donation buys nothing on this jax
    (unusable donations aren't even freed early) and would only emit a
    'donated buffers were not usable' warning per compiled shape.
    Contract: the caller must place the carry with the matching sharding
    (``ctx.replicate``) and must NOT reuse it after the call — its
    buffer is invalidated, which tests/test_transfers.py pins so a jax
    upgrade cannot silently regress the API to copying again."""
    row = NamedSharding(ctx.mesh, P(ctx.axis))
    rep = NamedSharding(ctx.mesh, P())
    jitted_cache: Dict[int, Callable] = {}

    @functools.wraps(fn)
    def call(*args):
        jitted = jitted_cache.get(len(args))
        if jitted is None:
            in_sh = tuple(row if i < n_batch_args else rep for i in range(len(args)))
            jitted = jax.jit(
                fn, in_shardings=in_sh, out_shardings=rep,
                donate_argnums=tuple(carry_args) if donate else ())
            jitted_cache[len(args)] = jitted
        return jitted(*args)

    return call


def keyed_reduce(values: jnp.ndarray, keys: jnp.ndarray, num_keys: int,
                 mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """The shuffle: sum ``values`` (n, ...) into ``num_keys`` groups by key
    (n,) int32.  Invalid/padded rows carry mask=False.  Dense one-hot matmul
    formulation so XLA tiles it onto the MXU instead of scatter-adds.

    Equivalent of every reducer-side 'sum values per Tuple key' in the
    reference (e.g. bayesian/BayesianDistribution.java:273-281)."""
    onehot = jax.nn.one_hot(keys, num_keys, dtype=values.dtype)  # (n, k)
    if mask is not None:
        onehot = onehot * mask.astype(values.dtype)[:, None]
    # (k, n) @ (n, ...) -> (k, ...)
    return jnp.tensordot(onehot, values, axes=[[0], [0]])


def keyed_count(keys: jnp.ndarray, num_keys: int,
                mask: Optional[jnp.ndarray] = None,
                dtype=jnp.float32) -> jnp.ndarray:
    """Histogram of keys: the degenerate keyed_reduce with values=1."""
    onehot = jax.nn.one_hot(keys, num_keys, dtype=dtype)
    if mask is not None:
        onehot = onehot * mask.astype(dtype)[:, None]
    return onehot.sum(axis=0)


# --------------------------------------------------------------------------
# idiom 3: broadcast
# --------------------------------------------------------------------------

def replicate(ctx: MeshContext, tree):
    """Broadcast of a read-only model (SimulatedAnnealing.scala:85)."""
    return jax.tree_util.tree_map(ctx.replicate, tree)


# --------------------------------------------------------------------------
# idiom 4 explicit: counters
# --------------------------------------------------------------------------

def counter_sum(ctx: MeshContext, fn: Callable):
    """Wrap a per-shard fn returning a dict of scalar metrics; returns the
    psum across shards (Hadoop counters / Spark accumulators)."""
    def inner(*args):
        out = fn(*args)
        return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, ctx.axis), out)

    return shard_map(inner, mesh=ctx.mesh,
                     in_specs=P(ctx.axis), out_specs=P())


# --------------------------------------------------------------------------
# idiom 5: independent-chain fan-out (mapPartitions)
# --------------------------------------------------------------------------

def chain_fanout(ctx: MeshContext, step_fn: Callable,
                 state_specs: Any = None) -> Callable:
    """Run independent per-chain computations with chains sharded over the
    mesh: the analog of Spark mapPartitions running one SA/GA chain per
    executor (SimulatedAnnealing.scala:109, GeneticAlgorithm.scala:69).

    ``step_fn(state_tree)`` maps a pytree whose leaves have leading dim =
    total chains (divisible by mesh size) to a pytree of the same leading dim.
    Inside, each device sees only its chains; there is no cross-chain
    communication, so no collectives are emitted at all."""
    spec = P(ctx.axis) if state_specs is None else state_specs
    return jax.jit(shard_map(step_fn, mesh=ctx.mesh, in_specs=spec,
                             out_specs=spec))


# --------------------------------------------------------------------------
# segment top-k (secondary-sort replacement)
# --------------------------------------------------------------------------

def grouped_top_k(scores: jnp.ndarray, k: int, largest: bool = True):
    """Per-row top-k of a (groups, candidates) score matrix: replaces the
    reference's secondary sort (values arriving rank-sorted per key,
    knn/NearestNeighbor.java:80-81) with lax.top_k.
    Returns (values, indices), each (groups, k)."""
    s = scores if largest else -scores
    vals, idx = jax.lax.top_k(s, k)
    return (vals if largest else -vals), idx
