"""Multi-host distributed backend (SURVEY.md §5 'distributed communication
backend'): the rebuild's answer to the reference's Hadoop/Spark cluster
runtime, built on jax.distributed + GSPMD.

Scaling model: within a slice, collectives ride ICI; across slices/hosts they
ride DCN.  The data axis should span ICI (fast all-reduce of histogram
partials), a host/slice axis spans DCN and only replicated/small state
crosses it — ``make_hybrid_mesh`` encodes exactly that split.

Everything degrades to single-process: ``initialize()`` is a no-op without
coordinator info, and ``from_process_local`` falls back to ``device_put``
when there is one process, so the same job code runs on a laptop CPU mesh,
one TPU chip, or a multi-host pod.

Multi-host contract (validated by a true 2-process CPU test,
tests/test_distributed.py): each process loads its own EQUAL-SIZE input
shard; device reductions over the resulting global arrays give every
process the identical global result (models computed this way are
bit-identical across processes), and host-side tallies go through
``all_reduce_counters`` before rendering (cli.run does this, printing on
process 0 only).  Per-record outputs (prediction part files) are written
per process as part-m-<process_index> — the Hadoop one-part-per-task
layout (core/artifacts.write_text_output); training jobs whose artifact
is the global model write identical bytes on every process.

Every registered job carries an explicit multi-process mode
(cli.jobs.register ``dist=``), enforced by cli.run under
``jax.process_count() > 1``:

  * ``sharded`` — the job consumes its local shard and produces global
    results internally (device reductions over sharded global arrays, or
    explicit collectives: NB, trees/forest, MI, numerical correlation,
    Apriori support counting);
  * ``gather`` — host-side global computation: cli.run allgathers the
    per-process input FILES into a local spool dir first
    (``allgather_object`` transport, basenames preserved), so every
    process computes over the FULL input and writes the identical full
    output — the reference's shuffle gave host-side reductions the same
    global view.  Take process 0's output (its counters are already
    global: cli.run skips the counter all-reduce for gather jobs).  The
    dataset is the UNION of the per-process inputs: feed distinct shards
    (or the whole file on one process and empty shards elsewhere);
    replicating the same file to every process double-counts it;
  * ``map`` — per-record transform over the local shard; per-process
    part-m files are the correct Hadoop layout;
  * ``partition`` — global input view (gather-style spool when shards
    differ; an identical shared-fs input used as-is) but the job splits
    its WORK by ``work_slice`` — SA chains / GA islands / the KNN test
    axis — the reference's Spark mapPartitions executor semantics
    (spark SimulatedAnnealing.scala:109, GeneticAlgorithm.scala:69).
    Counters are per-process partials (cli.run all-reduces them);
    'set'-style counters are emitted only by the slice owning item 0 so
    the sum reproduces the value.

A job with no mode (or an explicit ``refuse``) is rejected loudly under
multi-process instead of silently emitting shard-local results.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# set once this process successfully joins a multi-process run
_joined = False


def process_count() -> int:
    """jax.process_count with a fallback for jax builds lacking it."""
    return getattr(jax, "process_count", lambda: 1)()


def is_multiprocess() -> bool:
    return process_count() > 1


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               auto: Optional[bool] = None) -> bool:
    """Join (or skip joining) a multi-host run.

    Three modes:
      * explicit: pass the full (coordinator, num_processes, process_id)
        triple, or set JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
        JAX_PROCESS_ID;
      * auto-detect (``auto=True`` or AVENIR_TPU_DISTRIBUTED=1): bare
        ``jax.distributed.initialize()`` — on TPU pod runtimes the cluster
        is discovered from the environment;
      * neither: single-process no-op, returns False.
    A partially-specified explicit config raises instead of silently
    running single-process (each host computing 'global' results over only
    its own shard is the worst failure mode of this module)."""
    # idempotent: chained CLI runs in one process (level-wise Apriori,
    # pipeline scripts) re-enter distributed mode per job; the first join
    # holds for the process lifetime.  NOTE: must not touch jax.process_count
    # before the actual join — it would initialize the XLA backend and
    # jax.distributed.initialize refuses to run after that
    global _joined
    if _joined:
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        np_env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(np_env) if np_env else None
    if process_id is None:
        pid_env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(pid_env) if pid_env else None
    if coordinator_address and num_processes and num_processes > 1:
        if process_id is None:
            raise ValueError("coordinator + num_processes set but no "
                             "process id (JAX_PROCESS_ID)")
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        _joined = True
        return True
    if coordinator_address and num_processes is None:
        raise ValueError("JAX_COORDINATOR_ADDRESS set without "
                         "JAX_NUM_PROCESSES; refusing to run single-process")
    if num_processes and num_processes > 1 and not coordinator_address:
        raise ValueError("JAX_NUM_PROCESSES > 1 without a coordinator "
                         "address; refusing to run single-process (each host "
                         "would compute 'global' results over its own shard)")
    if auto is None:
        auto = os.environ.get("AVENIR_TPU_DISTRIBUTED") == "1"
    if auto:
        jax.distributed.initialize()  # pod runtimes self-discover
        _joined = True
        return jax.process_count() > 1
    return False


def make_hybrid_mesh(data_axis: str = "data", host_axis: str = "hosts",
                     devices: Optional[Sequence] = None) -> Mesh:
    """(hosts, data) mesh: the data axis stays within a host/slice (ICI),
    the host axis spans DCN.  Single-host: a 1 x n mesh with the same axis
    names, so shardings written against it are portable."""
    devs = list(devices if devices is not None else jax.devices())
    # group by owning process so a mesh row NEVER mixes hosts (each row =
    # one host's ICI domain; the column axis is the only one crossing DCN)
    by_host: dict = {}
    for d in devs:
        by_host.setdefault(getattr(d, "process_index", 0), []).append(d)
    per_host = min(len(g) for g in by_host.values())
    if any(len(g) != per_host for g in by_host.values()):
        import warnings
        warnings.warn(
            f"uneven devices per host {sorted(len(g) for g in by_host.values())}; "
            f"truncating every host to {per_host}")
    grid = np.array([by_host[h][:per_host] for h in sorted(by_host)])
    return Mesh(grid, (host_axis, data_axis))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over every mesh axis (host x data) — the HDFS-block
    analog: each host/device owns a contiguous row range."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def from_process_local(local_rows: np.ndarray, mesh: Mesh):
    """Build a globally row-sharded array from each process's local rows —
    the multi-host ingest path (each host reads its own CSV shard, the
    global array is the concatenation; reference analog: HDFS blocks feeding
    data-local mappers).  Single-process: device_put with the same
    sharding.

    Local blocks MUST have equal row counts across processes: with unequal
    blocks jax.make_array_from_process_local_data builds a DIFFERENT global
    shape on each process and reductions return garbage with no error
    (verified on a 2-process CPU run).  The guard allgathers the row count
    (one tiny collective per ingest) and fails loudly instead."""
    sharding = row_sharding(mesh)
    if not is_multiprocess():
        return jax.device_put(local_rows, sharding)
    from jax.experimental import multihost_utils
    shapes = np.asarray(multihost_utils.process_allgather(
        np.array(local_rows.shape, dtype=np.int64)))   # (P, ndim)
    if not (shapes == shapes[0]).all():
        raise ValueError(
            f"per-process local shapes differ: {shapes.tolist()} — equalize "
            f"the input shards (pad or rebalance rows; fix column-count "
            f"drift) before ingest; mismatched blocks silently corrupt the "
            f"global array")
    return jax.make_array_from_process_local_data(sharding, local_rows)


def shard_rows(n_rows: int, index: int, count: int,
               chunk_rows: int = 1) -> Tuple[int, int]:
    """Contiguous source-row range ``[lo, hi)`` owned by shard ``index`` of
    ``count`` over an ``n_rows``-row source — THE split-point rule of the
    sharded streaming ingest (every caller must use it so two processes can
    never disagree about who owns a row).

    Split points are aligned to the ``chunk_rows`` grid: the grid is exactly
    the ``source_row_end`` accounting every streamed chunk reports (the PR 2
    checkpoint/resume axis), so a shard always consumes WHOLE ingest blocks
    — no mid-chunk truncation, and a bad record (counted on the source-row
    axis like any other row) belongs to exactly one shard, which is what
    makes per-shard quarantine tallies sum to the single-host totals.

    Properties (pinned by tests/test_sharded_stream.py):
      * ranges are disjoint and their union is ``[0, n_rows)``;
      * more shards than blocks leaves the extras EMPTY (``lo == hi``) —
        an empty shard is a valid degenerate participant, not an error;
      * the last shard absorbs the tail remainder block.
    """
    if count < 1:
        raise ValueError(f"shard count must be >= 1, got {count}")
    if not 0 <= index < count:
        raise ValueError(f"shard index {index} outside [0, {count})")
    if chunk_rows < 1:
        raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
    if n_rows < 0:
        raise ValueError(f"n_rows must be >= 0, got {n_rows}")
    blocks = -(-n_rows // chunk_rows)      # ceil: tail remainder is a block
    lo_b = blocks * index // count
    hi_b = blocks * (index + 1) // count
    return (min(lo_b * chunk_rows, n_rows),
            min(hi_b * chunk_rows, n_rows))


@dataclass(frozen=True)
class ShardSpec:
    """This process's identity in a row-range-sharded run: ``(index,
    count)``.  ``count == 1`` is the single-host degenerate (every shard
    helper becomes the identity)."""

    index: int = 0
    count: int = 1

    def __post_init__(self):
        if self.count < 1 or not 0 <= self.index < self.count:
            raise ValueError(f"bad shard spec {self.index}/{self.count}")

    @property
    def active(self) -> bool:
        return self.count > 1

    def range_for(self, n_rows: int, chunk_rows: int = 1) -> Tuple[int, int]:
        return shard_rows(n_rows, self.index, self.count, chunk_rows)


def shard_spec() -> ShardSpec:
    """The shard identity of THIS process: ``jax.process_index/count``
    under a joined multi-process run; the ``AVENIR_TPU_SHARD=i/P`` override
    for the jax.distributed-free smoke lane (two plain subprocesses
    exchanging partials through ``parallel.collectives.AllReducer``'s file
    transport); ``0/1`` otherwise.  The env override wins so the smoke
    lane can never be silently demoted to single-shard by a container
    where ``jax.distributed`` cannot rendezvous."""
    env = os.environ.get("AVENIR_TPU_SHARD")
    if env:
        try:
            i, _, p = env.partition("/")
            return ShardSpec(int(i), int(p))
        except ValueError as exc:
            raise ValueError(
                f"AVENIR_TPU_SHARD must look like 'index/count', got "
                f"{env!r}") from exc
    if is_multiprocess():
        return ShardSpec(jax.process_index(), process_count())
    return ShardSpec()


def work_slice(n: int):
    """This process's contiguous [lo, hi) share of ``n`` independent work
    items (annealing chains, GA islands, test rows) — the reference's Spark
    mapPartitions executor split as an index range.  Single-process:
    (0, n).  ``lo == 0 and hi > 0`` uniquely identifies the process owning
    item 0 (use it to emit global 'set'-style counters exactly once, so the
    cross-process counter SUM reproduces the value)."""
    p, total = (jax.process_index(), process_count()) \
        if is_multiprocess() else (0, 1)
    return n * p // total, n * (p + 1) // total


def allgather_object(obj):
    """All-gather an arbitrary picklable host object across processes,
    returning the per-process list in process order (single-process:
    ``[obj]``).  The transport is the device collective fabric
    (``multihost_utils.process_allgather`` over a padded uint8 buffer) —
    the same path the reference's shuffle rides, no side channel to
    configure.  Intended for SMALL host-side state: vocabularies,
    candidate sets, per-shard tallies — not bulk data."""
    if not is_multiprocess():
        return [obj]
    import pickle
    from jax.experimental import multihost_utils
    data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # size exchange as (hi, lo) int31 halves: process_allgather
    # canonicalizes int64 to int32 when jax_enable_x64 is off (this repo
    # never enables it), and a single int32 would cap payloads at 2 GiB —
    # cli.run's gather spool ships whole input shards through here
    hi_lo = np.array([data.size >> 31, data.size & 0x7FFFFFFF],
                     dtype=np.int32)
    pairs = np.asarray(multihost_utils.process_allgather(hi_lo)
                       ).reshape(-1, 2).astype(np.int64)
    sizes = (pairs[:, 0] << 31) + pairs[:, 1]
    buf = np.zeros((int(sizes.max()),), dtype=np.uint8)
    buf[:data.size] = data
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    return [pickle.loads(gathered[p, :sizes[p]].tobytes())
            for p in range(len(sizes))]


def all_reduce_host_array(x: np.ndarray) -> np.ndarray:
    """Element-wise sum of a same-shaped host array across processes
    (Apriori support counts, per-shard histograms), EXACT in the input
    dtype: the transport is the pickled-object path, because
    ``process_allgather`` canonicalizes int64/float64 down to 32 bits when
    jax_enable_x64 is off and would silently wrap counts past 2^31.
    Single-process: ``np.asarray(x)`` unchanged."""
    x = np.asarray(x)
    if not is_multiprocess():
        return x
    parts = allgather_object(x)
    out = parts[0].copy()
    for p in parts[1:]:
        out += p
    return out


def all_reduce_counters(counters):
    """Sum a Counters object across all processes (Hadoop counters are
    global; host-side tallies — validation counts, emitted-line counts —
    are per-process under multi-host and must be reduced before rendering).
    Single-process: identity.  Keys must match across processes (they do:
    every process runs the same job)."""
    if not is_multiprocess():
        return counters
    from jax.experimental import multihost_utils
    items = sorted(counters._c.items())
    vals = np.array([v for _, v in items], dtype=np.int64)
    summed = np.asarray(multihost_utils.process_allgather(vals)).sum(axis=0)
    for (key, _), v in zip(items, summed):
        counters._c[key] = int(v)
    return counters
