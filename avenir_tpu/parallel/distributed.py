"""Multi-host distributed backend (SURVEY.md §5 'distributed communication
backend'): the rebuild's answer to the reference's Hadoop/Spark cluster
runtime, built on jax.distributed + GSPMD.

Scaling model: within a slice, collectives ride ICI; across slices/hosts they
ride DCN.  The data axis should span ICI (fast all-reduce of histogram
partials), a host/slice axis spans DCN and only replicated/small state
crosses it — ``make_hybrid_mesh`` encodes exactly that split.

Everything degrades to single-process: ``initialize()`` is a no-op without
coordinator info, and ``from_process_local`` falls back to ``device_put``
when there is one process, so the same job code runs on a laptop CPU mesh,
one TPU chip, or a multi-host pod.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join (or skip joining) a multi-host run.

    Arguments default to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID — also set by TPU pod runtimes
    automatically).  Returns True when a multi-process runtime was
    initialized, False for the single-process fallback."""
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        np_env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(np_env) if np_env else None
    if process_id is None:
        pid_env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(pid_env) if pid_env else None
    if not coordinator_address or not num_processes or num_processes <= 1:
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def make_hybrid_mesh(data_axis: str = "data", host_axis: str = "hosts",
                     devices: Optional[Sequence] = None) -> Mesh:
    """(hosts, data) mesh: the data axis stays within a host/slice (ICI),
    the host axis spans DCN.  Single-host: a 1 x n mesh with the same axis
    names, so shardings written against it are portable."""
    devs = list(devices if devices is not None else jax.devices())
    n_hosts = max(getattr(jax, "process_count", lambda: 1)(), 1)
    per_host = len(devs) // n_hosts
    if per_host == 0:
        raise ValueError(f"{len(devs)} devices across {n_hosts} hosts: "
                         "fewer devices than hosts")
    if per_host * n_hosts != len(devs):
        # uneven layout: use the largest even grid, dropping the remainder
        # loudly rather than crashing in a reshape
        import warnings
        warnings.warn(f"{len(devs)} devices not divisible by {n_hosts} "
                      f"hosts; using {per_host * n_hosts} devices")
        devs = devs[:per_host * n_hosts]
    if n_hosts > 1 and per_host * n_hosts == len(devs):
        try:
            from jax.experimental import mesh_utils
            arr = mesh_utils.create_hybrid_device_mesh(
                (per_host,), (n_hosts,), devices=devs)
            # create_hybrid_device_mesh returns (dcn, ici)-ordered axes
            return Mesh(arr.reshape(n_hosts, per_host),
                        (host_axis, data_axis))
        except Exception:
            pass
    grid = np.array(devs).reshape(1, len(devs)) if n_hosts == 1 else \
        np.array(devs).reshape(n_hosts, per_host)
    return Mesh(grid, (host_axis, data_axis))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over every mesh axis (host x data) — the HDFS-block
    analog: each host/device owns a contiguous row range."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def from_process_local(local_rows: np.ndarray, mesh: Mesh):
    """Build a globally row-sharded array from each process's local rows —
    the multi-host ingest path (each host reads its own CSV shard, the
    global array is the concatenation; reference analog: HDFS blocks feeding
    data-local mappers).  Single-process: device_put with the same
    sharding."""
    sharding = row_sharding(mesh)
    if getattr(jax, "process_count", lambda: 1)() <= 1:
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(sharding, local_rows)
