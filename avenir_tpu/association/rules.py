"""Association rule mining from frequent itemsets.

Parity target: AssociationRuleMiner (association/AssociationRuleMiner.java).
Input lines are frequent itemsets with their support as the last field
(mapper :113-127).  For every itemset of size > 1, each non-empty proper
sub-list of size <= ``max_antecedent_size`` is an antecedent; the set
difference is the consequent; confidence = support(itemset) /
support(antecedent), emitted when strictly above the threshold
(reducer :182-195) as ``ante_items -> cons_items``.

The reference resolves antecedent support by a secondary-sort join (tag 0 =
support record sorts first, :124,140); here it is a host-side dict lookup —
rules whose antecedent is not itself a frequent itemset in the input are
skipped (the reference would silently reuse a stale ``anteSupport`` in that
case; we require the correct join).
"""

from __future__ import annotations

from itertools import combinations
from typing import Dict, List, Optional, Sequence, Tuple


def generate_sublists(items: Sequence[str], max_size: int
                      ) -> List[Tuple[str, ...]]:
    """All non-empty proper sub-lists up to ``max_size`` elements, preserving
    input order (chombo Utility.generateSublists as used at :133)."""
    n = len(items)
    out: List[Tuple[str, ...]] = []
    for size in range(1, min(max_size, n - 1) + 1):
        out.extend(combinations(items, size))
    return out


def parse_frequent_lines(lines: Sequence[str], delim: str = ",",
                         has_count: bool = False,
                         itemset_length: Optional[int] = None
                         ) -> List[Tuple[Tuple[str, ...], float]]:
    """``items...,support`` lines (mapper :113-118: all fields except the
    last are items).  ``has_count`` additionally strips the count column the
    count-mode Apriori output carries before the support; ``itemset_length``
    caps the item fields instead (for trans-id-mode Apriori output whose
    middle columns are transaction ids)."""
    out = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        tokens = line.split(delim)
        if itemset_length is not None:
            items = tuple(tokens[:itemset_length])
        else:
            items = tuple(tokens[:-2] if has_count else tokens[:-1])
        support = float(tokens[-1])
        out.append((items, support))
    return out


def mine_rules(frequent: Sequence[Tuple[Tuple[str, ...], float]],
               confidence_threshold: float, max_antecedent_size: int = 3,
               delim: str = ",", with_confidence: bool = False
               ) -> List[str]:
    """Rule lines ``ante -> cons`` (reducer :191).  ``with_confidence``
    appends the confidence (extension; default matches reference output)."""
    support: Dict[Tuple[str, ...], float] = {}
    for items, sup in frequent:
        support[tuple(sorted(items))] = sup

    rules: List[str] = []
    for items, total_support in frequent:
        if len(items) <= 1:
            continue
        for ante in generate_sublists(list(items), max_antecedent_size):
            ante_support = support.get(tuple(sorted(ante)))
            if ante_support is None or ante_support <= 0.0:
                continue
            confidence = total_support / ante_support
            if confidence > confidence_threshold:
                ante_set = set(ante)
                cons = [it for it in items if it not in ante_set]
                line = f"{delim.join(ante)} -> {delim.join(cons)}"
                if with_confidence:
                    line += f"{delim}{confidence:.3f}"
                rules.append(line)
    return rules
