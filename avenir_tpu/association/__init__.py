"""Association-rule pack: frequent itemset mining + rule mining.

Parity targets (SURVEY.md §2.8 `association`):
  * FrequentItemsApriori  (association/FrequentItemsApriori.java:89-343)
  * InfrequentItemMarker  (association/InfrequentItemMarker.java:77-141)
  * AssociationRuleMiner  (association/AssociationRuleMiner.java:87-197)
  * ItemSetList           (association/ItemSetList.java:34-102)
"""

from .itemsets import (ItemSet, TransactionMatrix, apriori_level,
                       format_itemset_lines, frequent_itemsets,
                       mark_infrequent, parse_itemset_lines,
                       read_transactions)
from .rules import generate_sublists, mine_rules

__all__ = [
    "ItemSet", "TransactionMatrix", "apriori_level", "format_itemset_lines",
    "frequent_itemsets", "mark_infrequent", "parse_itemset_lines",
    "read_transactions", "generate_sublists", "mine_rules",
]
