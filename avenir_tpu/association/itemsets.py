"""Frequent itemset mining — level-wise Apriori, TPU formulation.

Reference behavior (association/FrequentItemsApriori.java):
  * level 1: count each item's transactions (mapper :138-150, reducer :306-341)
  * level k: extend each frequent (k-1)-itemset with every co-occurring item
    of a transaction that contains it, dedup by sorted item tuple, count
    distinct supporting transactions (mapper :151-218, reducer :306-341)
  * emit only itemsets with support strictly above ``fia.support.threshold``
    (reducer :331); support printed with 3 decimals (:334-338)
  * itemset file format parsed back by ItemSetList (ItemSetList.java:73-84):
    ``item...,transId...,support`` (trans ids optional)

TPU design: instead of a shuffle keyed on item tuples, transactions are
encoded once as a boolean membership matrix ``M (n_trans, n_items)`` over the
item vocabulary.  The support count of a k-item candidate set ``C`` is

    count(C) = sum_t  prod_{j<k} M[t, C_j]

computed for ALL candidates at once as k gathered column blocks multiplied
elementwise and summed over transactions — a dense batched reduction that XLA
tiles onto the VPU/MXU, no host-side hashing in the hot path.  Candidate
generation (combinatorial, data-dependent shapes) stays host-side, exactly as
the reference keeps it in the mapper.

Note on count-mode parity: with ``fia.emit.trans.id=false`` the reference
counts *emissions*, which double-counts a transaction that reaches the same
k-itemset via several (k-1)-subsets (mapper :160-194 has no per-transaction
dedup).  We always compute the exact distinct-transaction support — identical
to the reference's transaction-id mode, which is its accurate path.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp


@jax.jit
def _support_kernel_mxu(M, C):
    """Candidate support partial counts: (chunk, V) 0/1 membership matrix
    x (n_cand, k) index sets -> (n_cand,) f32 counts — MXU formulation.

    Because membership is 0/1 and candidates are SETS,
    ``prod_j M[t, c_j] == (sum_j M[t, c_j] == k)`` — so support counting
    is ONE matmul against the multi-hot candidate matrix followed by an
    equality test, instead of k column-gathers (gathers lower to scalar
    loops on TPU, the r2/r3 anti-pattern).  The (n_cand, V) candidate
    matrix is built by scatter-add directly — ``one_hot(C, V)`` would
    materialize an (n_cand, k, V) f32 intermediate before the axis-1 sum,
    a k-fold memory blowup for a matrix the scatter writes in one pass
    (ADVICE r5).  All intermediate values are small integers (<= k <=
    vocab), exact in any matmul precision.  M arrives uint8 (4x less
    host->device link than f32) and upcasts here.  Module-level jit so
    each Apriori level (and each chunk) reuses ONE compiled program per
    shape instead of recompiling per call."""
    k = C.shape[1]
    V = M.shape[1]
    rows = jnp.arange(C.shape[0], dtype=C.dtype)[:, None]     # (n_cand, 1)
    K = jnp.zeros((C.shape[0], V), jnp.float32).at[rows, C].add(1.0)
    hits = M.astype(jnp.float32) @ K.T                        # (chunk, n_cand)
    return (hits == float(k)).astype(jnp.float32).sum(axis=0)


@jax.jit
def _support_kernel_gather(M, C):
    """Same counts via k column-gathers and a running product — the CPU
    formulation (the dense matmul does V/k x more arithmetic, a measured
    ~1.5x loss on the 1-core backend; the gather is what vectorizes well
    there).  Counts are identical to the MXU form: exact small ints."""
    Mf = M.astype(jnp.float32)
    acc = jnp.ones((M.shape[0], C.shape[0]), dtype=jnp.float32)
    for j in range(C.shape[1]):        # k is tiny and static
        acc = acc * Mf[:, C[:, j]]
    return acc.sum(axis=0)


def _support_kernel(M, C, platform: Optional[str] = None):
    """Platform dispatch (same auto-gate as the NB wire form, which reads
    ``MeshContext.device_platform``): the MXU matmul form on a real
    device, the gather form on cpu.  ``platform`` is the RUNTIME MESH's
    device platform — dispatching on ``jax.devices()[0]`` (the global
    default backend) would pick the wrong form whenever the mesh context
    runs on a different backend than the process default (ADVICE r5)."""
    if platform is None:
        from ..parallel.mesh import runtime_context
        platform = runtime_context().device_platform
    if platform == "cpu":
        return _support_kernel_gather(M, C)
    return _support_kernel_mxu(M, C)


@dataclass
class ItemSet:
    """One frequent itemset (ItemSetList.java:65-101)."""
    items: Tuple[str, ...]
    trans_ids: List[str] = dc_field(default_factory=list)
    support: float = 0.0
    count: int = 0

    def contains_item(self, item: str) -> bool:
        return item in self.items

    def contains_trans(self, trans_id: str) -> bool:
        return trans_id in self.trans_ids


def parse_itemset_lines(lines: Sequence[str], itemset_length: int,
                        contains_trans_ids: bool, delim: str = ","
                        ) -> List[ItemSet]:
    """Parse the per-level itemset file (ItemSetList.java:45-55): first
    ``itemset_length`` tokens are items; if ``contains_trans_ids`` the tokens
    up to the last are transaction ids; the last token is the support."""
    out: List[ItemSet] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        tokens = line.split(delim)
        items = tuple(tokens[:itemset_length])
        trans: List[str] = []
        if contains_trans_ids:
            trans = list(tokens[itemset_length:-1])
        try:
            support = float(tokens[-1])
        except ValueError:
            support = 0.0
        out.append(ItemSet(items, trans, support))
    return out


def _fmt_support(v: float) -> str:
    """Utility.formatDouble(support, 3)."""
    return f"{v:.3f}"


def format_itemset_lines(itemsets: Sequence[ItemSet], emit_trans_id: bool,
                         trans_id_output: bool, delim: str = ","
                         ) -> List[str]:
    """Reducer output layout (FrequentItemsApriori.java:331-340):
    trans-id mode w/ ids: ``items...,transIds...,support``;
    trans-id mode w/o ids: ``items...,support``;
    count mode: ``items...,count,support``."""
    lines = []
    for s in itemsets:
        parts = list(s.items)
        if emit_trans_id:
            if trans_id_output:
                parts.extend(s.trans_ids)
        else:
            parts.append(str(s.count))
        parts.append(_fmt_support(s.support))
        lines.append(delim.join(parts))
    return lines


def read_transactions(rows: Sequence[Sequence[str]], trans_id_ord: int = 0,
                      skip_field_count: int = 1,
                      infreq_item_marker: Optional[str] = None
                      ) -> List[Tuple[str, List[str]]]:
    """Tokenized CSV rows -> (trans_id, items) with the mapper's field
    conventions (FrequentItemsApriori.java:135-140,163-167): transaction id at
    ``trans_id_ord``, items from ``skip_field_count`` on, marked-infrequent
    tokens dropped."""
    out = []
    for row in rows:
        tid = row[trans_id_ord]
        items = [t for t in row[skip_field_count:]
                 if infreq_item_marker is None or t != infreq_item_marker]
        out.append((tid, items))
    return out


class TransactionMatrix:
    """Boolean membership matrix over the item vocabulary — the device-side
    representation of a transaction set.

    ``items`` pins an explicit (e.g. globally merged) vocabulary; items in
    the transactions but not in ``items`` are ignored, items in ``items``
    but absent locally get an all-zero column.  Multi-process Apriori
    builds every shard's matrix over the SAME merged vocabulary so
    candidate index sets agree across processes."""

    def __init__(self, transactions: Sequence[Tuple[str, List[str]]],
                 items: Optional[Sequence[str]] = None):
        self.trans_ids = [t for t, _ in transactions]
        vocab: Dict[str, int] = {}
        if items is not None:
            for it in items:
                vocab.setdefault(it, len(vocab))
        else:
            for _, items_ in transactions:
                for it in items_:
                    if it not in vocab:
                        vocab[it] = len(vocab)
        self.vocab = vocab
        self.items = list(vocab)
        n, m = len(transactions), max(len(vocab), 1)
        # uint8 membership: 4x less host->device link than f32; the
        # support kernel upcasts on device
        mat = np.zeros((n, m), dtype=np.uint8)
        for r, (_, row_items) in enumerate(transactions):
            for it in row_items:
                col = vocab.get(it)
                if col is not None:
                    mat[r, col] = 1
        self.matrix = mat

    @property
    def n_trans(self) -> int:
        return len(self.trans_ids)

    def support_counts(self, cand_idx: np.ndarray,
                       chunk: int = 1 << 22) -> np.ndarray:
        """Exact support counts for candidate sets ``cand_idx (n_cand, k)``
        of vocab indices: a jitted gather-product-reduce on device.
        Transactions are processed in chunks with float64 host accumulation
        so counts stay exact past float32's 2^24 integer ceiling."""
        if cand_idx.size == 0:
            return np.zeros((0,), dtype=np.int64)

        from ..parallel.mesh import runtime_context
        platform = runtime_context().device_platform
        C = jnp.asarray(cand_idx)
        total = np.zeros((cand_idx.shape[0],), dtype=np.float64)
        for lo in range(0, self.matrix.shape[0], chunk):
            part = _support_kernel(jnp.asarray(self.matrix[lo:lo + chunk]),
                                   C, platform)
            total += np.asarray(part, dtype=np.float64)
        return np.rint(total).astype(np.int64)

    def supporting_trans(self, item_idx: Sequence[int]) -> List[str]:
        mask = self.matrix[:, list(item_idx)].all(axis=1)
        return [tid for tid, m in zip(self.trans_ids, mask) if m]


def _level1_candidates(tm: TransactionMatrix) -> np.ndarray:
    return np.arange(len(tm.items), dtype=np.int32)[:, None]


def _extend_candidates(tm: TransactionMatrix, prior: Sequence[ItemSet]
                       ) -> List[Tuple[str, ...]]:
    """Candidate k-itemsets: each frequent (k-1)-itemset joined with every
    item co-occurring in some supporting transaction (mapper :160-194),
    dedup'd by sorted tuple.  Items absent from the vocabulary (e.g. pruned
    by the infrequent marker) cannot extend anything."""
    cands = set()
    vocab = tm.vocab
    for s in prior:
        if any(it not in vocab for it in s.items):
            continue
        base_idx = [vocab[it] for it in s.items]
        sub = tm.matrix[:, base_idx].all(axis=1)          # trans ⊇ itemset
        co = tm.matrix[sub].any(axis=0)                   # co-occurring items
        base = set(s.items)
        for j in np.nonzero(co)[0]:
            it = tm.items[j]
            if it not in base:
                cands.add(tuple(sorted(base | {it})))
    return sorted(cands)


def apriori_level(transactions: Sequence[Tuple[str, List[str]]],
                  itemset_length: int, total_trans_count: int,
                  support_threshold: float,
                  prior: Optional[Sequence[ItemSet]] = None,
                  emit_trans_id: bool = True,
                  collect_trans_ids: Optional[bool] = None) -> List[ItemSet]:
    """One reference MR pass: frequent itemsets of exactly
    ``itemset_length`` given the previous level's output (``prior``;
    required for length > 1).  Support must be strictly above the
    threshold (reducer :331).

    ``collect_trans_ids`` (default: ``emit_trans_id``) controls whether
    supporting transaction ids are materialized on the result — the job
    passes False when ``fia.trans.id.output=false`` drops them from the
    output anyway, since under multi-process the per-itemset id lists are
    the dominant allgather payload and would be spent producing nothing.

    Multi-process (``jax.process_count() > 1``): ``transactions`` is this
    process's shard and the result is the GLOBAL level — the reference's
    shuffle global-ness (FrequentItemsApriori.java:89-306) rebuilt as three
    collectives: the item vocabulary and the candidate sets are unioned
    across shards (``allgather_object``), every shard counts the SAME
    ordered candidate list on device, and the per-shard counts are
    all-reduced.  Every process returns the identical level, so chained
    levels and output files agree bit-for-bit across the pod."""
    from ..parallel.distributed import is_multiprocess
    dist = is_multiprocess()
    if collect_trans_ids is None:
        collect_trans_ids = emit_trans_id
    if dist:
        from ..parallel import distributed as _D
        local_items = sorted({it for _, row in transactions for it in row})
        global_items: List[str] = sorted(
            set().union(*_D.allgather_object(local_items)))
        tm = TransactionMatrix(transactions, items=global_items)
    else:
        tm = TransactionMatrix(transactions)
    if itemset_length == 1:
        cand_idx = _level1_candidates(tm)
        cand_items: List[Tuple[str, ...]] = [(it,) for it in tm.items]
    else:
        if prior is None:
            # convenience: chain the lower levels in-process (the reference
            # re-runs the job per level with the previous output file,
            # freq_items_apriori_tutorial.txt:33-41)
            # prior levels feed only candidate extension (items, not ids)
            prior = apriori_level(transactions, itemset_length - 1,
                                  total_trans_count, support_threshold,
                                  None, emit_trans_id,
                                  collect_trans_ids=False)
        cand_items = _extend_candidates(tm, prior)
        if dist:
            # a candidate exists if ANY shard has a supporting transaction
            # with a co-occurring item: union of the per-shard extensions
            cand_items = sorted(
                set().union(*_D.allgather_object(cand_items)))
        cand_idx = np.array(
            [[tm.vocab[it] for it in items] for items in cand_items],
            dtype=np.int32).reshape(len(cand_items), itemset_length)
    counts = tm.support_counts(cand_idx)
    if dist:
        counts = _D.all_reduce_host_array(counts)
    keep = [(items, int(cnt)) for items, cnt in zip(cand_items, counts)
            if float(cnt) / total_trans_count > support_threshold]
    trans_lists: List[List[str]] = [[] for _ in keep]
    if collect_trans_ids:
        trans_lists = [tm.supporting_trans([tm.vocab[i] for i in items])
                       for items, _ in keep]
        if dist:
            # per-shard supporting ids, concatenated in process order
            per_proc = _D.allgather_object(trans_lists)
            trans_lists = [[tid for shard in per_proc for tid in shard[i]]
                           for i in range(len(keep))]
    out = [ItemSet(items, trans, float(cnt) / total_trans_count, cnt)
           for (items, cnt), trans in zip(keep, trans_lists)]
    out.sort(key=lambda s: s.items)
    return out


def frequent_itemsets(transactions: Sequence[Tuple[str, List[str]]],
                      support_threshold: float, max_length: int,
                      total_trans_count: Optional[int] = None,
                      emit_trans_id: bool = True
                      ) -> Dict[int, List[ItemSet]]:
    """Full level-wise run 1..max_length — what ``fit.sh freqItems`` achieves
    by re-running the job with fia.item.set.length = 1,2,3,...
    (freq_items_apriori_tutorial.txt:33-41).  Multi-process: the default
    total is the all-reduced global transaction count."""
    if total_trans_count is not None:
        total = total_trans_count
    else:
        total = len(transactions)
        from ..parallel import distributed as _D
        if _D.is_multiprocess():
            total = int(_D.all_reduce_host_array(
                np.array([total], dtype=np.int64))[0])
    levels: Dict[int, List[ItemSet]] = {}
    prior: Optional[List[ItemSet]] = None
    for k in range(1, max_length + 1):
        level = apriori_level(transactions, k, total, support_threshold,
                              prior, emit_trans_id)
        if not level:
            break
        levels[k] = level
        prior = level
    return levels


def mark_infrequent(rows: Sequence[Sequence[str]],
                    frequent_items: Iterable[str], marker: str = "*",
                    skip_field_count: int = 1) -> List[List[str]]:
    """Map-only infrequent-item masking (InfrequentItemMarker.java:128-140):
    every item field not in the frequent level-1 set becomes ``marker``."""
    freq = set(frequent_items)
    out = []
    for row in rows:
        row = list(row)
        for i in range(skip_field_count, len(row)):
            if row[i] not in freq:
                row[i] = marker
        out.append(row)
    return out
