"""Closed-loop control jobs (org.avenir.control.*).

``retrainController`` runs the drift->retrain->validate->swap controller
(control/controller.py, TPU_NOTES §26) as a batch/ops job: resume a
crashed cycle if the journal holds one, otherwise consume alerts (an
``alerts.jsonl`` file from ``driftMonitor``/``predictDriftScore``, a
RESP alert queue, or an operator ``force`` trigger) and run ONE cycle to
its terminal outcome.  ``in_path`` is the fresh (drifted) labeled window
the incremental retrain trains on.

Config keys (``dtb.retrain.*`` next to the builder's ``dtb.*``):

  dtb.model.registry.dir       registry base dir (required)
  dtb.model.name               model name (default forest)
  dtb.feature.schema.file.path schema (required)
  dtb.retrain.state.dir        journal + cycle dirs (default
                               <registry>/_controller/<model>: stable
                               across runs, which is what makes a crashed
                               job resumable by the next run)
  dtb.retrain.trigger          alerts | force (default alerts)
  dtb.retrain.alerts.path      alerts.jsonl to consume (trigger=alerts)
  dtb.retrain.alerts.source    file | resp (default file; resp drains
                               redis.alert.queue on the redis.server.*
                               broker)
  dtb.retrain.holdout.input    labeled delayed-label holdout CSV the
                               validation stage scores champion vs
                               candidate on (default: in_path)
  dtb.retrain.full.input       full dataset for scheduled full rebuilds
                               (default: in_path)
  dtb.retrain.full.rebuild.every  every Nth cycle rebuilds full (0=never)
  dtb.retrain.accuracy.margin  refusal slack, integer points (default 2)
  dtb.retrain.drift.margin     refusal slack, normalized drift (0.25)
  dtb.retrain.probation.outcomes  live outcomes per probation window
                               (0 = no probation, complete at swap)
  dtb.retrain.probation.windows   windows to survive (default 1)
  dtb.retrain.probation.margin    live floor = champion acc - this (5)
  dtb.retrain.probation.input  labeled CSV replayed as live delayed-label
                               outcomes against the SWAPPED serving
                               version — an underperforming candidate
                               auto-rolls-back mid-replay
  dtb.retrain.probation.timeout.s  a probation with NO outcomes resolves
                               as kept-with-a-warning after this long
                               (default 86400; 0 waits forever —
                               resolve_probation() is the escape)
  dtb.retrain.block.rows       streaming build block size (default 65536)
  dtb.retrain.checkpoint.blocks  checkpoint cadence (default 1)
  dtb.retrain.cache.policy     .avtc policy for retrain reads (use)
  dtb.retrain.retire.keep.last registry GC after each cycle (0 = off)
  dtb.retrain.cooldown.s       min seconds between cycle starts (0)
  dtb.retrain.swap.ack.timeout.s  fleet convergence wait (30)
  dtb.retrain.reload.hosts     comma list of fleet host labels for the
                               addressed-reload swap link (with
                               redis.server.* configured; empty = one
                               bare 'reload')
  dtb.num.trees / dtb.* tree keys   candidate forest hyper-parameters
                               (same keys as randomForestBuilder)

Output: ``<out>/decisions.jsonl`` (one line per completed cycle this run,
plus the journal's bounded history) and a one-line ``part-r-00000``
summary; counters in the universal ``<out>.counters.json`` sibling.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..core.config import Config
from ..core.metrics import Counters
from .jobs import _schema_path, _tree_params, register


def _wire_link(cfg: Config):
    """The out-of-process swap link: addressed reloads over the broker
    when one is configured (redis.server.host/endpoints present)."""
    if "redis.server.host" not in cfg and \
            "redis.server.endpoints" not in cfg:
        return None
    from ..control import WireFleetLink
    from ..io.respq import make_queue_client
    client = make_queue_client(
        {k: cfg.get(k) for k in ("redis.server.host", "redis.server.port",
                                 "redis.server.endpoints") if k in cfg})
    hosts = [h.strip() for h in
             (cfg.get("dtb.retrain.reload.hosts") or "").split(",")
             if h.strip()]
    return WireFleetLink(client,
                         request_queue=cfg.get("redis.request.queue",
                                               "requestQueue"),
                         hosts=hosts)


def _probation_replay(cfg: Config, controller, registry, name, schema,
                      counters: Counters) -> Optional[dict]:
    """Replay a labeled CSV as live delayed-label outcomes against the
    version the registry is NOW serving (the swapped candidate) — the
    batch-job stand-in for the fleet's outcome stream.  Stops the moment
    an outcome decides the cycle (probation passed, or rolled back)."""
    from ..control.controller import predict_outcomes
    from ..core.table import BadRecordPolicy, load_csv
    path = cfg.get("dtb.retrain.probation.input")
    if not path or controller.journal.stage != "probation":
        return None
    serving = registry.serving_version(name)
    loaded = registry.load(name, serving)
    table = load_csv(path, schema, cfg.field_delim_regex,
                     bad_records=BadRecordPolicy("skip", None, counters))
    # THE shared predict+decode (controller validation uses the same):
    # the replay must score the identical metric validation scored
    labels, actual = predict_outcomes(loaded.model, schema, table)
    counters.increment("Controller", "ProbationOutcomesReplayed",
                       len(labels))
    for pred, act in zip(labels, actual):
        verdict = controller.record_outcome(pred, act)
        if verdict is not None:
            return verdict
    return None


@register("org.avenir.control.RetrainController", "retrainController",
          dist="refuse")
def retrain_controller(cfg: Config, in_path: str, out_path: str
                       ) -> Counters:
    from ..control import (RetrainController, RetrainPolicy,
                           alerts_from_jsonl, alerts_from_resp)
    from ..serving.registry import ModelRegistry

    counters = Counters()
    registry = ModelRegistry(cfg.must_get("dtb.model.registry.dir"))
    name = cfg.get("dtb.model.name", "forest")
    schema = _schema_path(cfg, "dtb.feature.schema.file.path")
    from ..models.forest import ForestParams
    params = ForestParams(tree=_tree_params(cfg),
                          num_trees=cfg.get_int("dtb.num.trees", 5),
                          seed=cfg.get_int("dtb.random.seed", 0))
    state_dir = cfg.get("dtb.retrain.state.dir") or os.path.join(
        registry.base_dir, "_controller", name)
    policy = RetrainPolicy(
        full_rebuild_every=cfg.get_int("dtb.retrain.full.rebuild.every", 0),
        accuracy_margin=cfg.get_int("dtb.retrain.accuracy.margin", 2),
        drift_margin=cfg.get_float("dtb.retrain.drift.margin", 0.25),
        probation_outcomes=cfg.get_int("dtb.retrain.probation.outcomes", 0),
        probation_windows=cfg.get_int("dtb.retrain.probation.windows", 1),
        probation_margin=cfg.get_int("dtb.retrain.probation.margin", 5),
        probation_timeout_s=cfg.get_float(
            "dtb.retrain.probation.timeout.s", 24 * 3600.0),
        swap_ack_timeout_s=cfg.get_float("dtb.retrain.swap.ack.timeout.s",
                                         30.0),
        cooldown_s=cfg.get_float("dtb.retrain.cooldown.s", 0.0),
        chunk_rows=cfg.get_int("dtb.retrain.block.rows", 1 << 16),
        checkpoint_blocks=cfg.get_int("dtb.retrain.checkpoint.blocks", 1),
        baseline_bins=cfg.get_int("dtb.baseline.bins", 32),
        cache_policy=cfg.get("dtb.retrain.cache.policy", "use"),
        retire_keep_last=cfg.get_int("dtb.retrain.retire.keep.last", 0))
    link = _wire_link(cfg)
    controller = RetrainController(
        registry, name, schema, state_dir=state_dir,
        train_source=in_path,
        holdout_source=cfg.get("dtb.retrain.holdout.input"),
        full_source=cfg.get("dtb.retrain.full.input"),
        forest_params=params, fleet=link, policy=policy,
        counters=counters, delim_regex=cfg.field_delim_regex)

    try:
        trigger = cfg.get("dtb.retrain.trigger", "alerts")
        if trigger not in ("alerts", "force"):
            raise ValueError(f"dtb.retrain.trigger must be alerts|force, "
                             f"got {trigger!r}")
        summaries = []
        if controller.journal.pending:
            # a crashed prior run left a mid-flight cycle: resuming it wins
            # over starting anything new.  For a probation-wait this tick is
            # where the probation TIMEOUT gets evaluated (run_pending
            # returns None while genuinely waiting; the replay below feeds
            # outcomes when an input is configured)
            s = controller.run_pending()
            if s and "outcome" in s:
                summaries.append(s)
        elif trigger == "force":
            s = controller.force_cycle()
            if s and "outcome" in s:
                summaries.append(s)
        else:
            source = cfg.get("dtb.retrain.alerts.source", "file")
            if source == "resp":
                # the same broker resolution as the swap link: a sharded
                # deployment configured only with redis.server.endpoints
                # must drain its alert queue off the ring, not a
                # hard-coded single host
                from ..io.respq import make_queue_client
                client = make_queue_client(
                    {k: cfg.get(k) for k in
                     ("redis.server.host", "redis.server.port",
                      "redis.server.endpoints") if k in cfg})
                try:
                    controller.consume(alerts_from_resp(
                        client, cfg.get("redis.alert.queue", "alertQueue")))
                finally:
                    client.close()
            elif source == "file":
                apath = cfg.get("dtb.retrain.alerts.path")
                if not apath:
                    raise ValueError("dtb.retrain.trigger=alerts needs "
                                     "dtb.retrain.alerts.path (or "
                                     "dtb.retrain.alerts.source=resp)")
                controller.consume(alerts_from_jsonl(apath))
            else:
                raise ValueError(f"dtb.retrain.alerts.source must be "
                                 f"file|resp, got {source!r}")
            s = controller.run_pending()
            if s and "outcome" in s:
                summaries.append(s)
        verdict = _probation_replay(cfg, controller, registry, name, schema,
                                    counters)
        if verdict is not None:
            summaries.append(verdict)

        os.makedirs(out_path, exist_ok=True)
        with open(os.path.join(out_path, "decisions.jsonl"), "w") as fh:
            for s in summaries:
                fh.write(json.dumps({"this_run": True, **s},
                                    sort_keys=True) + "\n")
            for h in controller.journal.history:
                fh.write(json.dumps(h, sort_keys=True) + "\n")
        jr = controller.journal
        with open(os.path.join(out_path, "part-r-00000"), "w") as fh:
            od = cfg.field_delim_out
            fh.write(od.join([
                str(jr.cycle), jr.stage, str(jr["outcome"]),
                str(jr["champion_version"]), str(jr["candidate_version"]),
                str(registry.serving_version(name))]) + "\n")
        counters.set("Controller", "ServingVersion",
                     registry.serving_version(name) or 0)
        return counters
    finally:
        if link is not None:
            # the swap link's broker connection is job-scoped (the
            # alert drain closes its own): a cadenced runner must not
            # leak one socket per invocation
            try:
                link.client.close()
            except OSError:
                pass
