"""Drift-monitoring jobs (org.avenir.monitor.*).

``driftMonitor`` replays a record stream — a CSV file/dir or a RESP queue
— against a registry model's training baseline and emits one drift-score
row per (window, monitored distribution), CSV out like every other job.
Config keys (reference-style, ``dm.`` namespace):

  dm.model.registry.dir      registry base directory (required)
  dm.model.name              model name in the registry (required)
  dm.model.version           pin a version (default: newest intact)
  dm.feature.schema.file.path  override the artifact's embedded schema
  dm.window.rows             tumbling window size (default 2048)
  dm.longterm.decay          exponential long-window decay (default 0.9)
  dm.consecutive.windows     debounce: windows at a level before an
                             alert record emits (default 2)
  dm.warn.<stat> / dm.alert.<stat>   threshold overrides per statistic
                             (psi, kl, js, ks, chi2)
  dm.score.predictions       also run the model per window: prediction-
                             class distribution (prior drift) + delayed-
                             label accuracy when the class column holds
                             known labels (default false)
  dm.accuracy.warn/.alert    integer accuracy percents (0 = disabled)
  dm.accuracy.window         outcomes per quality window (default:
                             dm.window.rows)
  dm.source                  file | resp (default file)
  redis.server.host/port, redis.request.queue, dm.resp.max.idle.s
                             the RESP source (record lines rpop'ed in
                             window-sized drains; a literal 'stop' ends
                             the stream)

Output: ``windowIndex,windowKind,scope,rowKind,nRows,psi,kl,js,ks,chi2,
level`` rows (level = this window's immediate warn/alert standing;
debounced alert records additionally land in ``<out>/alerts.jsonl`` and
the counter dump).  The machine-readable counters land in the universal
``<out>.counters.json`` SIBLING that ``cli.run`` writes for every job
(r13) — the job no longer writes its own ``<out>/counters.json``, which
duplicated the shared writer with a pre-ledger-export snapshot.  Report
rows and alerts stream out per closed window; malformed records are
skipped and tallied in the ``BadRecords`` counter group rather than
killing the replay.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters
from .jobs import register, _splitter


def _threshold_overrides(cfg: Config, prefix: str):
    from ..monitor.drift import STATS
    out = {}
    for stat in STATS:
        key = f"{prefix}.{stat}"
        if key in cfg:
            out[stat] = cfg.get_float(key)
    return out


def _iter_line_windows(in_path: str, split, window_rows: int):
    """Token-row windows from a CSV file or a dir of part files, read
    line by line (never the whole stream in memory)."""
    if os.path.isdir(in_path):
        paths = sorted(os.path.join(in_path, p)
                       for p in os.listdir(in_path)
                       if os.path.isfile(os.path.join(in_path, p))
                       and not p.startswith(("_", ".")))
    else:
        paths = [in_path]
    rows: List[List[str]] = []
    for p in paths:
        with open(p, "r") as fh:
            for line in fh:
                line = line.rstrip("\r\n")
                if not line.strip():
                    continue
                rows.append(split(line))
                if len(rows) >= window_rows:
                    yield rows
                    rows = []
    if rows:
        yield rows


def _iter_resp_windows(cfg: Config, split, window_rows: int):
    """Token-row windows drained from a RESP list queue (pipelined pops,
    the serving loop's wire discipline); 'stop' or idle timeout ends."""
    from ..io.respq import RespClient
    client = RespClient(cfg.get("redis.server.host", "127.0.0.1"),
                        int(cfg.get("redis.server.port", 6379)))
    queue = cfg.get("redis.request.queue", "requestQueue")
    max_idle_s = cfg.get_float("dm.resp.max.idle.s", 10.0)
    idle_since = time.monotonic()
    stopped = False
    try:
        rows: List[List[str]] = []
        while not stopped:
            msgs = client.rpop_many(queue, window_rows)
            if not msgs:
                if time.monotonic() - idle_since > max_idle_s:
                    break
                time.sleep(0.002)
                continue
            idle_since = time.monotonic()
            for m in msgs:
                if m == "stop":
                    stopped = True
                else:
                    rows.append(split(m))
            while len(rows) >= window_rows:
                yield rows[:window_rows]
                rows = rows[window_rows:]
        if rows:
            yield rows
    finally:
        client.close()


# --------------------------------------------------------------------------
# shared monitoring plumbing: ``driftMonitor`` and ``predictDriftScore``
# are pinned byte-identical on the drift-report/alert artifacts, so the
# model resolution, policy/monitor/tracker construction, window sources,
# bad-record filtering, report formatting, and the per-window drain live
# HERE exactly once — a fix in one job cannot silently miss the other
# --------------------------------------------------------------------------

def _resolve_model_version(cfg: Config, registry, name: str) -> int:
    version: Optional[int] = cfg.get_int("dm.model.version", 0) or None
    if version is None:
        # serving_version, not latest_version: after a controller
        # rollback pin the monitor must score the model the fleet is
        # actually serving, not the refused/rolled-back newest one
        # (identical to latest when no pin exists)
        version = registry.serving_version(name)
        if version is None:
            raise FileNotFoundError(
                f"no intact versions of model {name!r} in "
                f"{registry.base_dir!r}")
    return version


def _monitor_schema(cfg: Config, registry, name: str, version: int,
                    loaded):
    """``dm.feature.schema.file.path`` override wins; otherwise the
    artifact's embedded schema.  Returns (schema, loaded) — the artifact
    is loaded at most once across callers (pass what you already
    have; stays None under an override a caller never needs more)."""
    from ..core.schema import FeatureSchema
    if "dm.feature.schema.file.path" in cfg:
        return FeatureSchema.load(
            cfg.must_get("dm.feature.schema.file.path")), loaded
    if loaded is None:
        loaded = registry.load(name, version)
    schema = loaded.schema
    if schema is None:
        raise ValueError(
            f"model {name!r} v{version} embeds no schema; set "
            "dm.feature.schema.file.path")
    return schema, loaded


def _make_policy_monitor(cfg: Config, baseline, counters):
    """The dm.* policy/monitor pair; returns (policy, monitor,
    window_rows)."""
    from ..monitor.accumulator import StreamDriftMonitor
    from ..monitor.policy import DriftPolicy
    window_rows = cfg.get_int("dm.window.rows", 2048)
    policy = DriftPolicy(
        warn=_threshold_overrides(cfg, "dm.warn"),
        alert=_threshold_overrides(cfg, "dm.alert"),
        consecutive=cfg.get_int("dm.consecutive.windows", 2),
        counters=counters,
        accuracy_warn=cfg.get_int("dm.accuracy.warn", 0),
        accuracy_alert=cfg.get_int("dm.accuracy.alert", 0),
        debug_on=cfg.debug_on)
    monitor = StreamDriftMonitor(
        baseline, policy=policy, window_rows=window_rows,
        decay=cfg.get_float("dm.longterm.decay", 0.9),
        counters=counters)
    return policy, monitor, window_rows


def _make_accuracy_tracker(cfg: Config, schema, policy, window_rows: int):
    """(neg, pos) = first two cardinality values, the reference's
    ConfusionMatrix convention; None when thresholds are off or the
    class attribute is not binarizable."""
    from ..monitor.policy import AccuracyTracker
    card = list(schema.class_attr_field.cardinality or [])
    if len(card) >= 2 and (policy.accuracy_warn > 0
                           or policy.accuracy_alert > 0):
        return AccuracyTracker(
            pos_class=card[1], neg_class=card[0], policy=policy,
            window=cfg.get_int("dm.accuracy.window", window_rows))
    return None


def _record_accuracy(tracker, cls_spec, table, labels) -> None:
    """Predicted-vs-actual outcomes for rows whose class column holds a
    KNOWN label (delayed-label rows with an unknown class are skipped)."""
    if tracker is None:
        return
    actual_codes = np.asarray(table.class_codes())
    card = cls_spec.labels or []
    known = actual_codes >= 0
    if known.any():
        tracker.record(
            [lab for lab, k in zip(labels, known) if k],
            [card[c] for c, k in zip(actual_codes, known) if k])


def _window_source(cfg: Config, in_path: str, window_rows: int):
    split = _splitter(cfg.field_delim_regex)
    source = cfg.get("dm.source", "file")
    if source == "file":
        return _iter_line_windows(in_path, split, window_rows)
    if source == "resp":
        return _iter_resp_windows(cfg, split, window_rows)
    raise ValueError(f"unknown dm.source {source!r} (file | resp)")


def _make_bad_filter(cfg: Config, schema, out_path: str, counters):
    """A monitoring replay must survive its stream: malformed records
    (short rows, unparseable numerics — the native parser's ``bad``
    contract) default to badrecords.policy=skip — counted in the
    Hadoop-style BadRecords group through the SAME BadRecordPolicy as
    every other ingest path (quarantine works too; lines re-join with
    the output delimiter) instead of killing the job mid-drain, where
    one bad token would lose every record already rpop'ed off a RESP
    queue.  badrecords.policy=fail restores the historic crash."""
    from ..core.table import BadRecordPolicy, _bad_row_checker
    pol = cfg.get("badrecords.policy", "skip")
    qpath = cfg.get("badrecords.quarantine.path") or \
        os.path.join(out_path, "_quarantine")
    bad_records = None
    if pol != "fail":
        bad_records = BadRecordPolicy(
            pol, qpath if pol == "quarantine" else None, counters)
    return bad_records, _bad_row_checker(schema)


def _filter_bad(rows, bad_records, is_bad, od: str):
    if bad_records is None:
        return rows
    good = [r for r in rows if not is_bad(r)]
    if len(good) < len(rows):
        bad_records.record([od.join(r) for r in rows if is_bad(r)])
    return good


def _level_of(row, policy) -> str:
    """This window's immediate warn/alert standing for one report row
    (the debounced alert stream is the policy's, not this label's)."""
    from ..monitor.drift import STATS
    level = "ok"
    for stat in STATS:
        if not row.applicable(stat):
            continue
        if row.stats[stat] >= policy.alert[stat]:
            return "alert"
        if row.stats[stat] >= policy.warn[stat]:
            level = "warn"
    return level


def _drain(monitor, policy, part_fh, alerts_path: str, od: str) -> None:
    """Flush closed-window report rows + debounced alert records NOW (a
    long-lived RESP drain must not retain every report in memory, and a
    killed job must not lose the windows it already scored);
    alerts.jsonl appears lazily on the first alert so a quiet run
    leaves no empty file behind."""
    from ..monitor.drift import STATS
    for report in monitor.reports:
        for row in report.rows:
            part_fh.write(od.join(
                [str(report.index), report.kind, row.scope, row.kind,
                 str(report.n_rows)]
                + [repr(round(row.stats[s], 6)) for s in STATS]
                + [_level_of(row, policy)]) + "\n")
    monitor.reports.clear()
    if policy.alerts:
        with open(alerts_path, "a") as fh:
            for rec in policy.alerts:
                fh.write(rec.to_json() + "\n")
        policy.alerts.clear()
    part_fh.flush()


def _fresh_alerts_path(out_path: str) -> str:
    # append-mode writes must not leave a previous run's alerts looking
    # like this run's (the file's existence IS the signal)
    path = os.path.join(out_path, "alerts.jsonl")
    if os.path.exists(path):
        os.remove(path)
    return path


@register("org.avenir.monitor.DriftMonitor", "driftMonitor", dist="refuse")
def drift_monitor(cfg: Config, in_path: str, out_path: str) -> Counters:
    from ..core.table import encode_rows
    from ..monitor.baseline import load_baseline
    from ..serving.registry import ModelRegistry

    counters = Counters()
    registry = ModelRegistry(cfg.must_get("dm.model.registry.dir"))
    name = cfg.must_get("dm.model.name")
    version = _resolve_model_version(cfg, registry, name)
    baseline = load_baseline(registry, name, version)
    counters.set("DriftMonitor", "ModelVersion", version)
    score_predictions = cfg.get_boolean("dm.score.predictions", False)
    # load the artifact at most once: the schema and (when enabled) the
    # predictor come from the same LoadedModel
    schema, loaded = _monitor_schema(cfg, registry, name, version, None)
    policy, monitor, window_rows = _make_policy_monitor(cfg, baseline,
                                                        counters)

    predictor = None
    tracker = None
    if score_predictions:
        from ..serving.predictor import make_predictor
        if loaded is None:
            loaded = registry.load(name, version)
        predictor = make_predictor(loaded, schema=schema).warm()
        tracker = _make_accuracy_tracker(cfg, schema, policy, window_rows)
    cls_spec = baseline.specs[baseline.class_row]

    windows = _window_source(cfg, in_path, window_rows)
    od = cfg.field_delim_out
    os.makedirs(out_path, exist_ok=True)
    alerts_path = _fresh_alerts_path(out_path)
    bad_records, is_bad = _make_bad_filter(cfg, schema, out_path, counters)

    with open(os.path.join(out_path, "part-r-00000"), "w") as part_fh:
        for rows in windows:
            rows = _filter_bad(rows, bad_records, is_bad, od)
            if not rows:
                continue
            table = encode_rows(rows, schema)
            class_codes = None
            if predictor is not None:
                labels = predictor.predict_rows(rows)
                # shared encoding with ServingMonitor: prediction-prior
                # drift must score identically offline and live
                class_codes = baseline.class_codes_for_labels(labels)
                _record_accuracy(tracker, cls_spec, table, labels)
            monitor.observe_table(table, class_codes=class_codes)
            _drain(monitor, policy, part_fh, alerts_path, od)
        monitor.close_window()       # score the partial tail window
        if tracker is not None:
            tracker.close()
        _drain(monitor, policy, part_fh, alerts_path, od)
    # machine-readable counters: the universal <out>.counters.json
    # sibling cli.run writes for EVERY job (after the ledger/timer
    # export, so it is the complete final dump) replaced the job-local
    # <out>/counters.json this job used to write
    return counters


@register("org.avenir.monitor.PredictDriftScore", "predictDriftScore",
          dist="refuse")
def predict_drift_score(cfg: Config, in_path: str, out_path: str
                        ) -> Counters:
    """Combined ``predict + driftScore`` in ONE pass (TPU_NOTES §22).

    Before the pipeline compiler this was two jobs and two passes over
    the records: ``modelPredictor`` (predictions part file) then
    ``driftMonitor`` with ``dm.score.predictions=true`` (drift report +
    alerts).  Here every window runs ONE fused XLA program — the whole
    ensemble vote AND the drift-monitor bin counting, the predicted
    classes flowing device-to-device into the monitor's class row — via
    ``pipeline.flows.PredictDriftFlow``; the window scores through the
    IDENTICAL ``StreamDriftMonitor`` path as ``driftMonitor``, so both
    artifacts are bit-identical to the two-job flow (pinned by
    tests/test_pipeline.py) at strictly fewer launches per window.

    Config: the ``dm.*`` keys of ``driftMonitor`` apply unchanged
    (windows, thresholds, decay, debounce, accuracy, source, bad
    records).  ``dm.pipeline.fuse=false`` forces the unfused (but still
    single-pass) path; non-forest model kinds, degenerate ensembles, and
    windows whose values are not float32-exact fall back to it per
    window automatically — results identical, only launch counts differ.

    Contract boundary: drift report rows, alert record CONTENTS, and
    predictions are byte-identical to the two-job flow always.  The
    interleave ORDER of accuracy vs drift alerts inside alerts.jsonl is
    additionally byte-pinned except in one corner: this job records
    delayed-label outcomes per exact re-filtered window, while
    ``driftMonitor`` records them per raw input batch — so when skipped
    bad records shift batch boundaries off window boundaries AND
    ``dm.accuracy.window`` is smaller than ``dm.window.rows``, an
    accuracy window crossing a drift-window boundary can drain on the
    other side of that drift window's alert than it does there.

    Output: ``<out>/part-r-00000`` drift rows + ``<out>/alerts.jsonl``
    exactly as ``driftMonitor``; predictions land in
    ``<out>/predictions/part-m-00000`` (``withRecord`` lines: the
    record, the output delimiter, the predicted class — ``ambiguous``
    for a min-odds veto — byte-identical to ``modelPredictor``'s
    default mode)."""
    from ..core.table import encode_rows
    from ..monitor.baseline import load_baseline
    from ..serving.registry import FOREST, ModelRegistry

    counters = Counters()
    registry = ModelRegistry(cfg.must_get("dm.model.registry.dir"))
    name = cfg.must_get("dm.model.name")
    version = _resolve_model_version(cfg, registry, name)
    baseline = load_baseline(registry, name, version)
    counters.set("DriftMonitor", "ModelVersion", version)
    loaded = registry.load(name, version)
    schema, loaded = _monitor_schema(cfg, registry, name, version, loaded)
    policy, monitor, window_rows = _make_policy_monitor(cfg, baseline,
                                                        counters)
    tracker = _make_accuracy_tracker(cfg, schema, policy, window_rows)
    cls_spec = baseline.specs[baseline.class_row]

    # the fused flow (forest ensembles); anything else predicts through
    # the serving predictor per window — same results, more launches
    flow = None
    if loaded.kind == FOREST and len(loaded.model) > 1:
        from ..models.forest import EnsembleModel
        from ..models.tree import DecisionTreeModel
        p = loaded.params
        min_odds = float(p.get("min_odds_ratio", 1.0))
        ensemble = EnsembleModel(
            [DecisionTreeModel(pl, schema) for pl in loaded.model],
            weights=p.get("weights"), min_odds_ratio=min_odds,
            # modelPredictor's exact rule, applied whether or not the
            # fused flow runs: an even unweighted forest must REFUSE
            # here too, not silently tie-break predictions the
            # byte-identity contract says cannot exist
            require_odd=min_odds <= 1.0 and p.get("weights") is None)
        if cfg.get_boolean("dm.pipeline.fuse", True):
            from ..pipeline.flows import PredictDriftFlow
            flow = PredictDriftFlow(ensemble, baseline, schema,
                                    window_rows)
    predictor = None

    def fallback_labels(rows):
        nonlocal predictor
        if predictor is None:
            from ..serving.predictor import make_predictor
            predictor = make_predictor(loaded, schema=schema)
        return predictor.predict_rows(rows)

    batches = _window_source(cfg, in_path, window_rows)
    bad_records, is_bad = _make_bad_filter(cfg, schema, out_path, counters)

    od = cfg.field_delim_out
    os.makedirs(out_path, exist_ok=True)
    pred_dir = os.path.join(out_path, "predictions")
    os.makedirs(pred_dir, exist_ok=True)
    alerts_path = _fresh_alerts_path(out_path)

    fused_windows = unfused_windows = 0

    def process_window(rows, part_fh, pred_fh) -> None:
        nonlocal fused_windows, unfused_windows
        table = encode_rows(rows, schema)
        res = flow.run_window(table) if flow is not None else None
        labels = res[0] if res is not None else fallback_labels(rows)
        # accuracy BEFORE the window closes: driftMonitor records a
        # batch's outcomes ahead of observe_table, so a window where an
        # accuracy alert and a drift alert both fire must drain them in
        # that same order (alerts.jsonl is byte-pinned against the
        # two-job flow)
        _record_accuracy(tracker, cls_spec, table, labels)
        if res is not None:
            fused_windows += 1
            monitor.close_counts(res[1], table.n_rows)
        else:
            unfused_windows += 1
            monitor.observe_table(
                table,
                class_codes=baseline.class_codes_for_labels(labels))
            monitor.close_window()  # no-op when the absorb auto-closed
        for r, lab in zip(rows, labels):
            pred_fh.write(od.join(r) + od
                          + (lab if lab is not None else "ambiguous")
                          + "\n")
        _drain(monitor, policy, part_fh, alerts_path, od)
        pred_fh.flush()

    # re-window AFTER bad-record filtering so window boundaries (and
    # therefore every report row) match driftMonitor's accumulate-
    # across-batches semantics exactly
    pending: List[List[str]] = []
    with open(os.path.join(out_path, "part-r-00000"), "w") as part_fh, \
            open(os.path.join(pred_dir, "part-m-00000"), "w") as pred_fh:
        for rows in batches:
            pending.extend(_filter_bad(rows, bad_records, is_bad, od))
            while len(pending) >= window_rows:
                process_window(pending[:window_rows], part_fh, pred_fh)
                pending = pending[window_rows:]
        if pending:
            process_window(pending, part_fh, pred_fh)
        if tracker is not None:
            tracker.close()
        _drain(monitor, policy, part_fh, alerts_path, od)
    counters.set("PredictDrift", "FusedWindows", fused_windows)
    counters.set("PredictDrift", "UnfusedWindows", unfused_windows)
    if flow is not None:
        flow.export(counters)
    return counters
