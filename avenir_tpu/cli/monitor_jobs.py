"""Drift-monitoring jobs (org.avenir.monitor.*).

``driftMonitor`` replays a record stream — a CSV file/dir or a RESP queue
— against a registry model's training baseline and emits one drift-score
row per (window, monitored distribution), CSV out like every other job.
Config keys (reference-style, ``dm.`` namespace):

  dm.model.registry.dir      registry base directory (required)
  dm.model.name              model name in the registry (required)
  dm.model.version           pin a version (default: newest intact)
  dm.feature.schema.file.path  override the artifact's embedded schema
  dm.window.rows             tumbling window size (default 2048)
  dm.longterm.decay          exponential long-window decay (default 0.9)
  dm.consecutive.windows     debounce: windows at a level before an
                             alert record emits (default 2)
  dm.warn.<stat> / dm.alert.<stat>   threshold overrides per statistic
                             (psi, kl, js, ks, chi2)
  dm.score.predictions       also run the model per window: prediction-
                             class distribution (prior drift) + delayed-
                             label accuracy when the class column holds
                             known labels (default false)
  dm.accuracy.warn/.alert    integer accuracy percents (0 = disabled)
  dm.accuracy.window         outcomes per quality window (default:
                             dm.window.rows)
  dm.source                  file | resp (default file)
  redis.server.host/port, redis.request.queue, dm.resp.max.idle.s
                             the RESP source (record lines rpop'ed in
                             window-sized drains; a literal 'stop' ends
                             the stream)

Output: ``windowIndex,windowKind,scope,rowKind,nRows,psi,kl,js,ks,chi2,
level`` rows (level = this window's immediate warn/alert standing;
debounced alert records additionally land in ``<out>/alerts.jsonl`` and
the counter dump).  The machine-readable counters land in the universal
``<out>.counters.json`` SIBLING that ``cli.run`` writes for every job
(r13) — the job no longer writes its own ``<out>/counters.json``, which
duplicated the shared writer with a pre-ledger-export snapshot.  Report
rows and alerts stream out per closed window; malformed records are
skipped and tallied in the ``BadRecords`` counter group rather than
killing the replay.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters
from .jobs import register, _splitter


def _threshold_overrides(cfg: Config, prefix: str):
    from ..monitor.drift import STATS
    out = {}
    for stat in STATS:
        key = f"{prefix}.{stat}"
        if key in cfg:
            out[stat] = cfg.get_float(key)
    return out


def _iter_line_windows(in_path: str, split, window_rows: int):
    """Token-row windows from a CSV file or a dir of part files, read
    line by line (never the whole stream in memory)."""
    if os.path.isdir(in_path):
        paths = sorted(os.path.join(in_path, p)
                       for p in os.listdir(in_path)
                       if os.path.isfile(os.path.join(in_path, p))
                       and not p.startswith(("_", ".")))
    else:
        paths = [in_path]
    rows: List[List[str]] = []
    for p in paths:
        with open(p, "r") as fh:
            for line in fh:
                line = line.rstrip("\r\n")
                if not line.strip():
                    continue
                rows.append(split(line))
                if len(rows) >= window_rows:
                    yield rows
                    rows = []
    if rows:
        yield rows


def _iter_resp_windows(cfg: Config, split, window_rows: int):
    """Token-row windows drained from a RESP list queue (pipelined pops,
    the serving loop's wire discipline); 'stop' or idle timeout ends."""
    from ..io.respq import RespClient
    client = RespClient(cfg.get("redis.server.host", "127.0.0.1"),
                        int(cfg.get("redis.server.port", 6379)))
    queue = cfg.get("redis.request.queue", "requestQueue")
    max_idle_s = cfg.get_float("dm.resp.max.idle.s", 10.0)
    idle_since = time.monotonic()
    stopped = False
    try:
        rows: List[List[str]] = []
        while not stopped:
            msgs = client.rpop_many(queue, window_rows)
            if not msgs:
                if time.monotonic() - idle_since > max_idle_s:
                    break
                time.sleep(0.002)
                continue
            idle_since = time.monotonic()
            for m in msgs:
                if m == "stop":
                    stopped = True
                else:
                    rows.append(split(m))
            while len(rows) >= window_rows:
                yield rows[:window_rows]
                rows = rows[window_rows:]
        if rows:
            yield rows
    finally:
        client.close()


@register("org.avenir.monitor.DriftMonitor", "driftMonitor", dist="refuse")
def drift_monitor(cfg: Config, in_path: str, out_path: str) -> Counters:
    from ..core.schema import FeatureSchema
    from ..core.table import encode_rows
    from ..monitor.accumulator import StreamDriftMonitor
    from ..monitor.baseline import load_baseline
    from ..monitor.drift import STATS
    from ..monitor.policy import AccuracyTracker, DriftPolicy
    from ..serving.registry import ModelRegistry

    counters = Counters()
    registry = ModelRegistry(cfg.must_get("dm.model.registry.dir"))
    name = cfg.must_get("dm.model.name")
    version: Optional[int] = cfg.get_int("dm.model.version", 0) or None
    if version is None:
        version = registry.latest_version(name)
        if version is None:
            raise FileNotFoundError(
                f"no intact versions of model {name!r} in "
                f"{registry.base_dir!r}")
    baseline = load_baseline(registry, name, version)
    counters.set("DriftMonitor", "ModelVersion", version)
    score_predictions = cfg.get_boolean("dm.score.predictions", False)
    # load the artifact at most once: the schema and (when enabled) the
    # predictor come from the same LoadedModel
    loaded = None
    if "dm.feature.schema.file.path" in cfg:
        schema = FeatureSchema.load(
            cfg.must_get("dm.feature.schema.file.path"))
    else:
        loaded = registry.load(name, version)
        schema = loaded.schema
        if schema is None:
            raise ValueError(
                f"model {name!r} v{version} embeds no schema; set "
                "dm.feature.schema.file.path")

    window_rows = cfg.get_int("dm.window.rows", 2048)
    policy = DriftPolicy(
        warn=_threshold_overrides(cfg, "dm.warn"),
        alert=_threshold_overrides(cfg, "dm.alert"),
        consecutive=cfg.get_int("dm.consecutive.windows", 2),
        counters=counters,
        accuracy_warn=cfg.get_int("dm.accuracy.warn", 0),
        accuracy_alert=cfg.get_int("dm.accuracy.alert", 0),
        debug_on=cfg.debug_on)
    monitor = StreamDriftMonitor(
        baseline, policy=policy, window_rows=window_rows,
        decay=cfg.get_float("dm.longterm.decay", 0.9),
        counters=counters)

    predictor = None
    tracker = None
    if score_predictions:
        from ..serving.predictor import make_predictor
        if loaded is None:
            loaded = registry.load(name, version)
        predictor = make_predictor(loaded, schema=schema).warm()
        card = list(schema.class_attr_field.cardinality or [])
        if len(card) >= 2 and (policy.accuracy_warn > 0
                               or policy.accuracy_alert > 0):
            # (neg, pos) = first two cardinality values, the reference's
            # ConfusionMatrix convention
            tracker = AccuracyTracker(
                pos_class=card[1], neg_class=card[0], policy=policy,
                window=cfg.get_int("dm.accuracy.window", window_rows))
    cls_spec = baseline.specs[baseline.class_row]

    split = _splitter(cfg.field_delim_regex)
    source = cfg.get("dm.source", "file")
    if source == "file":
        windows = _iter_line_windows(in_path, split, window_rows)
    elif source == "resp":
        windows = _iter_resp_windows(cfg, split, window_rows)
    else:
        raise ValueError(f"unknown dm.source {source!r} (file | resp)")

    # output streams PER CLOSED WINDOW (a long-lived RESP drain must not
    # retain every report in memory, and a killed job must not lose the
    # windows it already scored); alerts.jsonl is created lazily on the
    # first alert so a quiet run leaves no empty file behind
    od = cfg.field_delim_out
    os.makedirs(out_path, exist_ok=True)
    alerts_path = os.path.join(out_path, "alerts.jsonl")
    if os.path.exists(alerts_path):
        # append-mode writes must not leave a previous run's alerts
        # looking like this run's (the file's existence IS the signal)
        os.remove(alerts_path)

    def level_of(row) -> str:
        level = "ok"
        for stat in STATS:
            if not row.applicable(stat):
                continue
            if row.stats[stat] >= policy.alert[stat]:
                return "alert"
            if row.stats[stat] >= policy.warn[stat]:
                level = "warn"
        return level

    def drain(part_fh) -> None:
        for report in monitor.reports:
            for row in report.rows:
                part_fh.write(od.join(
                    [str(report.index), report.kind, row.scope, row.kind,
                     str(report.n_rows)]
                    + [repr(round(row.stats[s], 6)) for s in STATS]
                    + [level_of(row)]) + "\n")
        monitor.reports.clear()
        if policy.alerts:
            with open(alerts_path, "a") as fh:
                for rec in policy.alerts:
                    fh.write(rec.to_json() + "\n")
            policy.alerts.clear()
        part_fh.flush()

    # a monitoring replay must survive its stream: malformed records
    # (short rows, unparseable numerics — the native parser's ``bad``
    # contract) default to badrecords.policy=skip here — counted in the
    # Hadoop-style BadRecords group through the SAME BadRecordPolicy as
    # every other ingest path (quarantine works too; lines re-join with
    # the output delimiter) instead of killing the job mid-drain, where
    # one bad token would lose every record already rpop'ed off a RESP
    # queue.  badrecords.policy=fail restores the historic crash.
    from ..core.table import BadRecordPolicy, _bad_row_checker
    pol = cfg.get("badrecords.policy", "skip")
    qpath = cfg.get("badrecords.quarantine.path") or \
        os.path.join(out_path, "_quarantine")
    bad_records = None
    if pol != "fail":
        bad_records = BadRecordPolicy(
            pol, qpath if pol == "quarantine" else None, counters)
    is_bad = _bad_row_checker(schema)

    with open(os.path.join(out_path, "part-r-00000"), "w") as part_fh:
        for rows in windows:
            if bad_records is not None:
                good = [r for r in rows if not is_bad(r)]
                if len(good) < len(rows):
                    bad_records.record(
                        [od.join(r) for r in rows if is_bad(r)])
                rows = good
            if not rows:
                continue
            table = encode_rows(rows, schema)
            class_codes = None
            if predictor is not None:
                labels = predictor.predict_rows(rows)
                # shared encoding with ServingMonitor: prediction-prior
                # drift must score identically offline and live
                class_codes = baseline.class_codes_for_labels(labels)
                if tracker is not None:
                    actual_codes = np.asarray(table.class_codes())
                    card = cls_spec.labels or []
                    known = actual_codes >= 0
                    if known.any():
                        tracker.record(
                            [lab for lab, k in zip(labels, known) if k],
                            [card[c] for c, k in zip(actual_codes, known)
                             if k])
            monitor.observe_table(table, class_codes=class_codes)
            drain(part_fh)
        monitor.close_window()       # score the partial tail window
        if tracker is not None:
            tracker.close()
        drain(part_fh)
    # machine-readable counters: the universal <out>.counters.json
    # sibling cli.run writes for EVERY job (after the ledger/timer
    # export, so it is the complete final dump) replaced the job-local
    # <out>/counters.json this job used to write
    return counters
