"""Sequence-pack job registrations (org.avenir.markov.*, org.avenir.sequence.*).

Input convention (matching the reference jobs' mappers): each line is
``id fields... [classLabel,] state,state,state,...`` with
``skip.field.count`` leading fields ignored (mst.skip.field.count etc.).
"""

from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from .jobs import register, _splitter


def _parse_sequences(lines, split_line, skip: int, class_ord: int = -1):
    """Returns (sequences, labels, ids).  With a class label ordinal, that
    field is excluded from the sequence and skip is bumped like the reference
    mapper (MarkovStateTransitionModel.java:106-110)."""
    seqs, labels, ids = [], [], []
    eff_skip = skip + (1 if class_ord >= 0 else 0)
    for line in lines:
        it = split_line(line)
        ids.append(it[0] if it else "")
        labels.append(it[class_ord] if class_ord >= 0 else None)
        seqs.append(it[eff_skip:])
    return seqs, labels, ids


@register("org.avenir.markov.MarkovStateTransitionModel",
          "markovStateTransitionModel")
def markov_state_transition_model(cfg: Config, in_path: str,
                                  out_path: str) -> Counters:
    """Markov transition-matrix trainer (mst.* keys: skip.field.count,
    class.label.field.ord, model.states, trans.prob.scale)."""
    from ..sequence import markov as MK
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("mst.skip.field.count", 0)
    class_ord = cfg.get_int("mst.class.label.field.ord", -1)
    states = cfg.must_get_list("mst.model.states")
    scale = cfg.get_int("mst.trans.prob.scale", 1000)
    seqs, labels, _ = _parse_sequences(lines, split_line, skip, class_ord)
    if class_ord >= 0:
        model = MK.build_model(seqs, states, labels=labels, scale=scale)
    else:
        model = MK.build_model(seqs, states, scale=scale)
    out_lines = model.to_lines(cfg.field_delim_out)
    if not cfg.get_boolean("mst.output.states", True):
        out_lines = out_lines[1:]
    artifacts.write_text_output(out_path, out_lines)
    counters.increment("Markov", "Sequences", len(seqs))
    return counters


@register("org.avenir.markov.MarkovModelClassifier", "markovModelClassifier")
def markov_model_classifier(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Log-odds sequence classifier (mmc.* keys; output
    id[,actual],predClass,logOdds — MarkovModelClassifier.java:140-148)."""
    from ..sequence import markov as MK
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    od = cfg.field_delim_out
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("mmc.skip.field.count", 1)
    id_ord = cfg.get_int("mmc.id.field.ord", 0)
    validation = cfg.get_boolean("mmc.validation.mode", False)
    class_ord = cfg.get_int("mmc.class.label.field.ord", -1)
    if validation and class_ord < 0:
        raise ValueError("In validation mode actual class labels must be provided")
    class_labels = cfg.must_get_list("mmc.class.labels")
    threshold = cfg.get_float("mmc.log.odds.threshold", 0.0)
    model_lines = artifacts.read_text_input(cfg.must_get("mmc.mm.model.path"))
    # the log-odds classifier always needs per-class matrices
    model = MK.MarkovModel.from_lines(model_lines, class_based=True)
    eff_skip = skip + (1 if validation else 0)
    seqs, ids, actuals = [], [], []
    for line in lines:
        it = split_line(line)
        ids.append(it[id_ord])
        actuals.append(it[class_ord] if validation else None)
        seqs.append(it[eff_skip:])
    pred, log_odds = MK.classify(model, seqs, class_labels, threshold)
    out = []
    for i in range(len(seqs)):
        parts = [ids[i]]
        if validation:
            parts.append(actuals[i])
        parts.extend([pred[i], str(float(log_odds[i]))])
        out.append(od.join(parts))
        if validation:
            counters.increment("Validation",
                               "Correct" if pred[i] == actuals[i] else "Incorrect")
    artifacts.write_text_output(out_path, out, role="m")
    return counters


@register("org.avenir.markov.HiddenMarkovModelBuilder", "hiddenMarkovModelBuilder")
def hidden_markov_model_builder(cfg: Config, in_path: str,
                                out_path: str) -> Counters:
    """Supervised HMM builder (hmmb.* keys).  Input lines alternate
    observation and state tokens after the skipped fields:
    obs,state,obs,state,... (the tagged-sequence convention)."""
    from ..sequence import markov as MK
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("hmmb.skip.field.count", 0)
    states = cfg.must_get_list("hmmb.model.states")
    observations = cfg.must_get_list("hmmb.model.observations")
    scale = cfg.get_int("hmmb.trans.prob.scale", 1000)
    tagged = []
    for line in lines:
        it = split_line(line)[skip:]
        tagged.append([(it[i], it[i + 1]) for i in range(0, len(it) - 1, 2)])
    hmm = MK.build_hmm(tagged, states, observations, scale=scale)
    artifacts.write_text_output(out_path, hmm.to_lines(cfg.field_delim_out))
    counters.increment("HMM", "Sequences", len(tagged))
    return counters


@register("org.avenir.markov.ViterbiStatePredictor", "viterbiStatePredictor")
def viterbi_state_predictor(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Viterbi decode of observation sequences (vsp.* keys; output
    id,state,state,... — markov/ViterbiStatePredictor.java:77)."""
    from ..sequence import markov as MK
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    od = cfg.field_delim_out
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("vsp.skip.field.count", 1)
    model_lines = artifacts.read_text_input(cfg.must_get("vsp.hmm.model.path"))
    hmm = MK.HiddenMarkovModel.from_lines(model_lines)
    ids, seqs = [], []
    for line in lines:
        it = split_line(line)
        ids.append(it[0])
        seqs.append(it[skip:])
    decoded = MK.viterbi_decode(hmm, seqs)
    out = [od.join([ids[i]] + decoded[i]) for i in range(len(ids))]
    artifacts.write_text_output(out_path, out, role="m")
    return counters


@register("org.avenir.markov.ProbabilisticSuffixTreeGenerator",
          "probabilisticSuffixTreeGenerator")
def probabilistic_suffix_tree_generator(cfg: Config, in_path: str,
                                        out_path: str) -> Counters:
    """PST counts up to pstg.max.depth (markov/ProbabilisticSuffixTree
    Generator.java:88-295); output 'context,symbol,count' lines."""
    from ..sequence.pst import ProbabilisticSuffixTree
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("pstg.skip.field.count", 0)
    tree = ProbabilisticSuffixTree(max_depth=cfg.get_int("pstg.max.depth", 3))
    seqs = [split_line(l)[skip:] for l in lines]
    tree.add_sequences(seqs)
    artifacts.write_text_output(out_path, tree.to_lines(cfg.field_delim_out))
    counters.increment("PST", "Contexts", len(tree.counts))
    return counters


@register("org.avenir.sequence.CandidateGenerationWithSelfJoin",
          "candidateGenerationWithSelfJoin")
def candidate_generation_with_self_join(cfg: Config, in_path: str,
                                        out_path: str) -> Counters:
    """GSP candidate generation from (k-1)-frequent sequence lines
    'item,item,...[,support]' (sequence/CandidateGenerationWithSelfJoin.java)."""
    from ..sequence.pst import gsp_candidates
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    lines = artifacts.read_text_input(in_path)
    has_support = cfg.get_boolean("cgs.support.in.input", False)
    freq = []
    for l in lines:
        it = split_line(l)
        freq.append(it[:-1] if has_support else it)
    cands = gsp_candidates(freq)
    od = cfg.field_delim_out
    artifacts.write_text_output(out_path, (od.join(c) for c in cands))
    counters.increment("GSP", "Candidates", len(cands))
    return counters
