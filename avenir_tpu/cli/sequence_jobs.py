"""Sequence-pack job registrations (org.avenir.markov.*, org.avenir.sequence.*).

Input convention (matching the reference jobs' mappers): each line is
``id fields... [classLabel,] state,state,state,...`` with
``skip.field.count`` leading fields ignored (mst.skip.field.count etc.).
"""

from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from .jobs import register, _splitter


def _parse_sequences(lines, split_line, skip: int, class_ord: int = -1):
    """Returns (sequences, labels, ids).  With a class label ordinal, that
    field is excluded from the sequence and skip is bumped like the reference
    mapper (MarkovStateTransitionModel.java:106-110)."""
    seqs, labels, ids = [], [], []
    eff_skip = skip + (1 if class_ord >= 0 else 0)
    for line in lines:
        it = split_line(line)
        ids.append(it[0] if it else "")
        labels.append(it[class_ord] if class_ord >= 0 else None)
        seqs.append(it[eff_skip:])
    return seqs, labels, ids


@register("org.avenir.markov.MarkovStateTransitionModel",
          "markovStateTransitionModel",
          dist="gather")
def markov_state_transition_model(cfg: Config, in_path: str,
                                  out_path: str) -> Counters:
    """Markov transition-matrix trainer (mst.* keys: skip.field.count,
    class.label.field.ord, model.states, trans.prob.scale)."""
    from ..sequence import markov as MK
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("mst.skip.field.count", 0)
    class_ord = cfg.get_int("mst.class.label.field.ord", -1)
    states = cfg.must_get_list("mst.model.states")
    scale = cfg.get_int("mst.trans.prob.scale", 1000)
    seqs, labels, _ = _parse_sequences(lines, split_line, skip, class_ord)
    if class_ord >= 0:
        model = MK.build_model(seqs, states, labels=labels, scale=scale)
    else:
        model = MK.build_model(seqs, states, scale=scale)
    out_lines = model.to_lines(cfg.field_delim_out)
    if not cfg.get_boolean("mst.output.states", True):
        out_lines = out_lines[1:]
    artifacts.write_text_output(out_path, out_lines)
    counters.increment("Markov", "Sequences", len(seqs))
    return counters


@register("org.avenir.markov.MarkovModelClassifier", "markovModelClassifier",
          dist="map")
def markov_model_classifier(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Log-odds sequence classifier (mmc.* keys; output
    id[,actual],predClass,logOdds — MarkovModelClassifier.java:140-148)."""
    from ..sequence import markov as MK
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    od = cfg.field_delim_out
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("mmc.skip.field.count", 1)
    id_ord = cfg.get_int("mmc.id.field.ord", 0)
    validation = cfg.get_boolean("mmc.validation.mode", False)
    class_ord = cfg.get_int("mmc.class.label.field.ord", -1)
    if validation and class_ord < 0:
        raise ValueError("In validation mode actual class labels must be provided")
    class_labels = cfg.must_get_list("mmc.class.labels")
    threshold = cfg.get_float("mmc.log.odds.threshold", 0.0)
    model_lines = artifacts.read_text_input(cfg.must_get("mmc.mm.model.path"))
    # the log-odds classifier always needs per-class matrices
    model = MK.MarkovModel.from_lines(model_lines, class_based=True)
    eff_skip = skip + (1 if validation else 0)
    seqs, ids, actuals = [], [], []
    for line in lines:
        it = split_line(line)
        ids.append(it[id_ord])
        actuals.append(it[class_ord] if validation else None)
        seqs.append(it[eff_skip:])
    pred, log_odds = MK.classify(model, seqs, class_labels, threshold)
    out = []
    for i in range(len(seqs)):
        parts = [ids[i]]
        if validation:
            parts.append(actuals[i])
        parts.extend([pred[i], str(float(log_odds[i]))])
        out.append(od.join(parts))
        if validation:
            counters.increment("Validation",
                               "Correct" if pred[i] == actuals[i] else "Incorrect")
    artifacts.write_text_output(out_path, out, role="m")
    return counters


@register("org.avenir.markov.HiddenMarkovModelBuilder", "hiddenMarkovModelBuilder",
          dist="gather")
def hidden_markov_model_builder(cfg: Config, in_path: str,
                                out_path: str) -> Counters:
    """Supervised HMM builder (hmmb.* keys).  Input lines alternate
    observation and state tokens after the skipped fields:
    obs,state,obs,state,... (the tagged-sequence convention)."""
    from ..sequence import markov as MK
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("hmmb.skip.field.count", 0)
    states = cfg.must_get_list("hmmb.model.states")
    observations = cfg.must_get_list("hmmb.model.observations")
    scale = cfg.get_int("hmmb.trans.prob.scale", 1000)
    tagged = []
    for line in lines:
        it = split_line(line)[skip:]
        tagged.append([(it[i], it[i + 1]) for i in range(0, len(it) - 1, 2)])
    hmm = MK.build_hmm(tagged, states, observations, scale=scale)
    artifacts.write_text_output(out_path, hmm.to_lines(cfg.field_delim_out))
    counters.increment("HMM", "Sequences", len(tagged))
    return counters


@register("org.avenir.markov.ViterbiStatePredictor", "viterbiStatePredictor",
          dist="map")
def viterbi_state_predictor(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Viterbi decode of observation sequences (vsp.* keys; output
    id,state,state,... — markov/ViterbiStatePredictor.java:77)."""
    from ..sequence import markov as MK
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    od = cfg.field_delim_out
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("vsp.skip.field.count", 1)
    model_lines = artifacts.read_text_input(cfg.must_get("vsp.hmm.model.path"))
    hmm = MK.HiddenMarkovModel.from_lines(model_lines)
    ids, seqs = [], []
    for line in lines:
        it = split_line(line)
        ids.append(it[0])
        seqs.append(it[skip:])
    decoded = MK.viterbi_decode(hmm, seqs)
    out = [od.join([ids[i]] + decoded[i]) for i in range(len(ids))]
    artifacts.write_text_output(out_path, out, role="m")
    return counters


@register("org.avenir.markov.ProbabilisticSuffixTreeGenerator",
          "probabilisticSuffixTreeGenerator",
          dist="gather")
def probabilistic_suffix_tree_generator(cfg: Config, in_path: str,
                                        out_path: str) -> Counters:
    """PST counts up to pstg.max.depth (markov/ProbabilisticSuffixTree
    Generator.java:88-295); output 'context,symbol,count' lines."""
    from ..sequence.pst import ProbabilisticSuffixTree
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    lines = artifacts.read_text_input(in_path)
    skip = cfg.get_int("pstg.skip.field.count", 0)
    tree = ProbabilisticSuffixTree(max_depth=cfg.get_int("pstg.max.depth", 3))
    seqs = [split_line(l)[skip:] for l in lines]
    tree.add_sequences(seqs)
    artifacts.write_text_output(out_path, tree.to_lines(cfg.field_delim_out))
    counters.increment("PST", "Contexts", len(tree.counts))
    return counters


@register("org.avenir.sequence.CandidateGenerationWithSelfJoin",
          "candidateGenerationWithSelfJoin",
          dist="gather")
def candidate_generation_with_self_join(cfg: Config, in_path: str,
                                        out_path: str) -> Counters:
    """GSP candidate generation from (k-1)-frequent sequence lines
    'item,item,...[,support]' (sequence/CandidateGenerationWithSelfJoin.java)."""
    from ..sequence.pst import gsp_candidates
    counters = Counters()
    split_line = _splitter(cfg.field_delim_regex)
    lines = artifacts.read_text_input(in_path)
    has_support = cfg.get_boolean("cgs.support.in.input", False)
    freq = []
    for l in lines:
        it = split_line(l)
        freq.append(it[:-1] if has_support else it)
    cands = gsp_candidates(freq)
    od = cfg.field_delim_out
    artifacts.write_text_output(out_path, (od.join(c) for c in cands))
    counters.increment("GSP", "Candidates", len(cands))
    return counters


@register("org.avenir.sequence.SequencePositionalCluster",
          "sequencePositionalCluster",
          dist="gather")
def sequence_positional_cluster(cfg: Config, in_path: str, out_path: str
                                ) -> Counters:
    """Event-locality scoring in sliding time windows
    (sequence/SequencePositionalCluster.java; window analyzer is a
    re-specified hoidla equivalent, see sequence/positional.py).  Keys
    (reference setup :105-140, typos preserved): window.time.span,
    processing.time.step, quant.field.ordinal, seq.num..field.ordinal,
    wejghter.strategy, weighted.strategies (name=weight list),
    preferred.strategies, any.cond, min.occurence, max.interval.average,
    max.interval.max, min.range.length, min.event.time.interval,
    score.threshold, cond.expression."""
    from ..sequence.positional import LocalityConfig, positional_cluster
    counters = Counters()
    quant_ord = cfg.must_get_int("quant.field.ordinal",
                                 "missing quantity field ordinal")
    seq_ord = cfg.get_int("seq.num..field.ordinal",
                          cfg.get_int("seq.num.field.ordinal"))
    if seq_ord is None:
        raise ValueError("missing sequence field ordinal")
    weighted = cfg.get_boolean("wejghter.strategy",
                               cfg.get_boolean("weighted.strategy", False))
    wmap = {}
    for item in cfg.get_list("weighted.strategies", []):
        if "=" in item:
            name, w = item.split("=", 1)
            wmap[name.strip()] = float(w)
    config = LocalityConfig(
        window_time_span=cfg.must_get_int("window.time.span",
                                          "wondow time span must be specified"),
        time_step=cfg.must_get_int("processing.time.step",
                                   "missing window processing time step"),
        min_event_time_interval=cfg.get_int("min.event.time.interval", 100),
        weighted=weighted,
        weighted_strategies=wmap,
        preferred_strategies=cfg.get_list("preferred.strategies", ["count"]),
        any_cond=cfg.get_boolean("any.cond", True),
        min_occurence=cfg.get_int("min.occurence", 2),
        max_interval_average=cfg.get_float("max.interval.average", 0.0),
        max_interval_max=cfg.get_float("max.interval.max", 0.0),
        min_range_length=cfg.get_float("min.range.length", 0.0))
    threshold = cfg.must_get_float("score.threshold",
                                   "missing score threshold")
    rule = None
    cond_expr = cfg.get("cond.expression")
    if cond_expr:
        from ..explore.rules import RuleExpression
        # condition ordinals are absolute field ordinals over the raw row,
        # same convention as ruleEvaluator
        rule = RuleExpression.create(cond_expr + " > _",
                                     cfg.get("cond.delim", " and "))

    split_line = _splitter(cfg.field_delim_regex)
    records = []
    flags = []
    quants = {}
    for line in artifacts.read_text_input(in_path):
        line = line.strip()
        if not line:
            continue
        items = split_line(line)
        ts = int(items[seq_ord])
        records.append((ts, float(items[quant_ord])))
        flags.append(rule.evaluate(items) if rule is not None else True)
        quants[ts] = items[quant_ord]
    results = positional_cluster(records, config, threshold,
                                 condition_flags=flags)
    od = cfg.field_delim_out
    artifacts.write_text_output(
        out_path,
        [f"{ts}{od}{quants[ts]}{od}{score}" for ts, _, score in results])
    counters.increment("Locality", "scoredAboveThreshold", len(results))
    return counters


@register("org.avenir.spark.markov.StateTransitionRate",
          "stateTransitionRate",
          dist="gather")
def state_transition_rate(cfg: Config, in_path: str, out_path: str
                          ) -> Counters:
    """Per-key CTMC generator (rate) matrices from timestamped state events
    (spark/.../markov/StateTransitionRate.scala:47-168).  Keys:
    key.field.ordinals, time.field.ordinal, state.field.ordinal,
    state.values, rate.time.unit (hour|day|week), input.time.unit
    (ms|sec|formatted + input.time.format), trans.rate.output.precision.
    Output lines — key fields then the row-major rate matrix — feed
    contTimeStateTransitionStats directly (its state.trans.file.path)."""
    import datetime as _dt
    from ..sequence.pst import ctmc_rate_matrices
    from ..utils.timefmt import java_time_format
    counters = Counters()
    delim = cfg.get("field.delim.in", cfg.field_delim_regex)
    split_line = _splitter(delim)
    key_ords = [int(o) for o in cfg.must_get_list("key.field.ordinals")]
    time_ord = cfg.must_get_int("time.field.ordinal")
    state_ord = cfg.must_get_int("state.field.ordinal")
    states = cfg.must_get_list("state.values")
    state_code = {s: i for i, s in enumerate(states)}
    rate_unit = cfg.get("rate.time.unit", "week")
    in_unit = cfg.get("input.time.unit", "ms")
    fmt = (java_time_format(cfg.must_get("input.time.format"))
           if in_unit == "formatted" else None)

    key_of: Dict[tuple, int] = {}
    key_order: List[tuple] = []
    kidx, times, sidx = [], [], []
    for line in artifacts.read_text_input(in_path):
        line = line.strip()
        if not line:
            continue
        items = split_line(line)
        key = tuple(items[o] for o in key_ords)
        if key not in key_of:
            key_of[key] = len(key_order)
            key_order.append(key)
        ts = items[time_ord]
        if in_unit == "ms":
            epoch_ms = float(ts)
        elif in_unit == "sec":
            epoch_ms = float(ts) * 1000.0
        elif in_unit == "formatted":
            # naive parse + .timestamp() uses the host's local timezone,
            # mirroring Java SimpleDateFormat's default-TZ behavior in the
            # reference; epoch values are therefore machine-dependent —
            # keep formatted-mode flows out of byte-pinned fixtures
            epoch_ms = _dt.datetime.strptime(ts, fmt).timestamp() * 1000.0
        else:
            raise ValueError(f"invalid input time unit {in_unit!r}")
        kidx.append(key_of[key])
        times.append(epoch_ms)
        sidx.append(state_code[items[state_ord]])
    rates = ctmc_rate_matrices(np.asarray(kidx), np.asarray(times),
                               np.asarray(sidx), len(key_order), len(states),
                               rate_unit)
    prec = cfg.get_int("trans.rate.output.precision", 6)
    od = cfg.field_delim_out
    out_lines = [od.join(list(key_order[i]) +
                         [f"{v:.{prec}f}" for v in rates[i].ravel()])
                 for i in range(len(key_order))]
    artifacts.write_text_output(out_path, out_lines)
    counters.set("TransitionRate", "keys", len(key_order))
    counters.set("TransitionRate", "events", len(kidx))
    return counters


@register("org.avenir.spark.markov.ContTimeStateTransitionStats",
          "contTimeStateTransitionStats",
          dist="gather")
def cont_time_state_transition_stats(cfg: Config, in_path: str,
                                     out_path: str) -> Counters:
    """CTMC uniformization statistics (spark/.../markov/ContTimeState
    TransitionStats.scala).  Rate matrices per key are read from
    state.trans.file.path (lines: key fields, then row-major rate matrix);
    input lines are key fields + initial state [+ end state]; output is
    key + the statistic.  Keys: key.field.len, state.values, time.horizon,
    state.trans.stat (stateDwellTime|StateTransitionCount), target.states."""
    import numpy as np
    from ..sequence.pst import (ctmc_state_dwell_time,
                                ctmc_transition_count)
    counters = Counters()
    key_len = cfg.must_get_int("key.field.len", "missing key field length")
    states = cfg.must_get_list("state.values", "missing state values")
    n = len(states)
    horizon = cfg.must_get_float("time.horizon", "missing time horizon")
    stat_kind = cfg.must_get("state.trans.stat", "missing stat kind")
    targets = [states.index(s) for s in
               cfg.get_list("target.states", [])]
    need = 2 if stat_kind == "StateTransitionCount" else 1
    if len(targets) < need:
        raise ValueError(f"target.states needs {need} state(s) for "
                         f"{stat_kind}, got {len(targets)}")

    split_line = _splitter(cfg.field_delim_regex)
    rates = {}
    for line in artifacts.read_text_input(
            cfg.must_get("state.trans.file.path",
                         "missing state transition rate file")):
        line = line.strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            line = line[1:-1]
        items = [t.strip() for t in split_line(line)]
        key = tuple(items[:key_len])
        mat = np.asarray([float(v) for v in items[key_len:key_len + n * n]]
                         ).reshape(n, n)
        rates[key] = mat

    from ..sequence.pst import _uniformization_powers
    power_cache = {}
    out_lines = []
    od = cfg.field_delim_out
    for line in artifacts.read_text_input(in_path):
        line = line.strip()
        if not line:
            continue
        items = split_line(line)
        key = tuple(items[:key_len])
        init = states.index(items[key_len])
        end = (states.index(items[key_len + 1])
               if len(items) > key_len + 1 else None)
        Q = rates[key]
        if key not in power_cache:           # one power series per matrix
            power_cache[key] = _uniformization_powers(Q, horizon)
        pre = power_cache[key]
        if stat_kind == "stateDwellTime":
            stat = ctmc_state_dwell_time(Q, horizon, init, targets[0], end,
                                         precomputed=pre)
        elif stat_kind == "StateTransitionCount":
            stat = ctmc_transition_count(Q, horizon, init, targets[0],
                                         targets[1], end, precomputed=pre)
        else:
            raise ValueError(f"unknown state.trans.stat {stat_kind!r}")
        out_lines.append(od.join(list(key) + [f"{stat:.6f}"]))
        counters.increment("CTMC", "records")
    artifacts.write_text_output(out_path, out_lines)
    return counters


@register("org.avenir.spark.sequence.EventTimeDistribution",
          "eventTimeDistribution",
          dist="gather")
def event_time_distribution(cfg: Config, in_path: str, out_path: str
                            ) -> Counters:
    """Per-key event-time histogram
    (spark/.../sequence/EventTimeDistribution.scala:40-95): key = the
    id.field.ordinals tuple, value = histogram of the record's time cycle —
    hourOfDay (epoch-millis % day / hour, optionally / hour.granularity) or
    dayOfWeek.  The reduceByKey(h1.merge(h2)) shuffle is one device
    ``keyed_reduce`` over (key index, bin) one-hots — a production call
    site for the collectives layer.

    Known reference bug, not reproduced: the Scala dayOfWeek branch divides
    by MILISEC_PER_WEEK then by MILISEC_PER_DAY (:70-74), collapsing every
    timestamp to ~0; we compute (millis % week) / day, the day-of-week the
    name intends.

    Output: keyFields..., bin:count pairs (bins ascending)."""
    import jax.numpy as jnp
    from ..parallel.collectives import keyed_reduce, sharded_jit_reduce
    from ..parallel.mesh import runtime_context
    counters = Counters()
    delim = cfg.field_delim_regex
    od = cfg.field_delim_out
    key_ords = [int(x) for x in cfg.must_get_list("id.field.ordinals")]
    time_ord = int(cfg.must_get("time.field.ordinal"))
    resolution = cfg.get("time.resolution", "hourOfDay")
    granularity = cfg.get_int("hour.granularity", 0)
    MS_HOUR = 3600 * 1000
    MS_DAY = 24 * MS_HOUR
    MS_WEEK = 7 * MS_DAY

    split_line = _splitter(delim)
    keys: List[str] = []
    key_idx: Dict[str, int] = {}
    key_codes, cycles = [], []
    for line in artifacts.read_text_input(in_path):
        items = split_line(line)
        key = od.join(items[o] for o in key_ords)
        if key not in key_idx:
            key_idx[key] = len(keys)
            keys.append(key)
        ts = int(items[time_ord])
        if resolution == "hourOfDay":
            cyc = (ts % MS_DAY) // MS_HOUR
            if granularity > 0:
                cyc //= granularity
        elif resolution == "dayOfWeek":
            cyc = (ts % MS_WEEK) // MS_DAY
        else:
            raise ValueError(f"unknown time.resolution {resolution!r}")
        key_codes.append(key_idx[key])
        cycles.append(int(cyc))
    if not keys:
        artifacts.write_text_output(out_path, [])
        return counters
    n_bins = max(cycles) + 1
    # tile events through the keyed_reduce so the (chunk, n_bins) one-hot
    # stays bounded regardless of event count (a 10M-event input would
    # otherwise materialize ~GB of dense one-hot at once)
    key_arr = np.asarray(key_codes, dtype=np.int32)
    cyc_arr = np.asarray(cycles, dtype=np.int64)
    # ONE compiled shape (tail chunks zero-pad: a zero one-hot row sums
    # into no key) row-sharded over the mesh, with a DEVICE-RESIDENT
    # donated int32 accumulator carry: the running histogram updates IN
    # PLACE (identical shape/dtype/sharding twin), so the old per-chunk
    # defensive copy AND the per-chunk D2H readback both disappear — the
    # production wiring of collectives.sharded_jit_reduce(donate=True).
    # int32 cells are exact to 2^31 events per (key, bin), past the f64
    # host accumulation it replaces.  Multi-process (dist=gather: every
    # process holds the full input) keeps the eager host-local reduce —
    # sharding host-local chunks over a hybrid mesh would bypass the
    # from_process_local ingest discipline.
    from ..parallel.distributed import is_multiprocess
    ctx = runtime_context()
    n_keys = len(keys)
    sharded = not is_multiprocess()
    chunk = max((1 << 22) // max(n_bins, 1), 1024)
    chunk += (-chunk) % ctx.n_devices          # mesh-divisible
    if sharded:
        reduce_chunk = sharded_jit_reduce(
            lambda oh, kk, acc: acc + keyed_reduce(oh, kk, n_keys
                                                   ).astype(jnp.int32),
            ctx, n_batch_args=2, donate=True, carry_args=(2,))
        acc = ctx.replicate(jnp.zeros((n_keys, n_bins), jnp.int32))
    else:
        hist = np.zeros((n_keys, n_bins), dtype=np.float64)
    for s in range(0, len(cyc_arr), chunk):
        e = min(s + chunk, len(cyc_arr))
        if sharded:
            onehot = np.zeros((chunk, n_bins), dtype=np.float32)
            onehot[np.arange(e - s), cyc_arr[s:e]] = 1.0
            kk = np.zeros((chunk,), dtype=np.int32)
            kk[:e - s] = key_arr[s:e]
            # batch args placed WITH the row sharding (no reshard copy
            # inside the jit); the carry was ctx.replicate'd once and its
            # layout matches, so its donation updates in place
            acc = reduce_chunk(ctx.shard_rows(onehot),
                               ctx.shard_rows(kk), acc)
        else:
            onehot = np.zeros((e - s, n_bins), dtype=np.float32)
            onehot[np.arange(e - s), cyc_arr[s:e]] = 1.0
            hist += np.asarray(keyed_reduce(jnp.asarray(onehot),
                                            jnp.asarray(key_arr[s:e]),
                                            n_keys))           # (K, n_bins)
    if sharded:
        from ..utils.tracing import fetch
        hist = fetch(acc, dtype=np.float64)    # ONE readback for the job
    out_lines = []
    for ki, key in enumerate(keys):
        bins = [f"{b}:{int(hist[ki, b])}" for b in range(n_bins)
                if hist[ki, b] > 0]
        out_lines.append(od.join([key] + bins))
    artifacts.write_text_output(out_path, out_lines)
    counters.increment("EventTime", "Keys", len(keys))
    counters.increment("EventTime", "Events", len(cycles))
    return counters


@register("org.avenir.spark.sequence.SequenceGenerator", "sequenceGenerator",
          dist="gather")
def sequence_generator(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Event-stream -> per-entity ordered sequences
    (spark/.../sequence/SequenceGenerator.scala:25-81): records grouped by
    id.field.ordinals, ordered by seq.field (numeric when parseable, else
    lexicographic — the reference sorts chombo Records, which compare
    typed), emitting the val.field.ordinals fields of each event in order.

    Output: keyFields..., then the ordered events' value fields flattened.
    This is the standard preparation step feeding the Markov/PST trainers
    (an event log becomes markovStateTransitionModel input)."""
    counters = Counters()
    delim = cfg.field_delim_regex
    od = cfg.field_delim_out
    key_ords = [int(x) for x in cfg.must_get_list("id.field.ordinals")]
    val_ords = [int(x) for x in cfg.must_get_list("val.field.ordinals")]
    seq_ord = int(cfg.must_get("seq.field"))
    split_line = _splitter(delim)
    groups: Dict[str, List] = {}
    for line in artifacts.read_text_input(in_path):
        items = split_line(line)
        key = od.join(items[o] for o in key_ords)
        raw = items[seq_ord]
        try:
            sk = (0, float(raw), "")
        except ValueError:
            sk = (1, 0.0, raw)
        groups.setdefault(key, []).append((sk, [items[o] for o in val_ords]))
    out_lines = []
    for key in sorted(groups):
        events = sorted(groups[key], key=lambda e: e[0])
        flat = [f for _, vals in events for f in vals]
        out_lines.append(od.join([key] + flat))
    artifacts.write_text_output(out_path, out_lines)
    counters.increment("SequenceGenerator", "Entities", len(groups))
    return counters
