"""Optimize-pack jobs: simulatedAnnealing / geneticAlgorithm.

Invocation matches the Spark driver convention (resource/opt.sh:9-16):
``python -m avenir_tpu.cli.run simulatedAnnealing <outputPath> <opt.conf>``
with the HOCON block keys of resource/opt.conf.  The domain callback class
name maps to our domain registry (org.avenir.examples.TaskScheduleSearch ->
TaskScheduleDomain).
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from .jobs import register

DOMAIN_REGISTRY: Dict[str, str] = {
    "org.avenir.examples.TaskScheduleSearch":
        "avenir_tpu.optimize.task_schedule:TaskScheduleDomain",
    "taskSchedule":
        "avenir_tpu.optimize.task_schedule:TaskScheduleDomain",
}


def load_domain(class_name: str, config_file: str):
    target = DOMAIN_REGISTRY.get(class_name)
    if target is None:
        raise KeyError(f"unknown domain callback {class_name!r}; known: "
                       f"{sorted(DOMAIN_REGISTRY)}")
    mod_name, _, cls_name = target.partition(":")
    import importlib
    mod = importlib.import_module(mod_name)
    return getattr(mod, cls_name).load(config_file)


def _safe_int(v: float) -> int:
    """Counter-safe conversion: inf/nan (e.g. every chain stuck on invalid
    solutions) clamp instead of raising OverflowError/ValueError."""
    if np.isnan(v):
        return 0
    return int(np.clip(v, -(2 ** 62), 2 ** 62))


def _parse_start(domain, line: str, od: str) -> np.ndarray:
    """Parse a starting solution; tolerates re-ingesting our own output lines,
    which append ``<od><cost>`` to the solution string (the reference's
    iterate-on-prior-solutions workflow feeds output back as input)."""
    line = line.strip()
    try:
        return domain.from_string(line)
    except (ValueError, IndexError):
        head, sep, _ = line.rpartition(od)
        if not sep:
            raise
        return domain.from_string(head)


@register("org.avenir.spark.optimize.SimulatedAnnealing", "simulatedAnnealing",
          dist="gather")
def simulated_annealing_job(cfg: Config, in_path: str, out_path: str) -> Counters:
    """SA over the configured domain (opt.conf keys; SURVEY.md §3.3).
    in_path may hold starting solutions (one per line, reference component
    format); otherwise num.optimizers random starts are generated."""
    from ..optimize.annealing import AnnealingParams, simulated_annealing
    counters = Counters()
    params = AnnealingParams(
        max_num_iterations=cfg.get_int("max.num.iterations", 300),
        num_optimizers=cfg.get_int("num.optimizers", 8),
        initial_temp=cfg.get_float("initial.temp", 30.0),
        cooling_rate=cfg.get_float("cooling.rate.value", 0.99),
        cooling_rate_geometric=cfg.get_boolean("cooling.rate.geometric", True),
        temp_update_interval=cfg.get_int("temp.update.interval", 2),
        max_step_size=cfg.get_int("max.step.size", 1),
        step_size_strategy=cfg.get("step.size.strategy", "constant"),
        step_size_mean=cfg.get_float("step.size.mean", 1.0),
        step_size_std_dev=cfg.get_float("step.size.std.dev", 1.0),
        locally_optimize=cfg.get_boolean("locally.optimize", False),
        max_num_local_iterations=cfg.get_int("max.num.local.iterations", 50),
        seed=cfg.get_int("random.seed", 0),
    )
    domain = load_domain(cfg.must_get("domain.callback.class.name"),
                         cfg.must_get("domain.callback.config.file"))
    starts = None
    if in_path and os.path.exists(in_path):
        lines = artifacts.read_text_input(in_path)
        if lines:
            od = cfg.field_delim_out
            starts = np.stack([_parse_start(domain, l, od) for l in lines])
            params.num_optimizers = len(lines)
    res = simulated_annealing(domain, params, start_solutions=starts)
    od = cfg.field_delim_out
    order = np.argsort(res.best_costs)
    out_lines = [f"{domain.to_string(res.best_solutions[i])}{od}"
                 f"{res.best_costs[i]:.3f}" for i in order]
    artifacts.write_text_output(out_path, out_lines)
    for k, v in res.counters.items():
        counters.set("Annealing", k, _safe_int(v))
    counters.set("Annealing", "estimatedInitialTemp",
                 _safe_int(res.estimated_initial_temp))
    return counters


@register("org.avenir.spark.optimize.GeneticAlgorithm", "geneticAlgorithm",
          dist="gather")
def genetic_algorithm_job(cfg: Config, in_path: str, out_path: str) -> Counters:
    """GA over the configured domain (GeneticAlgorithm.scala:69-176)."""
    from ..optimize.genetic import GeneticParams, genetic_algorithm
    counters = Counters()
    params = GeneticParams(
        num_generations=cfg.get_int("num.generations", 100),
        population_size=cfg.get_int("population.size", 32),
        num_islands=cfg.get_int("num.partitions", 4),
        crossover_prob=cfg.get_float("crossover.prob", 0.8),
        mutation_prob=cfg.get_float("mutation.prob", 0.2),
        seed=cfg.get_int("random.seed", 0),
    )
    domain = load_domain(cfg.must_get("domain.callback.class.name"),
                         cfg.must_get("domain.callback.config.file"))
    res = genetic_algorithm(domain, params)
    od = cfg.field_delim_out
    out_lines = [f"{domain.to_string(res.island_best[i])}{od}"
                 f"{res.island_best_costs[i]:.3f}"
                 for i in np.argsort(res.island_best_costs)]
    artifacts.write_text_output(out_path, out_lines)
    counters.set("Genetic", "bestCost", _safe_int(res.best_cost))
    return counters
