"""Optimize-pack jobs: simulatedAnnealing / geneticAlgorithm.

Invocation matches the Spark driver convention (resource/opt.sh:9-16):
``python -m avenir_tpu.cli.run simulatedAnnealing <outputPath> <opt.conf>``
with the HOCON block keys of resource/opt.conf.  The domain callback class
name maps to our domain registry (org.avenir.examples.TaskScheduleSearch ->
TaskScheduleDomain).
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from .jobs import register

DOMAIN_REGISTRY: Dict[str, str] = {
    "org.avenir.examples.TaskScheduleSearch":
        "avenir_tpu.optimize.task_schedule:TaskScheduleDomain",
    "taskSchedule":
        "avenir_tpu.optimize.task_schedule:TaskScheduleDomain",
}


def load_domain(class_name: str, config_file: str):
    target = DOMAIN_REGISTRY.get(class_name)
    if target is None:
        raise KeyError(f"unknown domain callback {class_name!r}; known: "
                       f"{sorted(DOMAIN_REGISTRY)}")
    mod_name, _, cls_name = target.partition(":")
    import importlib
    mod = importlib.import_module(mod_name)
    return getattr(mod, cls_name).load(config_file)


def _safe_int(v: float) -> int:
    """Counter-safe conversion: inf/nan (e.g. every chain stuck on invalid
    solutions) clamp instead of raising OverflowError/ValueError."""
    if np.isnan(v):
        return 0
    return int(np.clip(v, -(2 ** 62), 2 ** 62))


def _parse_start(domain, line: str, od: str) -> np.ndarray:
    """Parse a starting solution; tolerates re-ingesting our own output lines,
    which append ``<od><cost>`` to the solution string (the reference's
    iterate-on-prior-solutions workflow feeds output back as input)."""
    line = line.strip()
    try:
        return domain.from_string(line)
    except (ValueError, IndexError):
        head, sep, _ = line.rpartition(od)
        if not sep:
            raise
        return domain.from_string(head)


@register("org.avenir.spark.optimize.SimulatedAnnealing", "simulatedAnnealing",
          dist="partition")
def simulated_annealing_job(cfg: Config, in_path: str, out_path: str) -> Counters:
    """SA over the configured domain (opt.conf keys; SURVEY.md §3.3).
    in_path may hold starting solutions (one per line, reference component
    format); otherwise num.optimizers random starts are generated.

    Multi-process: each process anneals its ``work_slice`` of the chains
    with a process-folded seed (distinct streams — the reference's Spark
    executors each draw their own rng,
    spark SimulatedAnnealing.scala:96-255), then the per-chain bests are
    allgathered so every process writes the identical merged output.
    Single-process output is byte-identical to the pre-partition job (the
    golden SA fixture): slice = all chains, seed fold = +0, allgather =
    identity."""
    from ..optimize.annealing import (COUNTER_KEYS, AnnealingParams,
                                      simulated_annealing)
    from ..parallel.distributed import allgather_object, work_slice
    counters = Counters()
    params = AnnealingParams(
        max_num_iterations=cfg.get_int("max.num.iterations", 300),
        num_optimizers=cfg.get_int("num.optimizers", 8),
        initial_temp=cfg.get_float("initial.temp", 30.0),
        cooling_rate=cfg.get_float("cooling.rate.value", 0.99),
        cooling_rate_geometric=cfg.get_boolean("cooling.rate.geometric", True),
        temp_update_interval=cfg.get_int("temp.update.interval", 2),
        max_step_size=cfg.get_int("max.step.size", 1),
        step_size_strategy=cfg.get("step.size.strategy", "constant"),
        step_size_mean=cfg.get_float("step.size.mean", 1.0),
        step_size_std_dev=cfg.get_float("step.size.std.dev", 1.0),
        locally_optimize=cfg.get_boolean("locally.optimize", False),
        max_num_local_iterations=cfg.get_int("max.num.local.iterations", 50),
        seed=cfg.get_int("random.seed", 0),
    )
    domain = load_domain(cfg.must_get("domain.callback.class.name"),
                         cfg.must_get("domain.callback.config.file"))
    starts = None
    if in_path and os.path.exists(in_path):
        lines = artifacts.read_text_input(in_path)
        if lines:
            od = cfg.field_delim_out
            starts = np.stack([_parse_start(domain, l, od) for l in lines])
            params.num_optimizers = len(lines)
    lo, hi = work_slice(params.num_optimizers)
    owns_first = lo == 0 and hi > lo
    params.num_optimizers = hi - lo
    params.seed += lo  # fold by chain offset: distinct per-process streams
    if starts is not None:
        starts = starts[lo:hi]
    od = cfg.field_delim_out
    local = ([], 0.0, 0.0)
    if hi > lo:
        res = simulated_annealing(domain, params, start_solutions=starts)
        local = ([(float(res.best_costs[i]),
                   domain.to_string(res.best_solutions[i]))
                  for i in range(hi - lo)],
                 res.counters["costIncreaseAcum"],
                 res.counters["worseSolnCount"])
        for k, v in res.counters.items():
            counters.set("Annealing", k, _safe_int(v))
    else:  # more processes than chains: empty slice, counter keys must
        for k in COUNTER_KEYS:
            counters.set("Annealing", k, 0)  # still match for the reduce
    gathered = allgather_object(local)
    merged = [p for sols, _, _ in gathered for p in sols]
    merged.sort(key=lambda cs: cs[0])
    out_lines = [f"{sol}{od}{cost:.3f}" for cost, sol in merged]
    artifacts.write_text_output(out_path, out_lines)
    # initial-temp diagnostic = total cost increase / total worse count,
    # derived from the GLOBAL sums (a slice-local ratio would silently
    # change meaning with pod size); emitted once for the counter reduce
    total_inc = sum(ci for _, ci, _ in gathered)
    total_worse = sum(nw for _, _, nw in gathered)
    est = total_inc / total_worse if total_worse > 0 else 0.0
    counters.set("Annealing", "estimatedInitialTemp",
                 _safe_int(est) if owns_first else 0)
    return counters


@register("org.avenir.spark.optimize.GeneticAlgorithm", "geneticAlgorithm",
          dist="partition")
def genetic_algorithm_job(cfg: Config, in_path: str, out_path: str) -> Counters:
    """GA over the configured domain (GeneticAlgorithm.scala:69-176).

    Multi-process: each process evolves its ``work_slice`` of the islands
    with an island-offset seed (the reference's num.partitions IS its
    executor fan-out, GeneticAlgorithm.scala:69), then island bests are
    allgathered so every process writes the identical merged output.
    Single-process output is byte-identical to the pre-partition job."""
    from ..optimize.genetic import GeneticParams, genetic_algorithm
    from ..parallel.distributed import allgather_object, work_slice
    counters = Counters()
    params = GeneticParams(
        num_generations=cfg.get_int("num.generations", 100),
        population_size=cfg.get_int("population.size", 32),
        num_islands=cfg.get_int("num.partitions", 4),
        crossover_prob=cfg.get_float("crossover.prob", 0.8),
        mutation_prob=cfg.get_float("mutation.prob", 0.2),
        seed=cfg.get_int("random.seed", 0),
    )
    domain = load_domain(cfg.must_get("domain.callback.class.name"),
                         cfg.must_get("domain.callback.config.file"))
    lo, hi = work_slice(params.num_islands)
    owns_first = lo == 0 and hi > lo
    params.num_islands = hi - lo
    params.seed += lo  # fold by island offset: distinct per-process streams
    od = cfg.field_delim_out
    local = []
    if hi > lo:
        res = genetic_algorithm(domain, params)
        local = [(float(res.island_best_costs[i]),
                  domain.to_string(res.island_best[i]))
                 for i in range(hi - lo)]
    merged = [p for proc in allgather_object(local) for p in proc]
    merged.sort(key=lambda cs: cs[0])
    out_lines = [f"{sol}{od}{cost:.3f}" for cost, sol in merged]
    artifacts.write_text_output(out_path, out_lines)
    # global best emitted exactly once (the cross-process counter reduce
    # SUMS values; every process setting it would P-fold it)
    counters.set("Genetic", "bestCost",
                 _safe_int(merged[0][0]) if owns_first and merged else 0)
    return counters
