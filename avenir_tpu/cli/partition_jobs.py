"""Candidate-split generation + data-partitioning job registrations.

Namespaces: cpg.* (explore/ClassPartitionGenerator.java:485-510), dap.*
(tree/DataPartitioner.java:135-201,296-321).  SplitGenerator
(tree/SplitGenerator.java) is the same job as ClassPartitionGenerator with
tree-pipeline path conventions; both names resolve here.
"""

from __future__ import annotations

import os

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from ..core.table import load_csv
from .jobs import register, _schema_path


@register("org.avenir.explore.ClassPartitionGenerator",
          "classPartitionGenerator",
          "org.avenir.tree.SplitGenerator", "splitGenerator",
          dist="gather")
def class_partition_generator(cfg: Config, in_path: str, out_path: str
                              ) -> Counters:
    """Scores every candidate split of the configured attributes (or emits
    the dataset info content at root).  Keys: cpg.feature.schema.file.path,
    cpg.split.algorithm, cpg.split.attributes (absent -> root mode),
    cpg.parent.info."""
    from ..models import partition as PT
    counters = Counters()
    schema = _schema_path(cfg, "cpg.feature.schema.file.path")
    algo = cfg.get("cpg.split.algorithm", "giniIndex")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    attrs = cfg.get_int_list("cpg.split.attributes")
    if not attrs:
        stat = PT.root_info(table, algo)
        artifacts.write_text_output(out_path, [f"{stat}"])
        counters.increment("Splits", "rootInfo", 1)
        return counters
    parent_info = cfg.must_get_float("cpg.parent.info",
                                     "missing parent info")
    scored = PT.score_candidate_splits(table, attrs, algo, parent_info)
    # the splits file uses its own delimiter (default ';') so categorical
    # keys containing ',' stay parseable — matching DataPartitioner's
    # hardcoded ';' line format (DataPartitioner.java:216)
    delim = cfg.get("cpg.split.file.delim", ";")
    artifacts.write_text_output(out_path,
                                [s.to_line(delim) for s in scored])
    counters.increment("Splits", "candidates", len(scored))
    return counters


@register("org.avenir.tree.DataPartitioner", "dataPartitioner",
          dist="gather")
def data_partitioner(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Physically partitions data by the chosen candidate split into
    ``split=<i>/segment=<j>/data/partition.txt`` under out_path
    (DataPartitioner.java:102-128).  Keys: dap.feature.schema.file.path,
    dap.candidate.splits.path (default: sibling ``splits/part-r-00000`` of
    the input, :162), dap.split.selection.strategy (best|randomFromTop),
    dap.num.top.splits, dap.split.file.delim (default ';' — the pipeline
    writes the splits file with that field.delim.out so categorical keys
    containing ',' stay parseable), dap.seed."""
    from ..models import partition as PT
    counters = Counters()
    schema = _schema_path(cfg, "dap.feature.schema.file.path")
    cand_path = cfg.get("dap.candidate.splits.path")
    if not cand_path:
        cand_path = os.path.join(os.path.dirname(in_path.rstrip("/")),
                                 "splits", "part-r-00000")
    lines = artifacts.read_text_input(cand_path)
    chosen = PT.choose_split(
        lines, schema,
        strategy=cfg.get("dap.split.selection.strategy", "best"),
        num_top=cfg.get_int("dap.num.top.splits", 5),
        seed=cfg.get_int("dap.seed"),
        delim=cfg.get("dap.split.file.delim", ";"))
    raw = artifacts.read_text_input(in_path)
    segments = PT.partition_rows(raw, schema, chosen, cfg.field_delim_regex)
    split_dir = os.path.join(out_path, f"split={chosen.index}")
    for j, seg_lines in enumerate(segments):
        seg_dir = os.path.join(split_dir, f"segment={j}", "data")
        os.makedirs(seg_dir, exist_ok=True)
        with open(os.path.join(seg_dir, "partition.txt"), "w") as fh:
            fh.write("\n".join(seg_lines) + ("\n" if seg_lines else ""))
        counters.increment("Partition", f"segment_{j}_rows", len(seg_lines))
    counters.increment("Partition", "segments", len(segments))
    return counters
