"""Text-pack + rule-evaluation job registrations.

Namespaces: text.* (text/WordCounter.java:92-96), rue.*
(explore/RuleEvaluator.java:99-119,210-226).
"""

from __future__ import annotations

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from .jobs import register, _splitter


@register("org.avenir.text.WordCounter", "wordCounter",
          dist="gather")
def word_counter(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Word-count MR (text/WordCounter.java).  Keys: text.field.ordinal
    (whole line when not positive, mapper :102-106)."""
    from ..text import word_count
    counters = Counters()
    ordinal = cfg.get_int("text.field.ordinal", 0)
    split = _splitter(cfg.field_delim_regex)
    texts = []
    for line in artifacts.read_text_input(in_path):
        line = line.rstrip("\n")
        if not line:
            continue
        texts.append(split(line)[ordinal] if ordinal > 0 else line)
    pairs = word_count(texts)
    delim = cfg.field_delim_out
    artifacts.write_text_output(out_path,
                                [f"{w}{delim}{c}" for w, c in pairs])
    counters.increment("WordCount", "distinctWords", len(pairs))
    counters.increment("WordCount", "totalWords", sum(c for _, c in pairs))
    return counters


@register("org.avenir.explore.RuleEvaluator", "ruleEvaluator",
          dist="gather")
def rule_evaluator(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Rule confidence/support evaluation (explore/RuleEvaluator.java).
    Keys: rue.rule.names (list), rue.rule.<name> (each ``condition >
    consequent``), rue.class.attr.ord, rue.conf.strategy
    (confAccuracy|confEntropy), rue.data.size, rue.class.values,
    rue.cond.delim (conjunct separator override)."""
    from ..explore import rules as RU
    counters = Counters()
    sep = cfg.get("rue.cond.delim", RU.DEFAULT_CONJUNCT_SEP)
    names = cfg.must_get_list("rue.rule.names", "missing rule list")
    rules = {}
    for name in names:
        rule = cfg.must_get(f"rue.rule.{name}", "missing rule definition")
        rules[name] = RU.RuleExpression.create(rule, sep)
    class_ord = cfg.must_get_int("rue.class.attr.ord",
                                 "missing class attribute ordinal")
    strategy = cfg.must_get("rue.conf.strategy",
                            "missing confidence strategy list")
    data_size = cfg.must_get_int("rue.data.size", "missing data size")
    class_values = cfg.must_get_list("rue.class.values",
                                     "missing class values")

    split = _splitter(cfg.field_delim_regex)
    rows = [split(line.rstrip("\n"))
            for line in artifacts.read_text_input(in_path)
            if line.strip()]
    n_cols = max(len(r) for r in rows) if rows else 0
    columns = [np.asarray([r[i] if i < len(r) else "" for r in rows],
                          dtype=object) for i in range(n_cols)]
    results = RU.evaluate_rules(rules, columns, class_ord, data_size,
                                strategy, class_values)
    delim = cfg.field_delim_out
    artifacts.write_text_output(
        out_path,
        [f"{name}{delim}{conf:.3f}{delim}{sup:.3f}"
         for name, conf, sup in results])
    counters.increment("Rules", "evaluated", len(results))
    return counters


@register("org.chombo.mr.TemporalFilter", "temporalFilter",
          dist="map")
def temporal_filter(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Time-range record filter (the chombo TemporalFilter MR the
    reference's fit flow runs before Apriori, resource/fit.sh:29-40,
    fit.properties tef.* block).  Keys: tef.time.stamp.field.ordinal,
    tef.time.range=<start>:<end> (epoch, inclusive),
    tef.time.stamp.in.mili, tef.time.zone.shift.hours,
    tef.seasonal.cycle.type (only anyTimeRange is supported — the other
    chombo cycle types have no user in the reference's avenir flows)."""
    counters = Counters()
    cycle = cfg.get("tef.seasonal.cycle.type", "anyTimeRange")
    if cycle != "anyTimeRange":
        raise ValueError(f"unsupported seasonal cycle type {cycle!r}; "
                         f"only anyTimeRange")
    ts_ord = cfg.must_get_int("tef.time.stamp.field.ordinal",
                              "missing timestamp field ordinal")
    lo, _, hi = cfg.must_get("tef.time.range",
                             "missing time range").partition(":")
    lo, hi = float(lo), float(hi)
    in_mili = cfg.get_boolean("tef.time.stamp.in.mili", False)
    shift_s = cfg.get_int("tef.time.zone.shift.hours", 0) * 3600
    split = _splitter(cfg.field_delim_regex)
    kept = []
    n_in = 0
    for line in artifacts.read_text_input(in_path):
        line = line.rstrip("\n")
        if not line.strip():
            continue
        n_in += 1
        ts = float(split(line)[ts_ord])
        if in_mili:
            ts /= 1000.0
        ts += shift_s
        if lo <= ts <= hi:
            kept.append(line)
    artifacts.write_text_output(out_path, kept, role="m")
    counters.set("TemporalFilter", "inputRecords", n_in)
    counters.set("TemporalFilter", "keptRecords", len(kept))
    return counters
