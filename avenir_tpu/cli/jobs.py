"""Job registry: the ``hadoop jar avenir.jar <ClassName> -Dconf.path=... in out``
entry points, rebuilt (SURVEY.md §1 L6->L5->L4 interface).

Every reference job class name (and a short camelCase alias) maps to a Python
job function ``job(config, in_path, out_path) -> Counters``.  Driver shell
scripts keep working by swapping the ``hadoop jar``/``spark-submit`` line for
``python -m avenir_tpu.cli.run <ClassName> -Dconf.path=<file> <in> <out>``.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.config import Config
from ..core.schema import FeatureSchema
from ..core.table import BadRecordPolicy, load_csv
from ..core.metrics import Counters, CostBasedArbitrator
from ..core import artifacts
from ..parallel.mesh import runtime_context

JOBS: Dict[str, Callable] = {}

# multi-process behavior class per job function (parallel/distributed.py
# module docstring defines the contract cli.run enforces):
#   sharded   — consumes its local shard, internally global (device
#               reductions / collectives)
#   gather    — host-side global computation; cli.run allgathers the input
#               lines so every process computes the full result
#   map       — per-record transform; per-process part files are correct
#   partition — global input view (gather-style spool when shards differ)
#               but the job SPLITS ITS WORK by process_index (chain/island
#               slices, the test axis) — the reference's Spark
#               mapPartitions executor semantics
#               (spark SimulatedAnnealing.scala:109).  Counters are
#               per-process partials (cli.run all-reduces them)
#   refuse    — known shard-local-wrong with no fix: rejected under
#               jax.process_count() > 1
JOB_DIST: Dict[Callable, str] = {}
_DIST_MODES = ("sharded", "gather", "map", "partition", "refuse")


def register(*names: str, dist: str):
    if dist not in _DIST_MODES:
        raise ValueError(f"register(dist={dist!r}): must be one of "
                         f"{_DIST_MODES}")

    def deco(fn):
        for n in names:
            JOBS[n] = fn
        JOB_DIST[fn] = dist
        return fn
    return deco


def dist_mode(fn: Callable) -> str:
    """The job's multi-process class; unregistered functions default to
    'refuse' so nothing can silently emit shard-local results."""
    return JOB_DIST.get(fn, "refuse")


def shards_by_row_range(fn: Callable, cfg) -> bool:
    """True when this job, under this config, splits ONE shared input by
    row range itself (dtb.streaming.shard, TPU_NOTES §20) — the case
    where every process legitimately receives the IDENTICAL input path
    and cli.run's identical-shard refusal for sharded jobs must stand
    down: the job's own split arithmetic guarantees each process consumes
    a disjoint row range of it."""
    return (fn is random_forest_builder
            and cfg.get_boolean("dtb.streaming.ingest", False)
            and cfg.get("dtb.streaming.shard", "auto") != "off")


def resolve(name: str) -> Callable:
    if name in JOBS:
        return JOBS[name]
    # allow bare class name for fully-qualified registrations
    for k, v in JOBS.items():
        if k.split(".")[-1] == name:
            return v
    raise KeyError(f"unknown job {name!r}; known: {sorted(JOBS)}")


def _schema_path(cfg: Config, key: str) -> FeatureSchema:
    return FeatureSchema.load(cfg.must_get(key))


def _bad_records_policy(cfg: Config, counters: Counters,
                        out_path: Optional[str] = None
                        ) -> Optional[BadRecordPolicy]:
    """The job-level ``badrecords.policy`` knob (fail|skip|quarantine):
    Hadoop's skip-bad-records, rebuilt for the native ingest.  Quarantined
    raw lines land in ``badrecords.quarantine.path`` (default
    ``<out>/_quarantine``); skip/quarantine tallies surface through the
    job's Hadoop-style counter dump (``BadRecords`` group)."""
    pol = cfg.get("badrecords.policy", "fail")
    if pol == "fail":
        return None
    qpath = cfg.get("badrecords.quarantine.path")
    if pol == "quarantine" and not qpath:
        if not out_path:
            raise ValueError("badrecords.policy=quarantine needs "
                             "badrecords.quarantine.path (no output dir "
                             "to default under)")
        qpath = os.path.join(out_path, "_quarantine")
    return BadRecordPolicy(pol, qpath, counters)


def _cache_policy(cfg: Config, counters: Counters,
                  prefix: str = "dtb.streaming.cache"):
    """The job-level columnar-cache knob (``<prefix>.policy`` =
    off|use|build|require, ``<prefix>.dir`` overriding the default
    ``<csv>.avtc`` sidecar location).  Tallies surface through the job's
    counter dump as the ``ColumnarCache`` group, next to ``Transfers``."""
    pol = cfg.get(f"{prefix}.policy", "off")
    if pol == "off":
        return None
    from ..io.colcache import CachePolicy
    return CachePolicy(policy=pol, cache_dir=cfg.get(f"{prefix}.dir"),
                       counters=counters)


def _splitter(delim_regex: str):
    """Line splitter honoring field.delim.regex semantics: literal fast
    path, re.split otherwise — THE tokenizer, shared with core.table and
    serving (one delimiter semantics everywhere)."""
    from ..core.table import _make_splitter
    return _make_splitter(delim_regex)


# --------------------------------------------------------------------------
# org.avenir.tree
# --------------------------------------------------------------------------

def _tree_params(cfg: Config):
    """Map the dtb.* keys (resource/detr.properties, rafo.properties) onto
    TreeParams."""
    from ..models.tree import TreeParams
    # defaults match the reference job's (DecisionTreeBuilder.java:169,179,
    # 434,442,448): giniIndex / notUsedYet / best / minInfoGain / withReplace
    return TreeParams(
        split_algorithm=cfg.get("dtb.split.algorithm", "giniIndex"),
        attr_select_strategy=cfg.get("dtb.split.attribute.selection.strategy",
                                     "notUsedYet"),
        random_split_set_size=cfg.get_int("dtb.random.split.set.size", 3),
        split_select_strategy=cfg.get("dtb.split.select.strategy", "best"),
        top_split_count=cfg.get_int("dtb.top.split.count", 3),
        stopping_strategy=cfg.get("dtb.path.stopping.strategy", "minInfoGain"),
        max_depth=cfg.get_int("dtb.max.depth.limit", 3),
        min_info_gain=cfg.get_float("dtb.min.info.gain.limit", -1.0),
        min_population=cfg.get_int("dtb.min.population.limit", -1),
        sub_sampling=cfg.get("dtb.sub.sampling.strategy", "withReplace"),
        sub_sampling_rate=cfg.get_float("dtb.sub.sampling.rate", 100.0),
        seed=cfg.get_int("dtb.random.seed"),
    )


@register("org.avenir.tree.DecisionTreeBuilder", "decisionTreeBuilder",
          dist="sharded")
def decision_tree_builder(cfg: Config, in_path: str, out_path: str) -> Counters:
    """One level of tree growth per invocation — the reference job contract
    (tree/DecisionTreeBuilder.java, driven by resource/detr.sh's rotation of
    dtb.decision.file.path.out -> .in between runs).

    Differences from the reference noted: the job does not write re-tagged
    record files; records are routed by re-evaluating the decision paths, so
    the output dir just carries the input records forward for script compat."""
    from ..models import tree as T
    counters = Counters()
    schema = _schema_path(cfg, "dtb.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex, keep_raw=True,
                     bad_records=_bad_records_policy(cfg, counters, out_path))
    params = _tree_params(cfg)
    builder = T.TreeBuilder(table, params, runtime_context())
    dec_in = cfg.get("dtb.decision.file.path.in")
    dpl = T.DecisionPathList.from_json(open(dec_in).read()) if dec_in else None
    new_dpl = builder.build_one_level(table, dpl)
    with open(cfg.must_get("dtb.decision.file.path.out"), "w") as fh:
        fh.write(new_dpl.to_json())
    if out_path:
        artifacts.write_text_output(
            out_path, (cfg.field_delim_out.join(r) for r in table.raw_rows))
    counters.increment("Decision tree", "Paths", len(new_dpl.decision_paths))
    return counters


@register("org.avenir.tree.RandomForestBuilder", "randomForestBuilder",
          dist="sharded")
def random_forest_builder(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Full in-process random forest: the rafo.sh per-tree rerun loop
    (resource/rafo.sh:34-43) collapsed into one job.  Writes one decision-path
    JSON per tree into the output dir (tree_<i>.json).

    ``dtb.streaming.ingest=true`` trains through the chunked CSV->device
    pipeline (block size ``dtb.streaming.block.rows``): host memory holds
    one parsed block instead of the whole encoded dataset — the knob that
    makes the 100M-row flagship CSV feasible.  Models are bit-identical to
    the monolithic path.

    ``dtb.baseline.publish=true`` (with ``dtb.model.registry.dir``)
    additionally profiles the training data device-side (feature
    histograms + class distribution, ``dtb.baseline.bins`` numeric bins)
    and publishes the profile as a baseline sidecar of the registry
    version — the reference distribution the drift monitor
    (``driftMonitor`` job, serving hook) scores live traffic against.
    Streamed ingests tee the same single pass; no extra read.

    Fault tolerance (TPU_NOTES §15): ``badrecords.policy`` skips or
    quarantines malformed records; ``dtb.streaming.checkpoint.dir`` (+
    ``dtb.streaming.checkpoint.blocks``, default 16) persists ingest
    progress so ``dtb.streaming.resume=true`` (CLI ``--resume``) restarts
    from the last intact step and still produces the bit-identical model
    of an uninterrupted run.

    ``dtb.streaming.cache.policy=use|build|require`` (+ optional
    ``dtb.streaming.cache.dir``) slots the write-once binary columnar
    sidecar (TPU_NOTES §19) under the ingest: ``build`` emits
    ``<csv>.avtc/`` during the first full pass, later passes load the
    encoded chunks at memcpy speed and skip CSV parse entirely; models,
    resume, and quarantine behavior are bit-identical either way
    (``ColumnarCache`` counter group reports hits/bytes).

    ``dtb.pipeline.fuse=true`` (the default; TPU_NOTES §22) runs the
    streaming per-chunk device work — branch-code encode plus, under
    ``dtb.baseline.publish``, the baseline's bin-count absorb — as ONE
    ProgramCache-compiled XLA launch per chunk with device-resident
    intermediates and a donated count carry, instead of one launch per
    stage plus a host-side ``tee_blocks`` second consumer.  Models and
    baselines are bit-identical either way; the ``Dispatches`` counter
    group shows the per-site launch delta and the ``ProgramCache`` group
    reports this run's compile/hit tallies (a warm re-run of an
    identical job shows Retraces=0).  ``false`` restores the eager
    per-stage path.

    ``dtb.model.quantize=true`` (with ``dtb.model.registry.dir``;
    TPU_NOTES §24) additionally attaches the int8-quantized serving
    sidecar to the published version, enforcing
    ``dtb.model.quantize.budget`` (default 0.01 prediction-mismatch
    fraction vs the float ensemble) on a training-data sample at publish
    time — over-budget quantizations refuse to publish.  Streamed trains
    re-read a ``dtb.model.quantize.sample.rows`` head sample (default
    65536).  ``predictionService`` selects the sidecar with
    ``ps.quantized``; ``kernel.backend=auto|xla|pallas`` (env twin
    AVENIR_TPU_KERNEL_BACKEND) picks the hot-loop kernel form."""
    from ..models.forest import (ForestParams, build_forest,
                                 build_forest_from_stream)
    counters = Counters()
    schema = _schema_path(cfg, "dtb.feature.schema.file.path")
    params = ForestParams(tree=_tree_params(cfg),
                          num_trees=cfg.get_int("dtb.num.trees", 5),
                          seed=cfg.get_int("dtb.random.seed", 0))
    policy = _bad_records_policy(cfg, counters, out_path)
    reg_dir = cfg.get("dtb.model.registry.dir")
    baseline_builder = None
    if cfg.get_boolean("dtb.baseline.publish", False):
        if not reg_dir:
            # same refusal style as resume-without-streaming: a silently
            # ignored publish flag surfaces only when driftMonitor later
            # finds no sidecar — after the training pass the baseline
            # was supposed to ride is gone
            raise ValueError("dtb.baseline.publish needs "
                             "dtb.model.registry.dir (baselines ride "
                             "registry versions as sidecars)")
        from ..monitor.baseline import BaselineBuilder
        baseline_builder = BaselineBuilder(
            schema, n_bins=cfg.get_int("dtb.baseline.bins", 32))
    if cfg.get_boolean("dtb.model.quantize", False) and not reg_dir:
        # same refusal shape as baseline.publish: the quantized sidecar
        # rides a registry version — silently training without one only
        # surfaces when ps.quantized later finds nothing to serve
        raise ValueError("dtb.model.quantize needs dtb.model.registry.dir "
                         "(the int8 sidecar rides the registry version)")
    if cfg.get_boolean("dtb.streaming.resume", False) and \
            not cfg.get_boolean("dtb.streaming.ingest", False):
        # same refusal as the missing-checkpoint-dir case: a --resume that
        # silently retrains from row 0 through the monolithic path is the
        # failure mode the flag exists to prevent
        raise ValueError("dtb.streaming.resume needs "
                         "dtb.streaming.ingest=true (checkpoints only "
                         "exist for the streaming build)")
    shard_knob = cfg.get("dtb.streaming.shard", "auto")
    if shard_knob not in ("auto", "on", "off"):
        raise ValueError(f"dtb.streaming.shard must be auto|on|off, "
                         f"got {shard_knob!r}")
    if shard_knob == "on" and \
            not cfg.get_boolean("dtb.streaming.ingest", False):
        # same refusal shape as resume-without-ingest: a shard=on run that
        # silently trains monolithic single-host is the failure mode the
        # 'on' value exists to refuse
        raise ValueError("dtb.streaming.shard=on needs "
                         "dtb.streaming.ingest=true (only the streaming "
                         "build can row-range shard)")
    stream_reducer = None
    if cfg.get_boolean("dtb.streaming.ingest", False):
        from ..core.checkpoint import CheckpointManager
        from ..core.table import iter_csv_chunks, prefetch_chunks
        from ..parallel.distributed import shard_spec
        # dtb.streaming.shard: row-range data parallelism for the
        # streaming build (TPU_NOTES §20).  auto = shard whenever this is
        # a multi-shard run (jax.distributed process, or the
        # AVENIR_TPU_SHARD/ALLREDUCE_DIR smoke lane); on = require one;
        # off = never (each process must then bring its own input file).
        # Every process reads the SAME csv and parses only its row range;
        # one all-reduce per tree level makes the model bit-identical to
        # the single-host build on every process.
        spec = shard_spec() if shard_knob != "off" else None
        sharded = spec is not None and spec.active
        if shard_knob == "on" and not sharded:
            raise ValueError(
                "dtb.streaming.shard=on needs a multi-shard run "
                "(jax.distributed, or AVENIR_TPU_SHARD=i/P with "
                "AVENIR_TPU_ALLREDUCE_DIR); refusing to silently train "
                "single-host")
        reducer = None
        if sharded:
            from ..parallel.collectives import AllReducer
            reducer = stream_reducer = AllReducer(spec=spec,
                                                  name="rf-stream")
            # identity values, emitted by shard 0 only: the cross-process
            # counter all-reduce SUMS, and a summed Shard/Count=2P would
            # read as a different topology than the job actually ran
            if spec.index == 0:
                counters.set("Shard", "Count", spec.count)
        ckpt_dir = cfg.get("dtb.streaming.checkpoint.dir")
        if ckpt_dir and sharded:
            # per-shard step dirs: N processes checkpointing the same
            # base dir would race the same step_<n> names
            ckpt_dir = os.path.join(
                ckpt_dir, f"shard-{spec.index}-of-{spec.count}")
        mgr = CheckpointManager(ckpt_dir) if ckpt_dir else None
        every = cfg.get_int("dtb.streaming.checkpoint.blocks", 16) \
            if mgr is not None else 0
        resume_state = None
        start_row = 0
        if cfg.get_boolean("dtb.streaming.resume", False):
            if mgr is None:
                # a silently-ignored resume flag would re-ingest from row 0
                # while the operator believes the job picked up where it
                # left off — refuse instead
                raise ValueError("dtb.streaming.resume needs "
                                 "dtb.streaming.checkpoint.dir")
            try:
                step, arrays, meta = mgr.restore()
            except FileNotFoundError:
                if mgr.steps():
                    # steps exist but NONE are intact — re-ingesting from
                    # row 0 as if this were a cold start is the silent
                    # failure the resume flag exists to prevent
                    raise RuntimeError(
                        f"dtb.streaming.resume: checkpoint dir "
                        f"{ckpt_dir!r} holds {len(mgr.steps())} step(s) "
                        f"but none restore intact; refusing to silently "
                        f"restart from row 0 — clear the dir to force a "
                        f"cold start")
                pass  # genuinely nothing saved yet: cold start
            else:
                resume_state = (arrays, meta)
                start_row = int(meta.get("source_rows_done") or 0)
                counters.set("Checkpoint", "ResumedFromStep", step)
                counters.set("Checkpoint", "ResumedSourceRows", start_row)
        # consumer_wait_key=None: this parse layer feeds from_stream's
        # staging thread, whose own stats already time the wait on it
        blocks = prefetch_chunks(iter_csv_chunks(
            in_path, schema, cfg.field_delim_regex,
            chunk_rows=cfg.get_int("dtb.streaming.block.rows", 1 << 22),
            bad_records=policy, start_row=start_row,
            cache=_cache_policy(cfg, counters),
            shard=(spec.index, spec.count) if sharded else None),
            consumer_wait_key=None)
        # the baseline rides the SAME single ingest pass either way (a
        # resumed run only re-profiles the re-read tail; the baseline is
        # a distribution estimate, not a bit-pinned artifact): fused, it
        # is a stage of the per-chunk program; unfused, from_stream tees
        # the block stream host-side
        fuse = cfg.get_boolean("dtb.pipeline.fuse", True)
        stream_stats: dict = {}
        models = build_forest_from_stream(
            blocks, schema, params,
            None if sharded else runtime_context(),
            checkpoint=mgr, checkpoint_every=every,
            resume_state=resume_state, reducer=reducer,
            baseline=baseline_builder, fuse=fuse, stats=stream_stats)
        pl = stream_stats.get("pipeline")
        if pl:
            # per-run program-cache tallies (TPU_NOTES §22): a warm
            # re-run of an identical job reports Retraces=0 here
            counters.update_group("ProgramCache", {
                "Chunks": pl["chunks"], "Hits": pl["hits"],
                "Misses": pl["misses"], "Retraces": pl["retraces"]})
    else:
        table = load_csv(in_path, schema, cfg.field_delim_regex,
                         bad_records=policy)
        if baseline_builder is not None:
            baseline_builder.update(table)
        models = build_forest(table, params, runtime_context())
    os.makedirs(out_path, exist_ok=True)
    for i, dpl in enumerate(models):
        with open(os.path.join(out_path, f"tree_{i}.json"), "w") as fh:
            fh.write(dpl.to_json())
    if reg_dir:
        # publish the trained forest into the serving registry (atomic
        # versioned artifact; a live predictionService hot-swaps to it on
        # its next refresh).  Every process trains the identical model
        # (sharded job, device reductions), so under multi-process only
        # process 0 publishes — the registry is single-writer per name
        import jax
        baseline = None
        if baseline_builder is not None:
            # partial shard counts all-reduce FIRST (collective: every
            # process participates), then only process 0 writes
            from ..monitor.baseline import allreduce_partials
            baseline = allreduce_partials(baseline_builder,
                                          reducer=stream_reducer).finalize()
        publish_owner = jax.process_index() == 0 and (
            stream_reducer is None or stream_reducer.spec.index == 0)
        if publish_owner:
            from ..serving.registry import ModelRegistry
            registry = ModelRegistry(reg_dir)
            model_name = cfg.get("dtb.model.name", "forest")
            version = registry.publish(model_name, models, schema=schema)
            counters.set("Random forest", "RegistryVersion", version)
            if baseline is not None:
                from ..monitor.baseline import publish_baseline
                publish_baseline(registry, model_name, version, baseline)
                counters.set("Random forest", "BaselineRows",
                             baseline.n_rows)
            if cfg.get_boolean("dtb.model.quantize", False):
                # int8 serving sidecar (TPU_NOTES §24): quantize the
                # published forest and enforce the pinned accuracy-delta
                # budget on a training-data sample BEFORE attaching —
                # an over-budget quantization refuses here, never at
                # serve time.  Streamed trains re-read a bounded head
                # sample (the encoded dataset is gone by publish time).
                from ..serving.quantized import publish_quantized
                if cfg.get_boolean("dtb.streaming.ingest", False):
                    from ..core.table import iter_csv_chunks as _icc
                    gen = _icc(
                        in_path, schema, cfg.field_delim_regex,
                        chunk_rows=cfg.get_int(
                            "dtb.model.quantize.sample.rows", 65536),
                        bad_records=BadRecordPolicy("skip"))
                    try:
                        sample = next(iter(gen))
                    except StopIteration:
                        raise ValueError(
                            "dtb.model.quantize: the input yielded no "
                            "sample rows to enforce the accuracy budget "
                            "on (empty/fully-filtered file)") from None
                    finally:
                        gen.close()   # release the parser handle now
                else:
                    sample = table
                info = publish_quantized(
                    registry, model_name, version, models, schema,
                    sample,
                    budget=cfg.get_float("dtb.model.quantize.budget",
                                         0.01))
                counters.set("Random forest", "QuantizedSampleRows",
                             int(info["n_sample"]))
                counters.set("Random forest",
                             "QuantizedMismatchPerMillion",
                             int(round(info["mismatch"] * 1e6)))
    counters.increment("Random forest", "Trees", len(models))
    return counters


@register("org.avenir.model.ModelPredictor", "modelPredictor",
          dist="map")
def model_predictor_job(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Generic map-only predictor (model/ModelPredictor.java:46-82): loads N
    decision-path model files (mop.model.dir.path + mop.model.file.names) and
    predicts via single model or weighted ensemble vote
    (mop.ensemble.memeber.weights — reference key name, typo included)."""
    from ..models.tree import DecisionPathList
    from ..models.forest import model_predictor
    counters = Counters()
    schema = _schema_path(cfg, "mop.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex, keep_raw=True)
    model_dir = cfg.get("mop.model.dir.path", "")
    names = cfg.get_list("mop.model.file.names")
    if not names:
        # extension: default to the forest builder's tree_<i>.json files in
        # numeric order, so rafo.sh needs no name list; other JSONs in the
        # dir (schemas, decision paths) are not treated as models
        import re as _re
        if not model_dir:
            cfg.must_get_list("mop.model.file.names")  # raise with key name
        if not os.path.isdir(model_dir):
            raise FileNotFoundError(f"model dir {model_dir!r} not found")
        matches = [(int(m.group(1)), f) for f in os.listdir(model_dir)
                   if (m := _re.fullmatch(r"tree_(\d+)\.json", f))]
        names = [f for _, f in sorted(matches)]
        if not names:
            raise FileNotFoundError(
                f"no tree_<i>.json models in {model_dir!r}; set "
                "mop.model.file.names explicitly for other layouts")
    path_lists = []
    for nm in names:
        p = os.path.join(model_dir, nm) if model_dir else nm
        with open(p) as fh:
            path_lists.append(DecisionPathList.from_json(fh.read()))
    weights = cfg.get_float_list("mop.ensemble.memeber.weights")
    output_mode = cfg.get("mop.output.mode", "withRecord")
    # per-mode mandatory ordinals (ModelPredictor.java:165-172); error
    # counting also requires the class ordinal (:116)
    error_counting = cfg.get_boolean("mop.error.counting.enabled", False)
    class_ord = None
    if output_mode == "withActualClassAttr" or error_counting:
        class_ord = cfg.must_get_int(
            "mop.rec.class.attr.ordinal",
            "missing class attribute ordinal") if \
            "mop.rec.class.attr.ordinal" in cfg else \
            cfg.must_get_int("mop.class.attr.ord",
                             "missing class attribute ordinal")
    id_ord = cfg.get_int("mop.rec.id.ordinal", 0) \
        if output_mode != "withKId" else \
        cfg.must_get_int("mop.rec.id.ordinal", "missing id ordinal")
    lines = model_predictor(
        table, schema, path_lists,
        output_mode=output_mode,
        id_ordinal=id_ord,
        class_attr_ordinal=class_ord,
        class_attr_values=cfg.get_list("mop.class.attr.values"),
        error_counting=error_counting,
        weights=weights,
        min_odds_ratio=cfg.get_float("mop.min.odds.ratio", 1.0),
        out_delim=cfg.field_delim_out, counters=counters)
    artifacts.write_text_output(out_path, lines, role="m")
    return counters


# --------------------------------------------------------------------------
# org.avenir.knn (+ the sifarish distance job the pipeline shells out to)
# --------------------------------------------------------------------------

def _load_train_test(in_path: str, prefix: str, schema: FeatureSchema,
                     delim: str):
    """Split a similarity-job input into (train, test, intra_set): files in
    a dir starting with ``prefix`` are the train/base set, the rest test; a
    single file (or a dir with only one kind) is intra-set."""
    import glob as _glob
    intra_set = False
    if os.path.isdir(in_path):
        files = sorted(p for p in _glob.glob(os.path.join(in_path, "*"))
                       if os.path.isfile(p))
        base = [p for p in files if os.path.basename(p).startswith(prefix)]
        other = [p for p in files if not os.path.basename(p).startswith(prefix)]
        if not base or not other:
            base = other = files
            intra_set = True
    else:
        base = other = [in_path]
        intra_set = True

    def load_many(paths):
        lines = []
        for p in paths:
            lines.extend(artifacts.read_text_input(p))
        from ..core.table import load_csv_text
        return load_csv_text("\n".join(lines), schema, delim)

    train = load_many(base)
    test = train if intra_set else load_many(other)
    return train, test, intra_set


@register("org.sifarish.feature.SameTypeSimilarity", "sameTypeSimilarity",
          "recordSimilarity",
          dist="gather")
def same_type_similarity(cfg: Config, in_path: str, out_path: str) -> Counters:
    """All-pairs record distance (the external sifarish job of
    resource/knn.sh:47, and avenir-spark RecordSimilarity.scala:65-103).

    Inter-set mode: files in the input dir starting with
    sts.base.set.split.prefix are the train/base set, the rest are test.
    Output lines: trainId,testId,distance,trainClass[,testClass]
    with distance scaled by sts.distance.scale (default 1000).
    Divergence: accepts our FeatureSchema JSON (sts.same.schema.file.path)
    rather than sifarish's rich schema."""
    from ..ops.distance import DistanceComputer
    counters = Counters()
    schema = _schema_path(cfg, "sts.same.schema.file.path")
    delim = cfg.field_delim_regex
    prefix = cfg.get("sts.base.set.split.prefix", "tr")
    scale = cfg.get_int("sts.distance.scale", 1000)
    metric = cfg.get("sts.distance.metric", "euclidean")
    train, test, intra_set = _load_train_test(in_path, prefix, schema, delim)
    comp = DistanceComputer(schema, metric=metric, scale=scale)
    dmat = comp.pairwise(test, train)
    id_ord = schema.id_fields[0].ordinal if schema.id_fields else 0
    train_ids = train.str_columns.get(id_ord, [str(i) for i in range(train.n_rows)])
    test_ids = test.str_columns.get(id_ord, [str(i) for i in range(test.n_rows)])
    # class columns are optional: pure similarity mode (sifarish's normal use)
    # has no class notion at all
    try:
        cls_field = schema.class_attr_field
        cvals = cls_field.cardinality or []
        train_cls = [cvals[c] if c >= 0 else "?" for c in train.class_codes()]
        test_cls = [cvals[c] if c >= 0 else "?" for c in test.class_codes()]
    except ValueError:
        train_cls = test_cls = None
    od = cfg.field_delim_out
    lines = []
    for ti in range(test.n_rows):
        # intra-set mode emits each unordered pair once (i < j), like
        # sifarish's within-set matching — never a self-pair, which would
        # leak labels into a downstream KNN validation
        for ri in range(ti + 1 if intra_set else 0, train.n_rows):
            parts = [train_ids[ri], test_ids[ti], str(int(dmat[ti, ri]))]
            if train_cls is not None:
                parts.append(train_cls[ri])
                parts.append(test_cls[ti])
            lines.append(od.join(parts))
    artifacts.write_text_output(out_path, lines)
    counters.increment("Similarity", "Pairs", len(lines))
    return counters


@register("org.avenir.spark.similarity.GroupedRecordSimilarity",
          "groupedRecordSimilarity",
          dist="gather")
def grouped_record_similarity(cfg: Config, in_path: str, out_path: str
                              ) -> Counters:
    """Per-group all-pairs record distance
    (spark/.../similarity/GroupedRecordSimilarity.scala:34-103): records
    grouped by grs.group.field.ordinals; within each group every unordered
    pair (i < j) gets a mixed-type distance.  The reference's
    groupByKey + per-group O(n^2) JVM loop becomes, per group, one tiled
    device distance matrix (groups padded to power-of-two row counts so the
    jitted kernel compiles O(log max-group) variants, not one per size).

    Output: group..., firstId, secondId, distance."""
    from ..ops.distance import DistanceComputer
    counters = Counters()
    schema = _schema_path(cfg, "sts.same.schema.file.path")
    delim = cfg.field_delim_regex
    od = cfg.field_delim_out
    scale = cfg.get_int("sts.distance.scale", 1000)
    metric = cfg.get("sts.distance.metric", "euclidean")
    group_ords = [int(x) for x in cfg.must_get_list("grs.group.field.ordinals")]
    from ..core.table import load_csv_text
    lines = artifacts.read_text_input(in_path)
    split_line = _splitter(delim)
    groups: Dict[str, List[str]] = {}
    for line in lines:
        items = split_line(line)
        groups.setdefault(od.join(items[o] for o in group_ords),
                          []).append(line)
    comp = DistanceComputer(schema, metric=metric, scale=scale)
    id_ord = schema.id_fields[0].ordinal if schema.id_fields else 0
    out_lines: List[str] = []
    for gkey in sorted(groups):
        glines = groups[gkey]
        n = len(glines)
        if n < 2:
            continue
        # pad to the next power of two: bounded compile count across groups
        padded = 1 << (n - 1).bit_length()
        table = load_csv_text(
            "\n".join(glines + glines[:1] * (padded - n)), schema, delim)
        dmat = comp.pairwise(table, table)[:n, :n]
        ids = table.str_columns.get(id_ord, [str(i) for i in range(n)])
        for i in range(n):
            for j in range(i + 1, n):
                out_lines.append(od.join(
                    [gkey, ids[i], ids[j], str(int(dmat[i, j]))]))
        counters.increment("Similarity", "Groups", 1)
    counters.increment("Similarity", "Pairs", len(out_lines))
    artifacts.write_text_output(out_path, out_lines)
    return counters


@register("org.avenir.knn.KnnPipeline", "knnPipeline", "knnInProcess",
          dist="partition")
def knn_pipeline(cfg: Config, in_path: str, out_path: str) -> Counters:
    """The whole knn.sh pipeline fused in process: tiled device
    distance + running top-k (ops/distance.pairwise_topk) feeding the
    Neighborhood vote directly — the all-pairs CSV between jobs
    (resource/knn.sh:47,53) never exists.  sameTypeSimilarity +
    nearestNeighbor remain for file-level parity with the reference.

    Input like sameTypeSimilarity: a dir whose sts.base.set.split.prefix
    files are the train set and the rest test; inter-set output +
    validation counters match the nearestNeighbor job.  A single file (or
    dir with only one kind) is intra-set, where this job deliberately
    diverges from the file pipeline: every row gets its k nearest among
    ALL other rows (proper leave-one-out), whereas sameTypeSimilarity's
    once-per-unordered-pair emission gives the file flow asymmetric,
    shrinking candidate sets (row i only ever sees rows > i).
    Class-conditional posterior weighting needs the Bayesian-join file
    flow; this job rejects it (and regression mode, which needs the file
    layout's target columns) loudly."""
    from ..ops.distance import DistanceComputer
    from ..models import knn as K
    from ..core.metrics import ConfusionMatrix
    counters = Counters()
    params = _knn_params(cfg)
    if params.class_cond_weighted:
        raise ValueError(
            "knnPipeline has no Bayesian posterior join; run the file "
            "pipeline (sameTypeSimilarity -> featureCondProbJoiner -> "
            "nearestNeighbor) for class-conditional weighting")
    if params.prediction_mode == "regression":
        raise ValueError(
            "knnPipeline is classification-only; KNN regression needs the "
            "nearestNeighbor file layout's target columns")
    schema = _schema_path(cfg, "sts.same.schema.file.path")
    delim = cfg.field_delim_regex
    od = cfg.field_delim_out
    prefix = cfg.get("sts.base.set.split.prefix", "tr")
    scale = cfg.get_int("sts.distance.scale", 1000)
    metric = cfg.get("sts.distance.metric", "euclidean")
    validation = cfg.get_boolean("nen.validation.mode", True)
    output_class_distr = cfg.get_boolean("nen.output.class.distr", False)

    train, test, intra_set = _load_train_test(in_path, prefix, schema, delim)
    comp = DistanceComputer(schema, metric=metric, scale=scale)
    k = min(params.top_match_count, train.n_rows - (1 if intra_set else 0))
    # nen.train.shard=true: multi-host data-parallel over the TRAIN axis
    # (TPU_NOTES §20) — each shard scans the FULL test set against its
    # row-range of the train set and the running best-k lists merge
    # through ONE lock-step collective per test chunk, so every shard
    # computes the identical (bit-identical to single-host) predictions.
    # The default stays the partition-mode test-axis split below.
    train_sharded = cfg.get_boolean("nen.train.shard", False)
    knn_reducer = None
    if train_sharded:
        from ..parallel.collectives import AllReducer
        from ..parallel.distributed import shard_spec
        spec = shard_spec()
        knn_reducer = AllReducer(spec=spec, name="knn-train")
        tr_lo, tr_hi = spec.range_for(train.n_rows)
        t_lo = 0
        nd, idx = comp.pairwise_topk(
            test, train.take_rows(tr_lo, tr_hi),
            k + 1 if intra_set else k,
            shard_reducer=knn_reducer, shard_base=tr_lo)
    else:
        # partition mode: this process classifies its work_slice of the
        # test axis against the FULL train set; per-process part files
        # union to the complete prediction set (single-process: slice =
        # everything)
        from ..parallel.distributed import work_slice
        t_lo, t_hi = work_slice(test.n_rows)
        test = test.take_rows(t_lo, t_hi)
        # intra-set: fetch one extra neighbor, then drop the self-match
        nd, idx = comp.pairwise_topk(test, train,
                                     k + 1 if intra_set else k)
    if intra_set:
        # self indices are TRAIN-relative: offset by the test slice start
        self_col = (np.arange(test.n_rows) + t_lo)[:, None]
        keep_last = np.argsort(idx == self_col, axis=1, kind="stable")[:, :k]
        nd = np.take_along_axis(nd, keep_last, axis=1)
        idx = np.take_along_axis(idx, keep_last, axis=1)

    cardinality = list(schema.class_attr_field.cardinality or [])
    # vote over SORTED class values like the nearestNeighbor job (which
    # sorts the classes observed in its input) so argmax tie-breaks match
    # the file pipeline even for unsorted schema cardinality; train rows
    # with labels outside the cardinality (code -1) vote as "?" — the
    # file pipeline emits "?" for them and treats it as its own class
    train_codes = train.class_codes()
    unknown = bool((train_codes < 0).any())
    class_values = sorted(set(cardinality) | ({"?"} if unknown else set()))
    if cardinality:
        remap = np.array([class_values.index(c) for c in cardinality],
                         dtype=np.int32)
        mapped = np.where(
            train_codes >= 0, remap[np.clip(train_codes, 0, None)],
            class_values.index("?") if unknown else 0).astype(np.int32)
    else:  # no cardinality: every label is unknown, all votes are "?"
        mapped = np.zeros_like(train_codes)
    ncls = mapped[idx]                            # (n_test, k)
    res = K.classify_topk(nd, ncls, class_values, params)

    id_ord = schema.id_fields[0].ordinal if schema.id_fields else 0
    test_ids = test.str_columns.get(
        id_ord, [str(i) for i in range(t_lo, t_lo + test.n_rows)])
    actual = None
    if validation:
        actual = [cardinality[c] if c >= 0 else "?"
                  for c in test.class_codes()]
        # (neg, pos) like the nearestNeighbor job: schema cardinality first
        # (NearestNeighbor.java:287-292), then the nen.class.attribute.values
        # override, then a degenerate-cardinality fallback
        if len(cardinality) >= 2:
            neg, pos = cardinality[0], cardinality[1]
        elif params.pos_class:
            neg, pos = params.neg_class, params.pos_class
        else:
            cvs = class_values if len(class_values) >= 2 else class_values * 2
            neg, pos = cvs[0], cvs[1]
        cm = ConfusionMatrix(neg, pos)
    out_lines = []
    for i in range(test.n_rows):
        parts = [test_ids[i]]
        if output_class_distr:
            for ci, cv in enumerate(class_values):
                parts.append(cv)
                parts.append(str(res.class_distr[i][ci]))
        if validation:
            parts.append(actual[i])
            cm.report(res.pred_class[i], actual[i])
        parts.append(res.pred_class[i])
        out_lines.append(od.join(parts))
    # train-sharded mode: every shard computed the IDENTICAL full
    # prediction set, so the output is a global artifact (identical bytes
    # from every process, like the sharded training jobs) and the
    # already-global counters are emitted by shard 0 only — the
    # cross-process counter sum must not multiply them by the shard count
    if knn_reducer is None or knn_reducer.spec.index == 0:
        if validation:
            cm.export(counters)
        counters.increment("Neighborhood", "Test records", test.n_rows)
    # partition-mode job: each process emits predictions for its test
    # slice as its own part file (single-process: part-r-00000 as before);
    # counters are per-slice partials that cli.run all-reduces
    artifacts.write_text_output(out_path, out_lines,
                                local_shard=knn_reducer is None)
    return counters


@register("org.avenir.knn.FeatureCondProbJoiner", "featureCondProbJoiner",
          dist="gather")
def feature_cond_prob_joiner(cfg: Config, in_path: str, out_path: str
                             ) -> Counters:
    """Join Bayesian feature posterior probabilities onto nearest-neighbor
    lines (knn/FeatureCondProbJoiner.java; knn.sh joinFeatureDistr step).

    Input dir holds two kinds of files: those starting with
    fcb.feature.cond.prob.split.prefix (default 'condProb') are the
    BayesianPredictor feature-prob output (itemID, P(x), class, P(x|c) pairs,
    actualClass — :111-118 mapper), the rest are neighbor lines
    (trainId,testId,distance,trainClass,testClass).  Output = the
    class-conditional-weighted layout NearestNeighbor consumes:
    testId, testClassActual, trainId, distance, trainClass, postProb
    (JoinerReducer :170-177)."""
    import glob as _glob
    counters = Counters()
    prefix = cfg.get("fcb.feature.cond.prob.split.prefix", "condProb")
    split = _splitter(cfg.field_delim_regex)
    od = cfg.field_delim_out
    prob_lines: List[List[str]] = []
    neigh_lines: List[List[str]] = []
    files = sorted(_glob.glob(os.path.join(in_path, "*"))) \
        if os.path.isdir(in_path) else [in_path]
    for p in files:
        base = os.path.basename(p)
        if not os.path.isfile(p) or base.startswith(("_", ".")):
            continue  # skip Hadoop-style markers (_SUCCESS, .crc)
        bucket = prob_lines if base.startswith(prefix) else neigh_lines
        bucket.extend(split(l) for l in artifacts.read_text_input(p))
    # train item -> (actual class, P(x|actual class))
    cls_prob: Dict[str, str] = {}
    for it in prob_lines:
        actual = it[-1]
        pairs = it[2:-1]
        for i in range(0, len(pairs) - 1, 2):
            if pairs[i] == actual:
                cls_prob[it[0]] = f"{actual}{od}{pairs[i + 1]}"
                break
    out = []
    for it in neigh_lines:
        train_id, test_id, dist = it[0], it[1], it[2]
        test_class = it[4] if len(it) > 4 else "?"
        joined = cls_prob.get(train_id)
        if joined is None:
            # a train item whose actual class had no (class, prob) pair —
            # bap.predict.class did not cover every class value
            counters.increment("Join", "unmatchedNeighbors")
            continue
        out.append(od.join([test_id, test_class, train_id, dist, joined]))
    artifacts.write_text_output(out_path, out)
    counters.set("Join", "joinedLines", len(out))
    return counters


def _knn_params(cfg: Config):
    from ..models.knn import KnnParams
    params = KnnParams(
        top_match_count=cfg.get_int("nen.top.match.count", 10),
        kernel_function=cfg.get("nen.kernel.function", "none"),
        kernel_param=cfg.get_int("nen.kernel.param", -1),
        # the reference uses BOTH spellings: mapper reads
        # nen.class.condition.weighted (NearestNeighbor.java:120), reducer the
        # typo'd nen.class.condtion.weighted (:239); accept either
        class_cond_weighted=cfg.get_boolean("nen.class.condtion.weighted", False)
        or cfg.get_boolean("nen.class.condition.weighted", False),
        inverse_distance_weighted=cfg.get_boolean("nen.inverse.distance.weighted",
                                                  False),
        decision_threshold=cfg.get_float("nen.decision.threshold", -1.0),
        use_cost_based_classifier=cfg.get_boolean("nen.use.cost.based.classifier",
                                                  False),
        prediction_mode=cfg.get("nen.prediction.mode", "classification"),
        regression_method=cfg.get("nen.regression.method", "average"),
    )
    cav = cfg.get_list("nen.class.attribute.values")
    if cav:
        params.pos_class, params.neg_class = cav[0], cav[1]
    if params.use_cost_based_classifier:
        costs = cfg.must_get_list("nen.misclassification.cost")
        params.false_pos_cost, params.false_neg_cost = int(costs[0]), int(costs[1])
    return params


@register("org.avenir.knn.NearestNeighbor", "nearestNeighbor", "knnClassifier",
          dist="gather")
def nearest_neighbor(cfg: Config, in_path: str, out_path: str) -> Counters:
    """KNN classification/regression over precomputed neighbor lines
    (knn/NearestNeighbor.java; the knn.sh 'knnClassifier' step).

    Input layout (TopMatchesMapper :130-183):
      normal:            trainId,testId,distance,trainClass[,testClassActual]
      classCondWeighted: testId,testClassActual,trainId,distance,trainClass,postProb
    Output: testId[,classDistr...][,actualClass],predicted  + Validation
    counters in validation mode."""
    import numpy as _np
    from ..models import knn as K
    counters = Counters()
    params = _knn_params(cfg)
    validation = cfg.get_boolean("nen.validation.mode", True)
    output_class_distr = cfg.get_boolean("nen.output.class.distr", False)
    delim = cfg.field_delim_regex
    od = cfg.field_delim_out
    lines_in = artifacts.read_text_input(in_path)

    is_linreg = (params.prediction_mode == "regression" and
                 params.regression_method == "linearRegression")

    # group neighbor candidates per test entity (TopMatchesMapper layouts)
    split_line = _splitter(delim)
    groups: Dict[str, Dict] = {}
    for line in lines_in:
        it = split_line(line)
        train_regr = test_regr = 0.0
        if params.class_cond_weighted:
            test_id, actual, train_id = it[0], it[1], it[2]
            dist, tclass, fpp = int(it[3]), it[4], float(it[5])
        else:
            idx = 0
            train_id = it[idx]; idx += 1
            test_id = it[idx]; idx += 1
            dist = int(it[idx]); idx += 1
            tclass = it[idx]; idx += 1
            actual = ""
            if validation:
                actual = it[idx]; idx += 1
            if is_linreg:
                train_regr = float(it[idx]); idx += 1
                test_regr = float(it[idx]); idx += 1
            fpp = -1.0
        g = groups.setdefault(test_id, {"actual": actual, "d": [], "c": [],
                                        "fpp": [], "trv": [], "tev": test_regr})
        g["d"].append(dist)
        g["c"].append(tclass)
        g["fpp"].append(fpp)
        g["trv"].append(train_regr)

    if not groups:
        artifacts.write_text_output(out_path, [])
        return counters

    class_values = sorted({c for g in groups.values() for c in g["c"]})
    cls_code = {c: i for i, c in enumerate(class_values)}
    test_ids = sorted(groups.keys())
    max_n = max(len(groups[t]["d"]) for t in test_ids)
    dmat = _np.full((len(test_ids), max_n), K.PAD_DISTANCE, dtype=_np.int64)
    cmat = _np.zeros((len(test_ids), max_n), dtype=_np.int32)
    fmat = _np.full((len(test_ids), max_n), -1.0, dtype=_np.float32)
    for i, t in enumerate(test_ids):
        g = groups[t]
        m = len(g["d"])
        dmat[i, :m] = g["d"]
        cmat[i, :m] = [cls_code[c] for c in g["c"]]
        fmat[i, :m] = g["fpp"]

    if params.prediction_mode == "regression":
        vals = _np.array([[float(class_values[c]) for c in row] for row in cmat])
        if is_linreg:
            nin = _np.zeros_like(dmat, dtype=_np.float64)
            x0 = _np.zeros((len(test_ids),))
            for i, t in enumerate(test_ids):
                m = len(groups[t]["trv"])
                nin[i, :m] = groups[t]["trv"]
                x0[i] = groups[t]["tev"]
            pred_vals = K.regress_grouped(dmat, vals, params,
                                          regr_input=x0, neighbor_input=nin)
        else:
            pred_vals = K.regress_grouped(dmat, vals, params)
        out_lines = []
        for i, t in enumerate(test_ids):
            parts = [t]
            if validation:
                parts.append(groups[t]["actual"])
            parts.append(str(int(pred_vals[i])))
            out_lines.append(od.join(parts))
        artifacts.write_text_output(out_path, out_lines)
        return counters

    res = K.classify_grouped(dmat, cmat, class_values, params, fmat)

    from ..core.metrics import ConfusionMatrix
    cm = None
    if validation:
        # the reference builds the matrix from the schema's class cardinality:
        # ConfusionMatrix(cardinality[0], cardinality[1]) = (neg, pos)
        # (NearestNeighbor.java:287-292)
        if "nen.feature.schema.file.path" in cfg:
            card = _schema_path(cfg, "nen.feature.schema.file.path") \
                .class_attr_field.cardinality
            neg, pos = card[0], card[1]
        elif params.pos_class:
            neg, pos = params.neg_class, params.pos_class
        else:
            cvs = class_values if len(class_values) >= 2 else class_values * 2
            neg, pos = cvs[0], cvs[1]
        cm = ConfusionMatrix(neg, pos)
    out_lines = []
    for i, t in enumerate(test_ids):
        parts = [t]
        if output_class_distr:
            distr = res.weighted_class_distr[i] if params.class_cond_weighted \
                else res.class_distr[i]
            for ci, cv in enumerate(class_values):
                parts.append(cv)
                parts.append(str(distr[ci]))
        if validation:
            parts.append(groups[t]["actual"])
        parts.append(res.pred_class[i])
        out_lines.append(od.join(parts))
        if cm is not None:
            cm.report(res.pred_class[i], groups[t]["actual"])
    if cm is not None:
        cm.export(counters)
    artifacts.write_text_output(out_path, out_lines)
    return counters


# --------------------------------------------------------------------------
# org.avenir.bayesian
# --------------------------------------------------------------------------

def _bayesian_predict_text(cfg: Config, in_path: str, out_path: str
                           ) -> Counters:
    """Text-mode prediction: tokenize each line's text, classify by summed
    token log-posteriors, echo record + prediction (+ validation counters
    when the class label column is present)."""
    from ..models import bayes_text
    counters = Counters()
    od = cfg.field_delim_out
    delim = cfg.field_delim_regex
    model = bayes_text.TextBayesModel.from_lines(
        artifacts.read_text_input(cfg.must_get("bap.bayesian.model.file.path")),
        od)
    lines_in = [l for l in artifacts.read_text_input(in_path) if l.strip()]
    texts, actuals = [], []
    for line in lines_in:
        text, _, label = line.rpartition(delim)
        if label.strip() in model.class_values and text:
            texts.append(text)
            actuals.append(label.strip())
        else:
            texts.append(line)
            actuals.append(None)
    pred, _scores = bayes_text.classify_text(model, texts)
    out = [f"{raw}{od}{p}" for raw, p in zip(lines_in, pred)]
    artifacts.write_text_output(out_path, out, role="m")
    known = [(a, p) for a, p in zip(actuals, pred) if a is not None]
    if known:
        correct = sum(1 for a, p in known if a == p)
        counters.set("Validation", "Correct", correct)
        counters.set("Validation", "Incorrect", len(known) - correct)
        counters.set("Validation", "Accuracy",
                     int(100 * correct / len(known)))
    return counters


@register("org.avenir.bayesian.BayesianDistribution", "bayesianDistribution",
          dist="sharded")
def bayesian_distribution(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Naive Bayes training job (bayesian/BayesianDistribution.java).

    Config keys honored (same names as the reference): bad.feature.schema.file.path,
    field.delim.regex, field.delim.out.  With NO schema file configured the
    input is text mode — ``text,classLabel`` lines, the token stream being
    the single feature (BayesianDistribution.java:117-130)."""
    from ..models import bayes
    counters = Counters()
    if cfg.get("bad.feature.schema.file.path") is None:
        from ..models import bayes_text
        model_t = bayes_text.train_text(artifacts.read_text_input(in_path),
                                        cfg.field_delim_regex)
        artifacts.write_text_output(out_path,
                                    model_t.to_lines(cfg.field_delim_out))
        counters.set("Distribution Data", "Class prior",
                     len(model_t.class_values))
        counters.set("Distribution Data", "Vocabulary", len(model_t.vocab))
        return counters
    schema = _schema_path(cfg, "bad.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex,
                     bad_records=_bad_records_policy(cfg, counters, out_path))
    ctx = runtime_context()
    model = bayes.train(table, ctx, counters)
    artifacts.write_text_output(out_path, model.to_lines(cfg.field_delim_out))
    return counters


@register("org.avenir.bayesian.BayesianPredictor", "bayesianPredictor",
          dist="map")
def bayesian_predictor(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Naive Bayes prediction job (bayesian/BayesianPredictor.java).

    Keys: bap.feature.schema.file.path, bap.bayesian.model.file.path,
    bap.predict.class, bap.predict.class.cost, bap.class.prob.diff.threshold,
    bap.output.feature.prob.only.  With NO schema file configured the input
    is text mode: ``text[,classLabel]`` lines classified by token stream."""
    from ..models import bayes
    if cfg.get("bap.feature.schema.file.path") is None:
        return _bayesian_predict_text(cfg, in_path, out_path)
    counters = Counters()
    schema = _schema_path(cfg, "bap.feature.schema.file.path")
    delim = cfg.field_delim_regex
    out_delim = cfg.field_delim_out
    table = load_csv(in_path, schema, delim, keep_raw=True)
    model_lines = artifacts.read_text_input(cfg.must_get("bap.bayesian.model.file.path"))
    model = bayes.NaiveBayesModel.from_lines(model_lines, schema, delim)
    res = bayes.predict(model, table)

    # predicting classes default to the first two of the class cardinality
    # (BayesianPredictor.java:151-159)
    pred_classes = cfg.get_list("bap.predict.class") or model.class_values[:2]
    if cfg.get_boolean("bap.output.feature.prob.only", False) \
            and not cfg.get_list("bap.predict.class"):
        # feature-prob mode feeds featureCondProbJoiner, which needs every
        # class's posterior (a record whose actual class is missing from the
        # pair list would silently drop all its neighbors downstream)
        pred_classes = list(model.class_values)
    neg_class, pos_class = pred_classes[0], pred_classes[1]
    prob_diff_threshold = cfg.get_int("bap.class.prob.diff.threshold", -1)

    arbitrator = None
    if cfg.get("bap.predict.class.cost") is not None:
        costs = cfg.must_get_list("bap.predict.class.cost", delim=out_delim)
        arbitrator = CostBasedArbitrator(neg_class, pos_class,
                                         int(costs[0]), int(costs[1]))

    cls_index = {v: i for i, v in enumerate(model.class_values)}
    actual_codes = table.class_codes()
    lines: List[str] = []

    if cfg.get_boolean("bap.output.feature.prob.only", False):
        # feature-probability output mode (BayesianPredictor.outputFeatureProb
        # :276-286): itemID, P(x), then (class, P(x|c)) pairs, then actual;
        # no prediction, no validation counters.
        id_ord = schema.id_fields[0].ordinal if schema.id_fields else 0
        for i, raw in enumerate(table.raw_rows):
            parts = [raw[id_ord], repr(float(res.feature_prior_prob[i]))]
            for cv in pred_classes:
                parts.append(cv)
                parts.append(repr(float(res.feature_post_prob[i, cls_index[cv]])))
            actual = (model.class_values[actual_codes[i]]
                      if actual_codes[i] >= 0 else "?")
            parts.append(actual)
            lines.append(out_delim.join(parts))
        artifacts.write_text_output(out_path, lines, role="m")
        return counters

    from ..core.metrics import ConfusionMatrix
    cm = ConfusionMatrix(neg_class, pos_class)
    for i, raw in enumerate(table.raw_rows):
        record = out_delim.join(raw)
        if arbitrator is not None:
            pos_p = int(res.class_probs[i, cls_index[pos_class]])
            neg_p = int(res.class_probs[i, cls_index[neg_class]])
            pred = arbitrator.arbitrate(pos_p, neg_p)
            prob = 100  # reference costArbitrate sets predProb=100 (:389-390)
        else:
            pred = res.pred_class[i]
            prob = int(res.pred_prob[i])
        parts = [record, pred, str(prob)]
        if prob_diff_threshold > 0:
            parts.append("classified" if res.class_prob_diff[i] > prob_diff_threshold
                         else "ambiguous")
        lines.append(out_delim.join(parts))
        actual = model.class_values[actual_codes[i]] if actual_codes[i] >= 0 else "?"
        cm.report(pred, actual)
        if pred == actual:
            counters.increment("Validation", "Correct")
        else:
            counters.increment("Validation", "Incorrect")
    cm.export(counters)
    artifacts.write_text_output(out_path, lines, role="m")  # map-only job
    return counters
