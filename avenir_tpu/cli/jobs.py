"""Job registry: the ``hadoop jar avenir.jar <ClassName> -Dconf.path=... in out``
entry points, rebuilt (SURVEY.md §1 L6->L5->L4 interface).

Every reference job class name (and a short camelCase alias) maps to a Python
job function ``job(config, in_path, out_path) -> Counters``.  Driver shell
scripts keep working by swapping the ``hadoop jar``/``spark-submit`` line for
``python -m avenir_tpu.cli.run <ClassName> -Dconf.path=<file> <in> <out>``.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.config import Config
from ..core.schema import FeatureSchema
from ..core.table import load_csv
from ..core.metrics import Counters, CostBasedArbitrator
from ..core import artifacts
from ..parallel.mesh import MeshContext

JOBS: Dict[str, Callable] = {}


def register(*names: str):
    def deco(fn):
        for n in names:
            JOBS[n] = fn
        return fn
    return deco


def resolve(name: str) -> Callable:
    if name in JOBS:
        return JOBS[name]
    # allow bare class name for fully-qualified registrations
    for k, v in JOBS.items():
        if k.split(".")[-1] == name:
            return v
    raise KeyError(f"unknown job {name!r}; known: {sorted(JOBS)}")


def _schema_path(cfg: Config, key: str) -> FeatureSchema:
    return FeatureSchema.load(cfg.must_get(key))


# --------------------------------------------------------------------------
# org.avenir.bayesian
# --------------------------------------------------------------------------

@register("org.avenir.bayesian.BayesianDistribution", "bayesianDistribution")
def bayesian_distribution(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Naive Bayes training job (bayesian/BayesianDistribution.java).

    Config keys honored (same names as the reference): bad.feature.schema.file.path,
    field.delim.regex, field.delim.out."""
    from ..models import bayes
    counters = Counters()
    schema = _schema_path(cfg, "bad.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    ctx = MeshContext()
    model = bayes.train(table, ctx, counters)
    artifacts.write_text_output(out_path, model.to_lines(cfg.field_delim_out))
    return counters


@register("org.avenir.bayesian.BayesianPredictor", "bayesianPredictor")
def bayesian_predictor(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Naive Bayes prediction job (bayesian/BayesianPredictor.java).

    Keys: bap.feature.schema.file.path, bap.bayesian.model.file.path,
    bap.predict.class, bap.predict.class.cost, bap.class.prob.diff.threshold,
    bap.output.feature.prob.only."""
    from ..models import bayes
    counters = Counters()
    schema = _schema_path(cfg, "bap.feature.schema.file.path")
    delim = cfg.field_delim_regex
    out_delim = cfg.field_delim_out
    table = load_csv(in_path, schema, delim, keep_raw=True)
    model_lines = artifacts.read_text_input(cfg.must_get("bap.bayesian.model.file.path"))
    model = bayes.NaiveBayesModel.from_lines(model_lines, schema, delim)
    res = bayes.predict(model, table)

    # predicting classes default to the first two of the class cardinality
    # (BayesianPredictor.java:151-159)
    pred_classes = cfg.get_list("bap.predict.class") or model.class_values[:2]
    neg_class, pos_class = pred_classes[0], pred_classes[1]
    prob_diff_threshold = cfg.get_int("bap.class.prob.diff.threshold", -1)

    arbitrator = None
    if cfg.get("bap.predict.class.cost") is not None:
        costs = cfg.must_get_list("bap.predict.class.cost", delim=out_delim)
        arbitrator = CostBasedArbitrator(neg_class, pos_class,
                                         int(costs[0]), int(costs[1]))

    cls_index = {v: i for i, v in enumerate(model.class_values)}
    actual_codes = table.class_codes()
    lines: List[str] = []

    if cfg.get_boolean("bap.output.feature.prob.only", False):
        # feature-probability output mode (BayesianPredictor.outputFeatureProb
        # :276-286): itemID, P(x), then (class, P(x|c)) pairs, then actual;
        # no prediction, no validation counters.
        id_ord = schema.id_fields[0].ordinal if schema.id_fields else 0
        for i, raw in enumerate(table.raw_rows):
            parts = [raw[id_ord], repr(float(res.feature_prior_prob[i]))]
            for cv in pred_classes:
                parts.append(cv)
                parts.append(repr(float(res.feature_post_prob[i, cls_index[cv]])))
            actual = (model.class_values[actual_codes[i]]
                      if actual_codes[i] >= 0 else "?")
            parts.append(actual)
            lines.append(out_delim.join(parts))
        artifacts.write_text_output(out_path, lines, role="m")
        return counters

    from ..core.metrics import ConfusionMatrix
    cm = ConfusionMatrix(neg_class, pos_class)
    for i, raw in enumerate(table.raw_rows):
        record = out_delim.join(raw)
        if arbitrator is not None:
            pos_p = int(res.class_probs[i, cls_index[pos_class]])
            neg_p = int(res.class_probs[i, cls_index[neg_class]])
            pred = arbitrator.arbitrate(pos_p, neg_p)
            prob = 100  # reference costArbitrate sets predProb=100 (:389-390)
        else:
            pred = res.pred_class[i]
            prob = int(res.pred_prob[i])
        parts = [record, pred, str(prob)]
        if prob_diff_threshold > 0:
            parts.append("classified" if res.class_prob_diff[i] > prob_diff_threshold
                         else "ambiguous")
        lines.append(out_delim.join(parts))
        actual = model.class_values[actual_codes[i]] if actual_codes[i] >= 0 else "?"
        cm.report(pred, actual)
        if pred == actual:
            counters.increment("Validation", "Correct")
        else:
            counters.increment("Validation", "Incorrect")
    cm.export(counters)
    artifacts.write_text_output(out_path, lines, role="m")  # map-only job
    return counters
