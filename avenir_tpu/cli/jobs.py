"""Job registry: the ``hadoop jar avenir.jar <ClassName> -Dconf.path=... in out``
entry points, rebuilt (SURVEY.md §1 L6->L5->L4 interface).

Every reference job class name (and a short camelCase alias) maps to a Python
job function ``job(config, in_path, out_path) -> Counters``.  Driver shell
scripts keep working by swapping the ``hadoop jar``/``spark-submit`` line for
``python -m avenir_tpu.cli.run <ClassName> -Dconf.path=<file> <in> <out>``.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.config import Config
from ..core.schema import FeatureSchema
from ..core.table import load_csv
from ..core.metrics import Counters, CostBasedArbitrator
from ..core import artifacts
from ..parallel.mesh import MeshContext

JOBS: Dict[str, Callable] = {}


def register(*names: str):
    def deco(fn):
        for n in names:
            JOBS[n] = fn
        return fn
    return deco


def resolve(name: str) -> Callable:
    if name in JOBS:
        return JOBS[name]
    # allow bare class name for fully-qualified registrations
    for k, v in JOBS.items():
        if k.split(".")[-1] == name:
            return v
    raise KeyError(f"unknown job {name!r}; known: {sorted(JOBS)}")


def _schema_path(cfg: Config, key: str) -> FeatureSchema:
    return FeatureSchema.load(cfg.must_get(key))


# --------------------------------------------------------------------------
# org.avenir.tree
# --------------------------------------------------------------------------

def _tree_params(cfg: Config):
    """Map the dtb.* keys (resource/detr.properties, rafo.properties) onto
    TreeParams."""
    from ..models.tree import TreeParams
    # defaults match the reference job's (DecisionTreeBuilder.java:169,179,
    # 434,442,448): giniIndex / notUsedYet / best / minInfoGain / withReplace
    return TreeParams(
        split_algorithm=cfg.get("dtb.split.algorithm", "giniIndex"),
        attr_select_strategy=cfg.get("dtb.split.attribute.selection.strategy",
                                     "notUsedYet"),
        random_split_set_size=cfg.get_int("dtb.random.split.set.size", 3),
        split_select_strategy=cfg.get("dtb.split.select.strategy", "best"),
        top_split_count=cfg.get_int("dtb.top.split.count", 3),
        stopping_strategy=cfg.get("dtb.path.stopping.strategy", "minInfoGain"),
        max_depth=cfg.get_int("dtb.max.depth.limit", 3),
        min_info_gain=cfg.get_float("dtb.min.info.gain.limit", -1.0),
        min_population=cfg.get_int("dtb.min.population.limit", -1),
        sub_sampling=cfg.get("dtb.sub.sampling.strategy", "withReplace"),
        sub_sampling_rate=cfg.get_float("dtb.sub.sampling.rate", 100.0),
        seed=cfg.get_int("dtb.random.seed"),
    )


@register("org.avenir.tree.DecisionTreeBuilder", "decisionTreeBuilder")
def decision_tree_builder(cfg: Config, in_path: str, out_path: str) -> Counters:
    """One level of tree growth per invocation — the reference job contract
    (tree/DecisionTreeBuilder.java, driven by resource/detr.sh's rotation of
    dtb.decision.file.path.out -> .in between runs).

    Differences from the reference noted: the job does not write re-tagged
    record files; records are routed by re-evaluating the decision paths, so
    the output dir just carries the input records forward for script compat."""
    from ..models import tree as T
    counters = Counters()
    schema = _schema_path(cfg, "dtb.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex, keep_raw=True)
    params = _tree_params(cfg)
    builder = T.TreeBuilder(table, params, MeshContext())
    dec_in = cfg.get("dtb.decision.file.path.in")
    dpl = T.DecisionPathList.from_json(open(dec_in).read()) if dec_in else None
    new_dpl = builder.build_one_level(table, dpl)
    with open(cfg.must_get("dtb.decision.file.path.out"), "w") as fh:
        fh.write(new_dpl.to_json())
    if out_path:
        artifacts.write_text_output(
            out_path, (cfg.field_delim_out.join(r) for r in table.raw_rows))
    counters.increment("Decision tree", "Paths", len(new_dpl.decision_paths))
    return counters


@register("org.avenir.tree.RandomForestBuilder", "randomForestBuilder")
def random_forest_builder(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Full in-process random forest: the rafo.sh per-tree rerun loop
    (resource/rafo.sh:34-43) collapsed into one job.  Writes one decision-path
    JSON per tree into the output dir (tree_<i>.json)."""
    from ..models.forest import ForestParams, build_forest
    counters = Counters()
    schema = _schema_path(cfg, "dtb.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    params = ForestParams(tree=_tree_params(cfg),
                          num_trees=cfg.get_int("dtb.num.trees", 5),
                          seed=cfg.get_int("dtb.random.seed", 0))
    models = build_forest(table, params, MeshContext())
    os.makedirs(out_path, exist_ok=True)
    for i, dpl in enumerate(models):
        with open(os.path.join(out_path, f"tree_{i}.json"), "w") as fh:
            fh.write(dpl.to_json())
    counters.increment("Random forest", "Trees", len(models))
    return counters


@register("org.avenir.model.ModelPredictor", "modelPredictor")
def model_predictor_job(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Generic map-only predictor (model/ModelPredictor.java:46-82): loads N
    decision-path model files (mop.model.dir.path + mop.model.file.names) and
    predicts via single model or weighted ensemble vote
    (mop.ensemble.memeber.weights — reference key name, typo included)."""
    from ..models.tree import DecisionPathList
    from ..models.forest import model_predictor
    counters = Counters()
    schema = _schema_path(cfg, "mop.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex, keep_raw=True)
    model_dir = cfg.get("mop.model.dir.path", "")
    names = cfg.must_get_list("mop.model.file.names")
    path_lists = []
    for nm in names:
        p = os.path.join(model_dir, nm) if model_dir else nm
        with open(p) as fh:
            path_lists.append(DecisionPathList.from_json(fh.read()))
    weights = cfg.get_float_list("mop.ensemble.memeber.weights")
    output_mode = cfg.get("mop.output.mode", "withRecord")
    # per-mode mandatory ordinals (ModelPredictor.java:165-172); error
    # counting also requires the class ordinal (:116)
    error_counting = cfg.get_boolean("mop.error.counting.enabled", False)
    class_ord = None
    if output_mode == "withActualClassAttr" or error_counting:
        class_ord = cfg.must_get_int(
            "mop.rec.class.attr.ordinal",
            "missing class attribute ordinal") if \
            "mop.rec.class.attr.ordinal" in cfg else \
            cfg.must_get_int("mop.class.attr.ord",
                             "missing class attribute ordinal")
    id_ord = cfg.get_int("mop.rec.id.ordinal", 0) \
        if output_mode != "withKId" else \
        cfg.must_get_int("mop.rec.id.ordinal", "missing id ordinal")
    lines = model_predictor(
        table, schema, path_lists,
        output_mode=output_mode,
        id_ordinal=id_ord,
        class_attr_ordinal=class_ord,
        class_attr_values=cfg.get_list("mop.class.attr.values"),
        error_counting=error_counting,
        weights=weights,
        min_odds_ratio=cfg.get_float("mop.min.odds.ratio", 1.0),
        out_delim=cfg.field_delim_out, counters=counters)
    artifacts.write_text_output(out_path, lines, role="m")
    return counters


# --------------------------------------------------------------------------
# org.avenir.bayesian
# --------------------------------------------------------------------------

@register("org.avenir.bayesian.BayesianDistribution", "bayesianDistribution")
def bayesian_distribution(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Naive Bayes training job (bayesian/BayesianDistribution.java).

    Config keys honored (same names as the reference): bad.feature.schema.file.path,
    field.delim.regex, field.delim.out."""
    from ..models import bayes
    counters = Counters()
    schema = _schema_path(cfg, "bad.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    ctx = MeshContext()
    model = bayes.train(table, ctx, counters)
    artifacts.write_text_output(out_path, model.to_lines(cfg.field_delim_out))
    return counters


@register("org.avenir.bayesian.BayesianPredictor", "bayesianPredictor")
def bayesian_predictor(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Naive Bayes prediction job (bayesian/BayesianPredictor.java).

    Keys: bap.feature.schema.file.path, bap.bayesian.model.file.path,
    bap.predict.class, bap.predict.class.cost, bap.class.prob.diff.threshold,
    bap.output.feature.prob.only."""
    from ..models import bayes
    counters = Counters()
    schema = _schema_path(cfg, "bap.feature.schema.file.path")
    delim = cfg.field_delim_regex
    out_delim = cfg.field_delim_out
    table = load_csv(in_path, schema, delim, keep_raw=True)
    model_lines = artifacts.read_text_input(cfg.must_get("bap.bayesian.model.file.path"))
    model = bayes.NaiveBayesModel.from_lines(model_lines, schema, delim)
    res = bayes.predict(model, table)

    # predicting classes default to the first two of the class cardinality
    # (BayesianPredictor.java:151-159)
    pred_classes = cfg.get_list("bap.predict.class") or model.class_values[:2]
    neg_class, pos_class = pred_classes[0], pred_classes[1]
    prob_diff_threshold = cfg.get_int("bap.class.prob.diff.threshold", -1)

    arbitrator = None
    if cfg.get("bap.predict.class.cost") is not None:
        costs = cfg.must_get_list("bap.predict.class.cost", delim=out_delim)
        arbitrator = CostBasedArbitrator(neg_class, pos_class,
                                         int(costs[0]), int(costs[1]))

    cls_index = {v: i for i, v in enumerate(model.class_values)}
    actual_codes = table.class_codes()
    lines: List[str] = []

    if cfg.get_boolean("bap.output.feature.prob.only", False):
        # feature-probability output mode (BayesianPredictor.outputFeatureProb
        # :276-286): itemID, P(x), then (class, P(x|c)) pairs, then actual;
        # no prediction, no validation counters.
        id_ord = schema.id_fields[0].ordinal if schema.id_fields else 0
        for i, raw in enumerate(table.raw_rows):
            parts = [raw[id_ord], repr(float(res.feature_prior_prob[i]))]
            for cv in pred_classes:
                parts.append(cv)
                parts.append(repr(float(res.feature_post_prob[i, cls_index[cv]])))
            actual = (model.class_values[actual_codes[i]]
                      if actual_codes[i] >= 0 else "?")
            parts.append(actual)
            lines.append(out_delim.join(parts))
        artifacts.write_text_output(out_path, lines, role="m")
        return counters

    from ..core.metrics import ConfusionMatrix
    cm = ConfusionMatrix(neg_class, pos_class)
    for i, raw in enumerate(table.raw_rows):
        record = out_delim.join(raw)
        if arbitrator is not None:
            pos_p = int(res.class_probs[i, cls_index[pos_class]])
            neg_p = int(res.class_probs[i, cls_index[neg_class]])
            pred = arbitrator.arbitrate(pos_p, neg_p)
            prob = 100  # reference costArbitrate sets predProb=100 (:389-390)
        else:
            pred = res.pred_class[i]
            prob = int(res.pred_prob[i])
        parts = [record, pred, str(prob)]
        if prob_diff_threshold > 0:
            parts.append("classified" if res.class_prob_diff[i] > prob_diff_threshold
                         else "ambiguous")
        lines.append(out_delim.join(parts))
        actual = model.class_values[actual_codes[i]] if actual_codes[i] >= 0 else "?"
        cm.report(pred, actual)
        if pred == actual:
            counters.increment("Validation", "Correct")
        else:
            counters.increment("Validation", "Incorrect")
    cm.export(counters)
    artifacts.write_text_output(out_path, lines, role="m")  # map-only job
    return counters
