"""Regress-pack jobs (org.avenir.regress.*).

Config keys follow regress/LogisticRegressionJob.java setup()/checkConvergence:
feature.schema.file.path, coeff.file.path, positive.class.value,
convergence.criteria, iteration.limit, convergence.threshold, plus our
learning.rate / l2.regularization extensions (the reference has no step size —
it overwrites coefficients with the raw gradient aggregate).
"""

from __future__ import annotations

import os

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters, ConfusionMatrix
from ..core import artifacts
from ..core.table import load_csv
from .jobs import register, _schema_path


@register("org.avenir.regress.LogisticRegressionJob", "logisticRegression",
          dist="sharded")
def logistic_regression(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Train to convergence (the reference main()'s do-while over MR runs,
    LogisticRegressionJob.java:203-211, collapsed into one in-process loop).
    The coefficient history file is read if present (resume) and rewritten
    with one line per iteration.

    Multi-process (dist=sharded): each process loads its OWN data shard;
    per-iteration gradient sums are all-reduced inside
    LogisticTrainer.step, so every process walks the identical coefficient
    history — the reference reducer's aggregation as a collective."""
    from ..regress import logistic as LR
    counters = Counters()
    schema = _schema_path(cfg, "feature.schema.file.path")
    params = LR.LogisticParams(
        pos_class_value=cfg.must_get("positive.class.value"),
        learning_rate=cfg.get_float("learning.rate", 0.1),
        convergence_criteria=cfg.get("convergence.criteria", LR.ITER_LIMIT),
        iteration_limit=cfg.get_int("iteration.limit", 10),
        convergence_threshold=cfg.get_float("convergence.threshold", 5.0),
        l2=cfg.get_float("l2.regularization", 0.0),
    )
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    trainer = LR.LogisticTrainer(schema, params)
    coeff_path = cfg.must_get("coeff.file.path")
    history = []
    if os.path.exists(coeff_path):
        history = LR.parse_history(artifacts.read_text_input(coeff_path),
                                   cfg.field_delim_out)
    w, history, iters = trainer.train(table, history)
    with open(coeff_path, "w") as fh:
        for h in history:
            fh.write(LR.format_coefficients(h, cfg.field_delim_out) + "\n")
    od = cfg.field_delim_out
    artifacts.write_text_output(out_path,
                                [LR.format_coefficients(w, od)])
    # global-identical values: emit once so the sharded counter SUM is exact
    import jax
    p0 = jax.process_index() == 0
    counters.set("Regression", "iterations", iters if p0 else 0)
    counters.set("Regression", "historyLength", len(history) if p0 else 0)
    return counters


@register("org.avenir.regress.LogisticRegressionPredictor",
          "logisticRegressionPredictor",
          dist="map")
def logistic_regression_predictor(cfg: Config, in_path: str, out_path: str
                                  ) -> Counters:
    """Map-only prediction with the trained coefficient file (last history
    line); validation mode fills a confusion matrix like the other predictors
    (model/PredictiveModel.java error counting)."""
    from ..regress import logistic as LR
    counters = Counters()
    schema = _schema_path(cfg, "feature.schema.file.path")
    params = LR.LogisticParams(
        pos_class_value=cfg.must_get("positive.class.value"))
    trainer = LR.LogisticTrainer(schema, params)
    history = LR.parse_history(
        artifacts.read_text_input(cfg.must_get("coeff.file.path")),
        cfg.field_delim_out)
    if not history:
        raise ValueError("empty coefficient file")
    w = history[-1]
    table = load_csv(in_path, schema, cfg.field_delim_regex, keep_raw=True)
    threshold = cfg.get_float("decision.threshold", 0.5)
    probs = trainer.predict_proba(table, w)
    pos_code = schema.class_attr_field.cat_code(params.pos_class_value)
    card = schema.class_attr_field.cardinality or []
    neg_code = next((c for c in range(len(card)) if c != pos_code),
                    1 - pos_code)
    codes = np.where(probs > threshold, pos_code, neg_code)
    od = cfg.field_delim_out
    validate = cfg.get_boolean("validation.mode", False)
    pos = params.pos_class_value
    cm = ConfusionMatrix(
        neg_class=next((c for c in card if c != pos), "0"), pos_class=pos)
    lines = []
    for i, row in enumerate(table.raw_rows):
        pred = card[int(codes[i])] if card else str(int(codes[i]))
        lines.append(od.join(row + [pred, f"{probs[i]:.3f}"]))
        if validate:
            cm.report(pred, row[schema.class_attr_field.ordinal])
    artifacts.write_text_output(out_path, lines, role="m")
    if validate:
        cm.export(counters)
    return counters
