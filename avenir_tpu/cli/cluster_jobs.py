"""Cluster-pack job registrations (org.avenir.cluster.*).

Config-key namespaces follow the reference setup() methods:
kmc.* (cluster/KmeansCluster.java:104-127, including the reference's
``kmc.attr.odinals`` typo) and agg.* (cluster/AgglomerativeGraphical.java:39-46).
"""

from __future__ import annotations

import os
from typing import List

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from ..core.table import load_csv
from .jobs import register, _schema_path, _splitter


@register("org.avenir.cluster.KmeansCluster", "kmeansCluster",
          dist="sharded")
def kmeans_cluster(cfg: Config, in_path: str, out_path: str) -> Counters:
    """One Lloyd iteration over every active cluster group (one reference MR
    pass, cluster/KmeansCluster.java).  Keys: kmc.schema.file.path,
    kmc.attr.odinals, kmc.movement.threshold, kmc.cluster.file.path,
    kmc.num.iterations (extension: loop in-process instead of re-running the
    job; default 1 = reference behavior), nads.output.precision.

    Multi-process (dist=sharded): each process loads its OWN data shard;
    the engine's per-shard assignment sums are all-reduced before the
    centroid update (kmeans.KMeansEngine.iterate), so every process
    derives the identical global centroids from its local rows — the
    reference reducer's shuffle as a collective.  The cluster (centroid)
    file is replicated side input."""
    from ..cluster import kmeans as KM
    counters = Counters()
    schema = _schema_path(cfg, "kmc.schema.file.path")
    ordinals = cfg.get_int_list("kmc.attr.odinals",
                                cfg.get_int_list("kmc.attr.ordinals"))
    if not ordinals:
        raise ValueError("missing attribute ordinals (kmc.attr.odinals)")
    threshold = cfg.must_get_float("kmc.movement.threshold",
                                   "missing movement threshold")
    precision = cfg.get_int("nads.output.precision", 3)
    iters = cfg.get_int("kmc.num.iterations", 1)
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    lines = artifacts.read_text_input(cfg.must_get("kmc.cluster.file.path",
                                                   "missing cluster file"))
    engine = KM.KMeansEngine(schema, ordinals,
                             cfg.get("kmc.distance.metric", "euclidean"))
    groups = KM.parse_cluster_lines(lines, schema.num_columns, threshold,
                                    cfg.field_delim_out)
    groups, it = KM.run_kmeans(table, groups, engine,
                               max_iter=max(iters, 1), precision=precision)
    out_lines = KM.format_cluster_lines(groups, cfg.field_delim_out, precision)
    artifacts.write_text_output(out_path, out_lines)
    # iteration/active tallies describe the GLOBAL model every process
    # derived identically; emit once so the sharded counter SUM is exact
    import jax
    p0 = jax.process_index() == 0
    counters.increment("Clustering", "iterations", it if p0 else 0)
    for g in groups:
        counters.increment("Clustering", "activeGroups",
                           int(g.active) if p0 else 0)
    return counters


@register("org.avenir.cluster.AgglomerativeGraphical", "agglomerativeGraphical",
          dist="gather")
def agglomerative_graphical(cfg: Config, in_path: str, out_path: str
                            ) -> Counters:
    """Greedy edge-weighted agglomerative pass
    (cluster/AgglomerativeGraphical.java).  Keys:
    agg.min.av.edge.weight.threshold, agg.map.file.dir.path (distance-store
    lines; MapFile equivalent), agg.dist.scale (set when the store holds
    distances rather than similarities)."""
    from ..cluster import agglomerative as AG
    counters = Counters()
    threshold = cfg.must_get_float("agg.min.av.edge.weight.threshold",
                                   "missing min average edge weight")
    map_path = cfg.must_get("agg.map.file.dir.path",
                            "missing distance map file")
    ps = None
    if os.path.isdir(map_path) and os.path.exists(
            os.path.join(map_path, "index.json")):
        # persistent MapFile-equivalent store (io.diststore) built by the
        # entityDistanceStore job: seek-per-key, nothing preloaded
        from ..io.diststore import EntityDistanceStore as _PStore
        ps = _PStore(map_path)

        class _LazyStore:
            # memo: try_membership probes the same entities repeatedly, so
            # parse each distance line at most once
            _cache: dict = {}

            def read(self, key):
                hit = self._cache.get(key)
                if hit is None:
                    hit = dict(ps.read(key) or [])
                    self._cache[key] = hit
                return hit

        store = _LazyStore()
    else:
        store = AG.EntityDistanceStore.from_lines(
            artifacts.read_text_input(map_path), cfg.field_delim_out)
    dist_scale = cfg.get_float("agg.dist.scale")
    split = _splitter(cfg.field_delim_regex)
    entity_ids: List[str] = []
    for line in artifacts.read_text_input(in_path):
        line = line.strip()
        if line:
            entity_ids.append(split(line)[0])
    try:
        clusters = AG.agglomerative_cluster(entity_ids, store, threshold,
                                            dist_scale)
    finally:
        if ps is not None:
            ps.close()
    artifacts.write_text_output(
        out_path, [c.to_line(cfg.field_delim_out) for c in clusters])
    counters.increment("Clustering", "clusters", len(clusters))
    return counters


@register("org.avenir.util.EntityDistanceMapFileAccessor",
          "entityDistanceStore",
          dist="gather")
def entity_distance_store(cfg: Config, in_path: str, out_path: str
                          ) -> Counters:
    """Build the persistent random-access distance store from entity-distance
    lines (util/EntityDistanceMapFileAccessor.write :69-92: key = first
    field, value = the rest).  out_path becomes the store directory."""
    from ..io.diststore import EntityDistanceStore as _PStore
    counters = Counters()
    store = _PStore.write(artifacts.read_text_input(in_path), out_path,
                          cfg.field_delim_out)
    counters.set("DistanceStore", "entities", len(store.keys()))
    return counters
