"""Command-line runner: drop-in replacement for the reference's driver lines.

Reference invocation (resource/detr.sh:52, resource/knn.sh:53):

    hadoop jar avenir.jar org.avenir.tree.DecisionTreeBuilder \
        -Dconf.path=detr.properties <inPath> <outPath>

Here:

    python -m avenir_tpu.cli.run org.avenir.tree.DecisionTreeBuilder \
        -Dconf.path=detr.properties <inPath> <outPath>

Also accepts the Spark-style ``<jobAlias> <inPath> <outPath> <conf.conf>``
argument order used by resource/opt.sh.  Prints Hadoop-style counter dumps.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

from ..core.platform import force_platform

force_platform()  # AVENIR_TPU_PLATFORM=cpu escape hatch, before any backend init

from ..core.config import Config, load_config
from . import jobs
from . import explore_jobs  # noqa: F401  (registers explore-pack jobs)
from . import sequence_jobs  # noqa: F401  (registers sequence-pack jobs)
from . import optimize_jobs  # noqa: F401  (registers optimize-pack jobs)
from . import reinforce_jobs  # noqa: F401  (registers reinforce-pack jobs)
from . import cluster_jobs  # noqa: F401  (registers cluster-pack jobs)
from . import regress_jobs  # noqa: F401  (registers regress-pack jobs)
from . import discriminant_jobs  # noqa: F401  (registers discriminant-pack jobs)
from . import association_jobs  # noqa: F401  (registers association-pack jobs)
from . import text_jobs  # noqa: F401  (registers text-pack + rule jobs)
from . import partition_jobs  # noqa: F401  (registers split/partition jobs)
from . import nn_jobs  # noqa: F401  (registers neural-net jobs)
from . import serving_jobs  # noqa: F401  (registers online-serving jobs)
from . import monitor_jobs  # noqa: F401  (registers drift-monitoring jobs)
from . import control_jobs  # noqa: F401  (registers closed-loop control jobs)
from . import online_jobs  # noqa: F401  (registers online-learning jobs)


def file_sha(path: str, full: bool) -> str:
    """Streaming content sha; cheap head+tail+size form (``full=False``)
    for the big sharded/map inputs where a full read would double ingest
    cost.  The cheap form also hashes strided interior samples so
    genuinely distinct shards that agree in head, tail, and size
    (fixed-width records differing mid-file) are not refused as
    IDENTICAL (round-4 advisor); still O(1) reads in file size."""
    import hashlib
    h = hashlib.sha256()
    size = os.path.getsize(path)
    with open(path, "rb") as fh:
        if full:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        else:
            h.update(f"{size}:".encode())
            h.update(fh.read(1 << 16))
            if size > (1 << 16):
                for frac in (0.25, 0.5, 0.75):
                    fh.seek(int(size * frac))
                    h.update(fh.read(4096))
                fh.seek(-(1 << 16), os.SEEK_END)
                h.update(fh.read(1 << 16))
    return h.hexdigest()


def write_counters_json(counters, out_path: Optional[str]) -> Optional[str]:
    """The machine-readable half of the job's counter dump:
    ``<out>.counters.json`` (``Counters.to_json`` bytes, tmp-then-rename
    so a crash never leaves a torn file) NEXT TO the job output — a
    sibling, never inside it: output dirs are consumed as inputs by
    chained jobs and byte-pinned by the golden flows, so a metadata file
    inside one would leak into the next stage's record stream.  EVERY
    job gets it (the render() print and this file come from the same
    writer); returns the path written, or None when the job has no
    output path or the write failed (counter persistence must never fail
    a finished job)."""
    if not out_path:
        return None
    dest = f"{out_path.rstrip('/' + os.sep)}.counters.json"
    tmp = f"{dest}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            fh.write(counters.to_json())
        os.replace(tmp, dest)
    except OSError as exc:
        print(f"[counters] could not persist {dest!r}: {exc}",
              file=sys.stderr)
        try:
            os.unlink(tmp)   # never litter a torn .tmp next to the output
        except OSError:
            pass
        return None
    return dest


def emit_counters(counters, out_path: Optional[str],
                  persist: bool = True) -> None:
    """The ONE counter emitter: print the Hadoop-style dump AND persist
    counters.json next to the job output (previously only driftMonitor
    persisted its counters; now every job's dump is diffable with
    ``tools/tracetool.py counter-diff``).  ``persist=False`` keeps the
    print without the file — the non-owner shards of the file-transport
    smoke lane, whose shard-local counters racing one counters.json
    would make the persisted dump nondeterministic."""
    print(counters.render())
    if persist:
        write_counters_json(counters, out_path)


def _telemetry_setup(cfg, job_name: str, in_path: Optional[str]):
    """Install run-scoped telemetry from the ``telemetry.*`` config keys
    (TPU_NOTES §21); returns ``(tracer, metrics_server, registry)``,
    all None when telemetry is off (the default — spans no-op).

      telemetry.trace.dir      span tracing: per-process JSONL + Chrome
                               trace export into this directory
      telemetry.run.id         trace file run id (default under a
                               sharded run: derived from job+INPUT —
                               the one path every shard of a row-range
                               run shares — so all shards agree; set
                               explicitly to keep multiple runs of the
                               same input apart in one dir)
      telemetry.metrics.port   /metrics + /healthz endpoint port (0 =
                               ephemeral, printed to stderr)
      telemetry.metrics.host   endpoint bind address (default 127.0.0.1;
                               set 0.0.0.0 so a load balancer / probe on
                               another host can reach /healthz)
      telemetry.metrics.snapshot.s   background snapshot cadence
                               (JSONL flight recorder next to the
                               output; 0 = off — works without a port:
                               the registry runs endpoint-less)

    Env twins AVENIR_TPU_TRACE_EVENTS_DIR / AVENIR_TPU_METRICS_PORT /
    AVENIR_TPU_METRICS_HOST / AVENIR_TPU_RUN_ID serve launchers that
    cannot edit the conf."""
    # `or None` twice: an empty config value OR an empty env var both
    # mean 'unset' (a launcher exporting AVENIR_TPU_METRICS_PORT="" must
    # leave telemetry off, not abort the job on int(""))
    trace_dir = cfg.get("telemetry.trace.dir") or \
        os.environ.get("AVENIR_TPU_TRACE_EVENTS_DIR") or None
    port = cfg.get("telemetry.metrics.port") or \
        os.environ.get("AVENIR_TPU_METRICS_PORT") or None
    snap_s = cfg.get_float("telemetry.metrics.snapshot.s", 0.0)
    if not trace_dir and port is None and snap_s <= 0:
        return None, None, None
    from .. import telemetry
    from ..parallel.distributed import shard_spec
    spec = shard_spec()
    tracer = server = registry = None
    if trace_dir:
        run_id = cfg.get("telemetry.run.id") or \
            os.environ.get("AVENIR_TPU_RUN_ID")
        if not run_id:
            import hashlib
            short = job_name.split(".")[-1]
            if spec.active:
                # every shard must derive the IDENTICAL id, or the
                # merged timeline falls apart — hash the job + the
                # shared INPUT path (out dirs are per-shard in the
                # smoke lane; the input is the one thing a row-range
                # sharded run shares by contract)
                run_id = short + "-" + hashlib.sha256(
                    f"{job_name}|{in_path}".encode()).hexdigest()[:8]
            else:
                import time as _time
                import uuid as _uuid
                # pid+second is NOT unique (two main() calls in one
                # process within a second would truncate each other's
                # trace file) — a short random tail keeps runs apart
                run_id = f"{short}-{_time.strftime('%Y%m%d%H%M%S')}" \
                         f"-{os.getpid()}-{_uuid.uuid4().hex[:6]}"
        tracer = telemetry.install_tracer(telemetry.Tracer(
            trace_dir, run_id=run_id, process_index=spec.index))
    if port is not None or snap_s > 0:
        # snapshot.s without a port still gets a registry: the JSONL
        # flight recorder must not silently require the endpoint too
        try:
            registry = telemetry.MetricsRegistry()
            telemetry.set_default_registry(registry)
            if port is not None:
                host = cfg.get("telemetry.metrics.host") or \
                    os.environ.get("AVENIR_TPU_METRICS_HOST") or \
                    "127.0.0.1"
                bind_port = int(port)
                if bind_port != 0 and spec.active:
                    # per-shard offset: a fixed port under a single-host
                    # multi-process run would EADDRINUSE every shard but
                    # one, abort the losers, and leave the survivor
                    # wedged at its first collective — the exact hang
                    # the stall detector exists to prevent.  Shard i
                    # scrapes at port+i, deterministically.
                    bind_port += spec.index
                server = telemetry.MetricsServer(
                    registry, port=bind_port, host=host).start()
        except Exception:
            # a failed endpoint start (port in use, bad port string) must
            # not leak the process-global tracer/registry installed above
            # into later in-process runs
            telemetry.set_default_registry(None)
            if tracer is not None:
                telemetry.uninstall_tracer()
                tracer.close()
            raise
        if server is not None:
            print(f"[telemetry] metrics endpoint "
                  f"http://{server.host}:{server.port}/metrics "
                  f"(+ /healthz)", file=sys.stderr)
    return tracer, server, registry


def parse_args(argv: List[str]):
    job_name: Optional[str] = None
    conf_path: Optional[str] = None
    overrides = {}
    positional: List[str] = []
    for a in argv:
        if a.startswith("-Dconf.path="):
            conf_path = a.split("=", 1)[1]
        elif a == "--resume":
            # restart a checkpointed streaming job from its last intact
            # step (sugar for -Ddtb.streaming.resume=true)
            overrides["dtb.streaming.resume"] = "true"
        elif a.startswith("-D"):
            k, _, v = a[2:].partition("=")
            overrides[k] = v
        elif job_name is None:
            job_name = a
        else:
            positional.append(a)
    # spark style: <in> <out> <file.conf> as last positional
    if conf_path is None and positional and positional[-1].endswith(".conf"):
        conf_path = positional.pop()
    return job_name, conf_path, overrides, positional


def _enter_distributed_mode(mode: str) -> None:
    """-Ddistributed.mode= / AVENIR_TPU_DISTRIBUTED=1 entry: join the
    multi-process run (env-driven; mode 'auto' additionally lets TPU pod
    runtimes self-discover), build the hybrid (hosts, data) mesh, and
    install it as the process-wide runtime context so every job (they all
    resolve MeshContext through ``runtime_context()``) runs sharded over
    it.  Single-process with the flag set still gets the 1 x n hybrid mesh
    — same axis names, so shardings are portable."""
    from ..parallel import distributed
    from ..parallel.mesh import MeshContext, set_runtime_context
    # 'auto' and the env flag both attempt pod self-discovery (the env
    # var's documented contract in parallel/distributed.py — downgrading
    # it to a local no-join would be the silent shard-local failure mode
    # that module refuses).  -Ddistributed.mode=1 on a single host joins
    # only via an explicit JAX_* triple, else runs the 1 x n hybrid mesh.
    distributed.initialize(auto=(mode in ("auto", "env")))
    set_runtime_context(MeshContext(distributed.make_hybrid_mesh()))


def _apply_dist_mode(fn, job_name: str, in_path: Optional[str], cfg=None):
    """Enforce the job's multi-process class (parallel/distributed.py
    docstring).  Single-process: identity.  Under ``process_count() > 1``:
    'sharded' and 'map' jobs run on their local shard unchanged; 'gather'
    jobs get their input allgathered into a process-local spool DIR so the
    host-side computation sees the FULL input on every process (the
    reference's shuffle global-ness); anything else is refused loudly —
    silently emitting shard-local results is the worst failure mode.

    Returns ``(effective_in_path, cleanup_dir_or_None)`` — the caller
    removes the spool dir after the job so chained pipelines don't
    accumulate full input copies in tmp.

    The spool is a DIRECTORY that preserves each input file's basename
    (suffixed ``.p<process>`` for cross-process uniqueness): several
    gather jobs key behavior on basenames inside the input dir — the
    train/test ``tr`` prefix of the similarity jobs, the ``condProb``
    prefix of featureCondProbJoiner — and flattening to one spool file
    would silently break them.  Every process joins the collectives even
    with no local input (``in_path=None`` contributes zero files); if
    processes disagree on WHETHER an input path was given at all, that is
    an argv mismatch and raises on every process rather than deadlocking
    half the pod inside a collective.

    Shared-filesystem deployments (identical argv on every host — the
    standard Hadoop-style launch) are detected FIRST via a digest
    exchange, and the response depends on the mode:

      * gather — the input already IS the global dataset on every
        process: use it as-is (no spool, no bulk gather, no P-fold
        double-count of the union semantics);
      * sharded / map — every process would treat the FULL file as its
        shard and the reductions/part files would silently P-fold-inflate
        the results, so this RAISES with instructions to split the input
        (AVENIR_TPU_ALLOW_IDENTICAL_SHARDS=1 overrides, for the corner
        case of genuinely identical distinct shards).

    Only genuinely differing gather shards pay the content gather, which
    ships whole shards through ``allgather_object`` and therefore assumes
    host-side-job-sized inputs (the per-process peak is ~process_count x
    the largest shard); file contents are hashed streaming in the digest
    phase and read again only when actually gathered."""
    from ..parallel.distributed import is_multiprocess
    if not is_multiprocess():
        return in_path, None
    mode = jobs.dist_mode(fn)
    if mode not in ("sharded", "gather", "map", "partition"):
        raise RuntimeError(
            f"job {job_name} is not multi-process safe (dist mode "
            f"{mode!r}): running it under jax.process_count() > 1 would "
            f"emit shard-local results; run it single-process")

    import glob
    import hashlib
    import tempfile
    import jax
    from ..parallel.distributed import allgather_object

    def input_paths():
        if in_path is None:
            return []
        if os.path.isdir(in_path):
            return sorted(p for p in glob.glob(os.path.join(in_path, "*"))
                          if os.path.isfile(p))
        return [in_path]

    paths = input_paths()
    # partition jobs need the same GLOBAL input view as gather (they slice
    # their WORK, not their input)
    full = mode in ("gather", "partition")
    digest = hashlib.sha256(repr(
        [(os.path.basename(p), file_sha(p, full)) for p in paths]
    ).encode()).hexdigest()
    meta = allgather_object((in_path is not None, digest))
    flags = [has for has, _ in meta]
    if len(set(flags)) > 1:
        raise RuntimeError(
            f"job {job_name}: processes disagree on whether an input "
            f"path was given ({flags}); fix the per-process argv")
    if in_path is None:
        return None, None
    identical = len({d for _, d in meta}) == 1

    if mode in ("sharded", "map"):
        row_range = cfg is not None and jobs.shards_by_row_range(fn, cfg)
        if not identical and row_range:
            # the inverse of the refusal below: row-range sharding assumes
            # ONE shared file, so under the per-process-shard-file layout
            # each process would parse only rows [lo_i, hi_i) of its OWN
            # file and (P-1)/P of every file's rows would silently never
            # train
            raise RuntimeError(
                f"job {job_name}: dtb.streaming.shard is active but the "
                f"{len(meta)} processes were given DISTINCT inputs — the "
                f"row-range split assumes every process reads the SAME "
                f"file and would silently drop rows from each per-process "
                f"shard file.  Give every process the same input path, or "
                f"set dtb.streaming.shard=off to train per-process shards")
        if identical and not os.environ.get(
                "AVENIR_TPU_ALLOW_IDENTICAL_SHARDS") and not row_range:
            # row-range-sharded jobs (dtb.streaming.shard) are the
            # sanctioned exception: one shared file, each process parses
            # only its own source-row range (TPU_NOTES §20)
            raise RuntimeError(
                f"job {job_name} (dist mode {mode!r}): all "
                f"{len(meta)} processes were given IDENTICAL input — each "
                f"would treat the full file as its shard and the results "
                f"would be silently {len(meta)}x inflated.  Give each "
                f"process its own input shard (or set "
                f"AVENIR_TPU_ALLOW_IDENTICAL_SHARDS=1 if the shards are "
                f"genuinely identical by coincidence)")
        return in_path, None

    # gather / partition: global input view on every process
    if identical:
        # shared-filesystem launch: the input already IS the global dataset
        if jax.process_index() == 0:
            print(f"[dist] {job_name}: input identical on all "
                  f"{len(meta)} processes; using it as-is (no gather)",
                  file=sys.stderr)
        return in_path, None
    # read as BYTES (a non-UTF-8 byte must not raise on one process while
    # its peers are already blocked in the collective), and exchange a
    # per-process ok/error through the gather so every process fails
    # together instead of hanging the pod (round-4 advisor)
    err = None
    local = []
    try:
        for p in paths:
            with open(p, "rb") as fh:
                local.append((os.path.basename(p), fh.read()))
    except Exception as exc:  # incl. MemoryError on a huge shard: any
        # pre-collective escape would leave the peers blocked forever
        err = f"process {jax.process_index()}: {type(exc).__name__}: {exc}"
        local = []
    gathered = allgather_object((err, local))
    errors = [e for e, _ in gathered if e]
    if errors:
        raise RuntimeError(
            f"job {job_name}: input gather failed on "
            f"{len(errors)} process(es): " + "; ".join(errors))
    spool = tempfile.mkdtemp(prefix="avenir_dist_gather_")
    for proc, (_, files) in enumerate(gathered):
        for base, data in files:
            with open(os.path.join(spool, f"{base}.p{proc}"), "wb") as fh:
                fh.write(data)
    if jax.process_index() == 0:
        print(f"[dist] {job_name}: gathered "
              f"{sum(len(f) for _, f in gathered)} input file(s) from "
              f"{len(gathered)} processes", file=sys.stderr)
    return spool, spool


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    job_name, conf_path, overrides, positional = parse_args(argv)
    if job_name is None:
        print("usage: python -m avenir_tpu.cli.run <JobClassOrAlias> "
              "-Dconf.path=<conf> [<inPath>] <outPath>", file=sys.stderr)
        return 2
    if "platform" in overrides:
        force_platform(overrides["platform"])
    dist_mode = overrides.get("distributed.mode") or (
        "env" if os.environ.get("AVENIR_TPU_DISTRIBUTED") == "1" else "")
    entered_distributed = False
    if dist_mode and dist_mode.lower() not in ("0", "false", "off"):
        _enter_distributed_mode(dist_mode)
        entered_distributed = True
    fn = jobs.resolve(job_name)
    cfg = load_config(conf_path, app=job_name.split(".")[-1][0].lower() +
                      job_name.split(".")[-1][1:]) if conf_path else Config()
    cfg.update(overrides)
    if len(positional) >= 2:
        in_path, out_path = positional[0], positional[1]
    elif len(positional) == 1:
        in_path, out_path = None, positional[0]
    else:
        in_path = out_path = None
    spool_dir = None
    tracer = metrics_server = registry = None
    backend_knob = cfg.get("kernel.backend")
    if backend_knob:
        # kernel.backend=auto|xla|pallas (TPU_NOTES §24): process-level
        # selection for the hot-loop pallas twins; the env twin
        # AVENIR_TPU_KERNEL_BACKEND is read by the dispatch layer itself.
        # Installed before the job, cleared in finally so one in-process
        # run cannot leak its selection into the next.
        from ..ops.pallas.dispatch import set_kernel_backend
        set_kernel_backend(backend_knob)
    try:
        # inside the try so a dist-mode refusal still runs the context
        # cleanup below (no hybrid-mesh leak into later in-process runs)
        orig_in_path = in_path   # pre-spool: the run-id anchor must be
        in_path, spool_dir = _apply_dist_mode(fn, job_name, in_path, cfg)
        # job-level step accounting into the counters channel (the rebuild's
        # replacement for the Hadoop UI's job timing; SURVEY §5), plus an
        # optional XLA profiler capture dir and the measured link-traffic
        # ledger (H2D/D2H bytes + dispatches at the instrumented hot paths)
        from ..utils.tracing import StepTimer, trace, transfer_ledger
        timer = StepTimer()
        # run-scoped telemetry (span tracer, /metrics + /healthz endpoint)
        # from the telemetry.* keys — off by default, spans no-op
        # the argv-level path, not a per-process gather spool dir
        tracer, metrics_server, registry = _telemetry_setup(
            cfg, job_name, orig_in_path)
        with transfer_ledger() as ledger:
            if registry is not None:
                # live sources: /metrics mid-job shows the ledger and the
                # step timer moving, not an end-of-job summary
                registry.attach_ledger(ledger)
                registry.attach_timer(timer)
                snap_s = cfg.get_float("telemetry.metrics.snapshot.s", 0.0)
                if snap_s > 0:
                    # sibling of the output, like counters.json: never
                    # write metadata INSIDE a dir later jobs consume.
                    # Owner-only under a shard spec, also like
                    # counters.json: shards sharing one out path must
                    # not truncate and interleave one flight recorder
                    from ..parallel.distributed import shard_spec as _ss
                    _spec = _ss()
                    own = not _spec.active or _spec.index == 0
                    registry.start_snapshots(
                        snap_s,
                        snapshot_path=(
                            f"{out_path.rstrip('/' + os.sep)}"
                            f".metrics.jsonl"
                            if out_path and own else None))
            with trace(cfg.get("profile.trace.dir") or
                       os.environ.get("AVENIR_TPU_TRACE_DIR")):
                with timer.step("job"):
                    counters = fn(cfg, in_path, out_path)
        if counters is not None:
            # ledger export BEFORE the all-reduce: each process moved its
            # own bytes, so the reduced dump shows true cluster totals
            ledger.export(counters)
            # Hadoop counters are cluster-global: under multi-host the per
            # -process host-side tallies are all-reduced, and only process 0
            # renders (matching the reference driver's single counter dump).
            # gather-mode jobs are the exception: every process computed the
            # identical full result, so their counters are ALREADY global —
            # summing would inflate each one by the process count.
            # Profiling times are exported AFTER the reduce — per-process
            # wall clock must not be summed across the pod.
            from ..parallel.distributed import all_reduce_counters, \
                shard_spec
            import jax
            if jobs.dist_mode(fn) != "gather":
                counters = all_reduce_counters(counters)
            timer.export(counters)
            if registry is not None:
                registry.attach_counters(counters)
            spec = shard_spec()
            if jax.process_index() == 0:
                emit_counters(counters, out_path,
                              persist=not spec.active or spec.index == 0)
    finally:
        if backend_knob:
            from ..ops.pallas.dispatch import set_kernel_backend
            set_kernel_backend(None)
        if registry is not None:
            registry.stop_snapshots()
        if metrics_server is not None:
            metrics_server.stop()
        if registry is not None:
            from ..telemetry import set_default_registry
            set_default_registry(None)
        if tracer is not None:
            from ..telemetry import uninstall_tracer
            uninstall_tracer()
            try:  # flush + Chrome export; telemetry must never fail a job
                tracer.close()
            except Exception as exc:
                print(f"[telemetry] trace close failed: {exc}",
                      file=sys.stderr)
        if spool_dir is not None:
            # gather spools hold a full copy of the global input; chained
            # pipelines must not accumulate them in tmp
            import shutil
            shutil.rmtree(spool_dir, ignore_errors=True)
        if entered_distributed:
            # don't leak the hybrid context into later in-process runs
            from ..parallel.mesh import set_runtime_context
            set_runtime_context(None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
