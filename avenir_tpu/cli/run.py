"""Command-line runner: drop-in replacement for the reference's driver lines.

Reference invocation (resource/detr.sh:52, resource/knn.sh:53):

    hadoop jar avenir.jar org.avenir.tree.DecisionTreeBuilder \
        -Dconf.path=detr.properties <inPath> <outPath>

Here:

    python -m avenir_tpu.cli.run org.avenir.tree.DecisionTreeBuilder \
        -Dconf.path=detr.properties <inPath> <outPath>

Also accepts the Spark-style ``<jobAlias> <inPath> <outPath> <conf.conf>``
argument order used by resource/opt.sh.  Prints Hadoop-style counter dumps.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from ..core.platform import force_platform

force_platform()  # AVENIR_TPU_PLATFORM=cpu escape hatch, before any backend init

from ..core.config import Config, load_config
from . import jobs
from . import explore_jobs  # noqa: F401  (registers explore-pack jobs)
from . import sequence_jobs  # noqa: F401  (registers sequence-pack jobs)
from . import optimize_jobs  # noqa: F401  (registers optimize-pack jobs)
from . import reinforce_jobs  # noqa: F401  (registers reinforce-pack jobs)
from . import cluster_jobs  # noqa: F401  (registers cluster-pack jobs)
from . import regress_jobs  # noqa: F401  (registers regress-pack jobs)
from . import discriminant_jobs  # noqa: F401  (registers discriminant-pack jobs)
from . import association_jobs  # noqa: F401  (registers association-pack jobs)
from . import text_jobs  # noqa: F401  (registers text-pack + rule jobs)
from . import partition_jobs  # noqa: F401  (registers split/partition jobs)
from . import nn_jobs  # noqa: F401  (registers neural-net jobs)


def parse_args(argv: List[str]):
    job_name: Optional[str] = None
    conf_path: Optional[str] = None
    overrides = {}
    positional: List[str] = []
    for a in argv:
        if a.startswith("-Dconf.path="):
            conf_path = a.split("=", 1)[1]
        elif a.startswith("-D"):
            k, _, v = a[2:].partition("=")
            overrides[k] = v
        elif job_name is None:
            job_name = a
        else:
            positional.append(a)
    # spark style: <in> <out> <file.conf> as last positional
    if conf_path is None and positional and positional[-1].endswith(".conf"):
        conf_path = positional.pop()
    return job_name, conf_path, overrides, positional


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    job_name, conf_path, overrides, positional = parse_args(argv)
    if job_name is None:
        print("usage: python -m avenir_tpu.cli.run <JobClassOrAlias> "
              "-Dconf.path=<conf> [<inPath>] <outPath>", file=sys.stderr)
        return 2
    if "platform" in overrides:
        force_platform(overrides["platform"])
    fn = jobs.resolve(job_name)
    cfg = load_config(conf_path, app=job_name.split(".")[-1][0].lower() +
                      job_name.split(".")[-1][1:]) if conf_path else Config()
    cfg.update(overrides)
    if len(positional) >= 2:
        in_path, out_path = positional[0], positional[1]
    elif len(positional) == 1:
        in_path, out_path = None, positional[0]
    else:
        in_path = out_path = None
    counters = fn(cfg, in_path, out_path)
    if counters is not None:
        print(counters.render())
    return 0


if __name__ == "__main__":
    sys.exit(main())
