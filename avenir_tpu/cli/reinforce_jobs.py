"""Reinforce-pack jobs: the generic MultiArmBandit batch job + the named
Hadoop bandit jobs as algorithm presets.

Parity targets: spark/.../reinforce/MultiArmBandit.scala:61-146 (generic,
model state round-tripped through files) and the Hadoop batch jobs
GreedyRandomBandit / SoftMaxBandit / AuerDeterministic /
RandomFirstGreedyBandit (reinforce/*.java), which are the same flow with a
fixed algorithm.

Config keys (mab.* namespace):
  mab.action.list           comma list of action ids (mandatory)
  mab.algorithm             factory name (default randomGreedy)
  mab.model.state.file.in   optional prior state file/dir
  mab.model.state.file.out  state output dir (default <out>/state)
  mab.decision.batch.size, mab.current.decision.round, mab.random.seed,
  plus algorithm knobs passed through (mab.random.selection.prob,
  mab.temp.constant, ...).
Input lines: group,action,reward  (reward feedback; may be empty dir).
Output: decisions 'group,action[,action...]' + saved state.
"""

from __future__ import annotations

import os
from typing import Dict

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from .jobs import register

_PASSTHROUGH_KEYS = [
    "min.trial", "decision.batch.size", "reward.scale",
    "current.decision.round", "random.seed", "random.selection.prob",
    "prob.reduction.algorithm", "prob.reduction.constant",
    "auer.greedy.constant",
    "confidence.factor", "temp.constant", "learning.rate", "alpha",
    "preference.step", "reference.reward.step", "initial.reference.reward",
    "distr.constant",
]


def _bandit_config(cfg: Config) -> Dict:
    out: Dict = {}
    for k in _PASSTHROUGH_KEYS:
        v = cfg.get(f"mab.{k}")
        if v is not None:
            out[k] = v
    if "random.seed" in out:
        out["random.seed"] = int(out["random.seed"])
    for ik in ("decision.batch.size", "min.trial", "current.decision.round",
               "reward.scale"):
        if ik in out:
            out[ik] = int(out[ik])
    return out


def _run_bandit(cfg: Config, in_path: str, out_path: str,
                algorithm: str) -> Counters:
    from ..reinforce.batch import GroupedBandits
    counters = Counters()
    actions = cfg.must_get_list("mab.action.list")
    gb = GroupedBandits(algorithm, actions, _bandit_config(cfg))
    delim = cfg.field_delim_out
    state_in = cfg.get("mab.model.state.file.in")
    if state_in and os.path.exists(state_in):
        gb.load_state(artifacts.read_text_input(state_in), delim)
    if in_path and os.path.exists(in_path):
        rewards = artifacts.read_text_input(in_path)
        gb.apply_rewards(rewards, delim)
        counters.increment("Bandit", "Rewards", len(rewards))
    if not gb.learners:
        groups = cfg.get_list("mab.group.list") or ["default"]
        for g in groups:
            gb.learner(g)
    decisions = gb.next_actions(delim=delim)
    artifacts.write_text_output(out_path, decisions)
    state_out = cfg.get("mab.model.state.file.out",
                        os.path.join(out_path, "state"))
    artifacts.write_text_output(state_out, gb.save_state(delim))
    counters.increment("Bandit", "Groups", len(gb.learners))
    return counters


@register("org.avenir.spark.reinforce.MultiArmBandit", "multiArmBandit",
          dist="gather")
def multi_arm_bandit(cfg: Config, in_path: str, out_path: str) -> Counters:
    return _run_bandit(cfg, in_path, out_path,
                       cfg.get("mab.algorithm", "randomGreedy"))


@register("org.avenir.reinforce.GreedyRandomBandit", "greedyRandomBandit",
          dist="gather")
def greedy_random_bandit(cfg: Config, in_path: str, out_path: str) -> Counters:
    """epsilon-greedy batch job (reinforce/GreedyRandomBandit.java:150-205)."""
    return _run_bandit(cfg, in_path, out_path, "randomGreedy")


@register("org.avenir.reinforce.SoftMaxBandit", "softMaxBandit",
          dist="gather")
def soft_max_bandit(cfg: Config, in_path: str, out_path: str) -> Counters:
    return _run_bandit(cfg, in_path, out_path, "softMax")


@register("org.avenir.reinforce.AuerDeterministic", "auerDeterministic",
          dist="gather")
def auer_deterministic(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Auer's deterministic UCB1 variant."""
    return _run_bandit(cfg, in_path, out_path, "ucb1")


@register("org.avenir.reinforce.RandomFirstGreedyBandit",
          "randomFirstGreedyBandit",
          dist="gather")
def random_first_greedy_bandit(cfg: Config, in_path: str,
                               out_path: str) -> Counters:
    """Random exploration first, then greedy: randomGreedy with linear
    epsilon decay."""
    cfg.set("mab.prob.reduction.algorithm",
            cfg.get("mab.prob.reduction.algorithm", "linear"))
    return _run_bandit(cfg, in_path, out_path, "randomGreedy")
