"""Online-serving jobs (org.avenir.serving.*).

``predictionService`` replays a file of request records through the
micro-batched serving loop — the offline harness for the online subsystem
(every layer the live loop uses: registry load, warm bucketed predictors,
coalescing policy, optional RESP wire transport).  Config keys
(reference-style, ``ps.`` namespace):

  ps.model.registry.dir     registry base directory (required)
  ps.model.name             model name in the registry (required)
  ps.model.version          pin a version (default: newest intact)
  ps.feature.schema.file.path  override the artifact's embedded schema
  ps.batch.max.size         micro-batch close size (default 64)
  ps.batch.max.wait.ms      micro-batch window (default 2.0)
  ps.batching               continuous | drain (default continuous)
  ps.slo.p99.ms             p99 latency budget; >0 enables the adaptive
                            coalescing window (default 0 = fixed)
  ps.queue.max.depth        admission threshold; submits past it answer
                            'busy' (default 0 = unbounded)
  ps.models                 comma list of resident models for the
                            multi-model router (ISSUE 18), each
                            ``name`` (follow the registry's serving
                            version) or ``name:version`` (pinned).
                            Every fleet worker then runs a ModelRouter
                            over the whole set; requests route by the
                            optional wire field ``m=<name[:version]>``
                            and requests without one serve the default
                            model (ps.model.name, else the first spec)
                            byte for byte.  Requires ps.transport=resp.
  ps.model.<name>.queue.max.depth
                            per-model admission depth for resident
                            <name> (tenant isolation: a noisy model is
                            answered 'busy' at ITS depth while quiet
                            residents keep their own budget; default =
                            ps.queue.max.depth)
  ps.canary.<name>.version  canary this version of resident <name>: a
                            deterministic per-request-id split routes
                            ps.canary.<name>.percent % (default 10) of
                            the model's traffic to it
  ps.shadow.<name>.version  shadow this version behind resident <name>:
                            it scores every request, replies come only
                            from the champion, divergence is counted
  ps.client.model           stamp every replayed request with this
                            ``m=<name[:version]>`` routing field (the
                            producer-side knob; default: no field)
  ps.quantized              serve the int8-quantized forest sidecar
                            (budget-pinned at publish; a version without
                            an intact sidecar warns and serves float —
                            default false)
  ps.workers                fleet size; >1 serves through a ServingFleet
                            of workers draining one RESP queue (default 1;
                            requires ps.transport=resp)
  ps.broker.shards          RESP broker shard count; >1 starts M embedded
                            RespServers and every client rides the
                            consistent-hash ShardedRespClient ring
                            (default 1; requires ps.transport=resp)
  ps.broker.durable         broker queue durability: off | commit |
                            fsync (env twin AVENIR_TPU_BROKER_DURABLE;
                            default off = today's in-memory bytes).
                            commit/fsync give every embedded shard a
                            write-ahead journal (under a job temp dir)
                            replayed on restart; fsync also forces the
                            OS flush per batch
  ps.broker.lease.timeout.s worker pops become visibility-timeout
                            leases with this expiry, acked by the
                            batched reply push; an expired lease
                            re-enqueues (at-least-once + broker reply
                            dedup = exactly-once effect).  Default 30
                            when ps.broker.durable != off, else 0 =
                            classic destructive pops
  ps.request.ttl.ms         stamp every request with an absolute
                            deadline this far in the future;
                            past-deadline requests answer '<id>,late'
                            before device dispatch (default 0 = none)
  ps.host.label             multi-host identity on metric series and
                            stats (default: this hostname)
  ps.autoscale              run the fleet under the SLO-driven
                            FleetAutoscaler (default false; implies the
                            fleet path, requires ps.transport=resp)
  ps.autoscale.min.workers / ps.autoscale.max.workers
                            active-worker bounds (default 1 / 4)
  ps.autoscale.interval.ms  sensor tick period (default 250)
  ps.bucket.sizes           jit shape buckets (default 1,8,64,512)
  ps.warm.start             pre-compile all buckets (default true)
  ps.latency.window         latency sample window (default 8192)
  ps.transport              inprocess | resp (default inprocess)
  ps.trace.sample           request-trace head sampling: trace every
                            Nth request end to end (flow events +
                            component histograms with exemplars, ISSUE
                            15; env twin AVENIR_TPU_TRACE_SAMPLE;
                            default 0 = off — zero cost beyond one
                            global read).  Sets the PROCESS sampling
                            rate for the job's lifetime, like the env
                            twin.
  ps.wire.native            auto | on | off (default auto): the native
                            serving data plane — one C pass per drained
                            batch for message parse/feature assembly and
                            reply RESP encode.  ``auto`` uses it when
                            the toolchain can build the codec and falls
                            back to pure python otherwise; ``off`` pins
                            the pure-python path (the differential
                            baseline).  Env twin AVENIR_TPU_NO_NATIVE=1
                            disables the build outright.
  redis.request.queue / redis.prediction.queue   resp-queue names

The input file holds one record per line (same layout the model's schema
describes); the output is one ``<requestId><delim><predictedClass>`` line
per request, requestId = 0-based input line number.  Latency percentiles
and throughput land in the counter dump (Serving group).
"""

from __future__ import annotations

from typing import List

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from .jobs import register, _schema_path, _splitter


@register("org.avenir.serving.PredictionService", "predictionService",
          dist="refuse")
def prediction_service(cfg: Config, in_path: str, out_path: str) -> Counters:
    from ..serving.registry import ModelRegistry
    from ..serving.predictor import DEFAULT_BUCKETS
    from ..serving.service import (BatchPolicy, PredictionService,
                                   RespPredictionLoop)
    from ..utils.tracing import StepTimer
    counters = Counters()
    # an EXPLICIT ps.trace.sample always wins — including 0, which must
    # be able to switch sampling off over an exported
    # AVENIR_TPU_TRACE_SAMPLE env twin (the untraced-baseline replay)
    if "ps.trace.sample" in cfg:
        from ..telemetry import reqtrace
        reqtrace.set_sample_rate(cfg.get_int("ps.trace.sample", 0))
    wire_native = cfg.get("ps.wire.native", "auto")
    if "ps.wire.native" in cfg:
        # explicit knob also sets the PROCESS default, so helper
        # clients built outside the service (the feeder below) follow
        from ..io import native_wire
        native_wire.set_mode(wire_native)
    registry = ModelRegistry(cfg.must_get("ps.model.registry.dir"))
    schema = _schema_path(cfg, "ps.feature.schema.file.path") \
        if "ps.feature.schema.file.path" in cfg else None
    policy = BatchPolicy(
        max_batch=cfg.get_int("ps.batch.max.size", 64),
        max_wait_ms=cfg.get_float("ps.batch.max.wait.ms", 2.0),
        batching=cfg.get("ps.batching", "continuous"),
        slo_p99_ms=cfg.get_float("ps.slo.p99.ms", 0.0),
        max_queue_depth=cfg.get_int("ps.queue.max.depth", 0))
    n_workers = cfg.get_int("ps.workers", 1)
    timer = StepTimer(keep_samples=cfg.get_int("ps.latency.window", 8192))
    # multi-model residency: ps.models lists name[:version] specs
    models_spec = [s.strip() for s in
                   (cfg.get("ps.models") or "").split(",") if s.strip()]
    if models_spec:
        from ..serving.router import parse_model_spec
        model_names = [parse_model_spec(s)[0] for s in models_spec]
        name = cfg.get("ps.model.name") or model_names[0]
        model_depths = {
            m: cfg.get_int(f"ps.model.{m}.queue.max.depth",
                           policy.max_queue_depth)
            for m in model_names
            if f"ps.model.{m}.queue.max.depth" in cfg}
    else:
        model_names, model_depths = [], {}
        name = cfg.must_get("ps.model.name")
    buckets = tuple(cfg.get_int_list("ps.bucket.sizes",
                                     list(DEFAULT_BUCKETS)))
    warm = cfg.get_boolean("ps.warm.start", True)
    version = cfg.get_int("ps.model.version", 0)
    quantized = cfg.get_boolean("ps.quantized", False)
    # tokenize with the INPUT delimiter (field.delim.regex, like every
    # other job); the service/wire delimiter is field.delim.out
    split = _splitter(cfg.field_delim_regex)
    rows = [split(line) for line in artifacts.read_text_input(in_path)]
    od = cfg.field_delim_out
    transport = cfg.get("ps.transport", "inprocess")
    n_shards = cfg.get_int("ps.broker.shards", 1)
    autoscale = cfg.get_boolean("ps.autoscale", False)
    if n_workers > 1 and transport != "resp":
        raise ValueError("ps.workers > 1 requires ps.transport=resp "
                         "(the fleet drains a RESP request queue)")
    if models_spec and transport != "resp":
        raise ValueError("ps.models requires ps.transport=resp (the "
                         "model router serves through the fleet)")
    if models_spec and version:
        raise ValueError("ps.models and ps.model.version are exclusive "
                         "— pin per model with name:version specs")
    if (n_shards > 1 or autoscale) and transport != "resp":
        raise ValueError("ps.broker.shards > 1 / ps.autoscale require "
                         "ps.transport=resp (both live on the wire tier)")
    if n_shards < 1:
        raise ValueError(f"ps.broker.shards must be >= 1, got {n_shards}")
    from ..io.respq import resolve_durable
    durable = resolve_durable(cfg.get("ps.broker.durable"))
    lease_s = cfg.get_float("ps.broker.lease.timeout.s",
                            30.0 if durable != "off" else 0.0)
    ttl_ms = cfg.get_float("ps.request.ttl.ms", 0.0)
    if (durable != "off" or lease_s > 0 or ttl_ms > 0) \
            and transport != "resp":
        raise ValueError("ps.broker.durable / ps.broker.lease.timeout.s"
                         " / ps.request.ttl.ms require ps.transport=resp"
                         " (all three live on the wire tier)")

    def pinned_factory():
        # pinned serving: build the predictor for that exact version
        # (hot-swap refresh is deliberately unavailable — a pin is a pin)
        from ..serving.predictor import make_predictor
        loaded = registry.load(name, version, schema=schema)
        return make_predictor(loaded, schema=schema, buckets=buckets,
                              delim=cfg.field_delim_out,
                              quantized=quantized)

    if n_workers > 1 or autoscale or n_shards > 1 or models_spec:
        # the fleet path also carries a 1-worker fleet over a sharded
        # ring (the RespPredictionLoop below is single-endpoint only)
        import os
        import shutil
        import tempfile
        from ..io.respq import RespServer, dedup_replies, make_queue_client
        from ..serving.autoscaler import AutoscalePolicy, FleetAutoscaler
        from ..serving.fleet import ServingFleet
        # the broker tier: M shard servers (M=1 keeps the plain client
        # underneath make_queue_client); started INSIDE the try so a
        # bind failure on shard k doesn't leak the k-1 already running
        servers: List[RespServer] = []
        fleet = feeder = scaler = sensor = journal_root = None
        try:
            if durable != "off":
                journal_root = tempfile.mkdtemp(
                    prefix="avenir-broker-journal-")
            for k in range(n_shards):
                jdir = os.path.join(journal_root, f"shard{k}") \
                    if journal_root else None
                servers.append(RespServer(durable=durable,
                                          journal_dir=jdir,
                                          counters=counters).start())
            req_q = cfg.get("redis.request.queue", "requestQueue")
            pred_q = cfg.get("redis.prediction.queue", "predictionQueue")
            wire_cfg = {"redis.server.endpoints":
                        [f"127.0.0.1:{s.port}" for s in servers],
                        "redis.request.queue": req_q,
                        "redis.prediction.queue": pred_q,
                        "redis.lease.timeout.s": lease_s}
            start_workers = n_workers
            if autoscale:
                # like fleet_host --autoscale MIN:MAX: the fleet starts
                # at the configured floor (the tick-level floor would
                # bring it up anyway, one worker per interval later)
                start_workers = max(
                    n_workers, cfg.get_int("ps.autoscale.min.workers", 1))
            fleet = ServingFleet(
                registry=registry if models_spec
                else (None if version else registry),
                model_name=name if models_spec
                else (None if version else name),
                predictor_factory=(pinned_factory
                                   if version and not models_spec
                                   else None),
                schema=schema, buckets=buckets, policy=policy,
                n_workers=start_workers, config=wire_cfg, warm=warm,
                delim=od, quantized=quantized,
                host_label=cfg.get("ps.host.label"),
                latency_window=cfg.get_int("ps.latency.window", 8192),
                wire_native=wire_native,
                models=models_spec or None,
                model_depths=model_depths or None)
            fleet.start()
            # deployment policies as config (multi-model fleets only)
            for mname in model_names:
                cv = cfg.get_int(f"ps.canary.{mname}.version", 0)
                if cv:
                    fleet.install_canary(
                        mname, version=cv,
                        percent=cfg.get_int(
                            f"ps.canary.{mname}.percent", 10))
                sv = cfg.get_int(f"ps.shadow.{mname}.version", 0)
                if sv:
                    fleet.install_shadow(mname, version=sv)
            if autoscale:
                # sensor connection is its own client (one per thread)
                sensor = make_queue_client(wire_cfg, delim=od)
                scaler = FleetAutoscaler(
                    fleet, sensor, queue=req_q,
                    policy=AutoscalePolicy(
                        min_workers=cfg.get_int(
                            "ps.autoscale.min.workers", 1),
                        max_workers=cfg.get_int(
                            "ps.autoscale.max.workers", 4),
                        slo_p99_ms=policy.slo_p99_ms),
                    interval_s=cfg.get_float(
                        "ps.autoscale.interval.ms", 250.0) / 1000.0,
                    counters=counters).start()
            feeder = make_queue_client(wire_cfg, delim=od)
            msgs = [od.join(["predict", str(i)] + row)
                    for i, row in enumerate(rows)]
            if ttl_ms > 0:
                from ..telemetry import reqtrace
                msgs = reqtrace.stamp_deadline(msgs, ttl_ms, delim=od)
            client_model = cfg.get("ps.client.model")
            if client_model:
                from ..telemetry import reqtrace
                msgs = reqtrace.stamp_model(msgs, client_model, delim=od)
            feeder.lpush_many(req_q, msgs)
            feeder.lpush(req_q, "stop")
            if not fleet.wait(timeout_s=300.0):
                # a wedged worker means an incomplete reply set: fail
                # loudly rather than writing a silently truncated output
                raise RuntimeError(
                    "predictionService fleet: worker(s) still draining "
                    "after 300s — replay aborted (partial output "
                    "suppressed)")
            if scaler is not None:
                scaler.stop()
                counters.set("Autoscaler", "FinalActiveWorkers",
                             fleet.active_workers())
            # first reply per id wins (the shared dedup_replies helper —
            # same consumer-side exactly-once contract the replay CLI
            # uses): the RespClient reconnect contract is at-least-once
            # on writes, so a re-pushed request could answer twice — and
            # a reply count that does not cover every request is a
            # corrupted replay, never a part file
            replies: List[str] = []
            while True:
                v = feeder.rpop(pred_q)
                if v is None:
                    break
                replies.append(v)
            by_id, dups = dedup_replies(replies, delim=od)
            if dups:
                import warnings
                warnings.warn(f"predictionService fleet: {dups} "
                              f"duplicate replies deduped (reconnect "
                              f"re-push window)", RuntimeWarning)
            if len(by_id) != len(rows):
                raise RuntimeError(
                    f"predictionService fleet: {len(by_id)} replies for "
                    f"{len(rows)} requests — replay aborted (partial "
                    f"output suppressed)")
            out: List[str] = [f"{rid}{od}{by_id[rid]}"
                              for rid in sorted(by_id, key=int)]
            # fold the fleet's aggregate counters + latency percentiles
            # into the job dump before teardown
            for grp, names in fleet.merged_counters().as_dict().items():
                counters.update_group(grp, names)
            fleet.merged_timer().export(counters, group="Serving")
            counters.set("Broker", "Shards", n_shards)
            versions = [w.service.version or 0 for w in fleet.workers]
            counters.set("Serving", "ModelVersion",
                         version or min(versions, default=0))
        finally:
            # tear down on EVERY path: an aborted replay must not leave
            # worker services running (and their gauges/health bound to
            # the default registry) or the feeder socket open
            if scaler is not None:
                scaler.stop()
            if fleet is not None:
                fleet.stop()
            for cli in (feeder, sensor):
                if cli is not None:
                    cli.close()
            for s in servers:
                s.stop()
            if journal_root is not None:
                shutil.rmtree(journal_root, ignore_errors=True)
        artifacts.write_text_output(out_path, out, role="m")
        return counters

    common = dict(policy=policy, counters=counters, timer=timer,
                  warm=warm, delim=cfg.field_delim_out,
                  wire_native=wire_native)
    if version:
        svc = PredictionService(pinned_factory(), **common)
        svc.version = version
    else:
        svc = PredictionService(registry=registry, model_name=name,
                                schema=schema, buckets=buckets,
                                quantized=quantized, **common)
    counters.set("Serving", "ModelVersion", svc.version or 0)
    if transport == "resp":
        import shutil
        import tempfile
        from ..io.respq import RespClient, RespServer
        journal_root = tempfile.mkdtemp(prefix="avenir-broker-journal-") \
            if durable != "off" else None
        server = RespServer(durable=durable,
                            journal_dir=journal_root,
                            counters=counters).start()
        try:
            req_q = cfg.get("redis.request.queue", "requestQueue")
            pred_q = cfg.get("redis.prediction.queue", "predictionQueue")
            wire_cfg = {"redis.server.port": server.port,
                        "redis.request.queue": req_q,
                        "redis.prediction.queue": pred_q,
                        "redis.lease.timeout.s": lease_s}
            loop = RespPredictionLoop(svc, wire_cfg)
            feeder = RespClient(port=server.port, delim=od,
                                counters=counters)
            msgs = [od.join(["predict", str(i)] + row)
                    for i, row in enumerate(rows)]
            if ttl_ms > 0:
                from ..telemetry import reqtrace
                msgs = reqtrace.stamp_deadline(msgs, ttl_ms, delim=od)
            for m in msgs:
                feeder.lpush(req_q, m)
            feeder.lpush(req_q, "stop")
            loop.run(max_idle_s=30.0)
            out: List[str] = []
            while True:
                v = feeder.rpop(pred_q)
                if v is None:
                    break
                out.append(v)
            out.sort(key=lambda r: int(r.split(od, 1)[0]))
            loop.close()
            feeder.close()
        finally:
            server.stop()
            if journal_root is not None:
                shutil.rmtree(journal_root, ignore_errors=True)
    elif transport == "inprocess":
        svc.start()
        futures = [svc.submit(row) for row in rows]
        results = []
        for f in futures:
            try:
                results.append(f.result(timeout=120))
            except Exception:
                # same contract as the wire transport: a malformed record
                # costs ITS response line, not the whole replay
                results.append(svc.error_label)
        svc.stop()
        out = [f"{i}{od}{r}" for i, r in enumerate(results)]
    else:
        raise ValueError(f"unknown ps.transport {transport!r} "
                         "(inprocess | resp)")
    artifacts.write_text_output(out_path, out, role="m")
    timer.export(counters, group="Serving")
    return counters
