"""Association-pack job registrations (org.avenir.association.*).

Config-key namespaces follow the reference setup() methods: fia.*
(FrequentItemsApriori.java:109-128, sample resource/fit.properties:17-24),
iim.* (InfrequentItemMarker.java:92-123), arm.*
(AssociationRuleMiner.java:99-106,167-175).
"""

from __future__ import annotations

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from .jobs import register, _splitter


def _read_rows(path: str, delim_regex: str):
    split = _splitter(delim_regex)
    return [split(line.strip()) for line in artifacts.read_text_input(path)
            if line.strip()]


@register("org.avenir.association.FrequentItemsApriori",
          "frequentItemsApriori",
          dist="sharded")
def frequent_items_apriori(cfg: Config, in_path: str, out_path: str
                           ) -> Counters:
    """One Apriori level (FrequentItemsApriori.java).  Keys:
    fia.item.set.length, fia.tans.id.ord, fia.skip.field.count,
    fia.emit.trans.id, fia.trans.id.output, fia.support.threshold,
    fia.total.tans.count, fia.item.set.file.path (level > 1),
    fia.infreq.item.marker."""
    from ..association import itemsets as IT
    counters = Counters()
    length = cfg.must_get_int("fia.item.set.length",
                              "missing item set length")
    trans_ord = cfg.must_get_int("fia.tans.id.ord",
                                 "missing transaction id ordinal")
    skip = cfg.get_int("fia.skip.field.count", 1)
    emit_tid = cfg.get_boolean("fia.emit.trans.id", True)
    tid_out = cfg.get_boolean("fia.trans.id.output", True)
    threshold = cfg.must_get_float("fia.support.threshold",
                                   "missing support threshold")
    total = cfg.must_get_int("fia.total.tans.count",
                             "missing total transaction count")
    marker = cfg.get("fia.infreq.item.marker")

    rows = _read_rows(in_path, cfg.field_delim_regex)
    transactions = IT.read_transactions(rows, trans_ord, skip, marker)
    prior = None
    if length > 1:
        prior = IT.parse_itemset_lines(
            artifacts.read_text_input(
                cfg.must_get("fia.item.set.file.path",
                             "missing item set file")),
            length - 1, emit_tid,
            cfg.get("fia.itemset.delim", cfg.field_delim_out))
    level = IT.apriori_level(transactions, length, total, threshold, prior,
                             emit_tid,
                             collect_trans_ids=emit_tid and tid_out)
    artifacts.write_text_output(
        out_path,
        IT.format_itemset_lines(level, emit_tid, tid_out,
                                cfg.field_delim_out))
    # counter semantics under the multi-process all-reduce: increment what
    # THIS process contributed — the level is global-identical on every
    # process (count it on process 0 only; the others add 0 so the counter
    # KEY still exists everywhere, which all_reduce_counters requires),
    # the transactions are per-shard (the sum is the global count, like
    # the reference's mapper counters)
    import jax
    from ..parallel import distributed as D
    mine = (not D.is_multiprocess()) or jax.process_index() == 0
    counters.increment("Apriori", "frequentItemSets",
                       len(level) if mine else 0)
    counters.increment("Apriori", "transactions", len(transactions))
    return counters


@register("org.avenir.association.InfrequentItemMarker",
          "infrequentItemMarker",
          dist="map")
def infrequent_item_marker(cfg: Config, in_path: str, out_path: str
                           ) -> Counters:
    """Map-only infrequent-item masking (InfrequentItemMarker.java).  Keys:
    iim.item.set.file.path (level-1 itemsets), iim.item.set.length (must be
    1), iim.contains.trans.id, iim.skip.field.count, iim.infreq.item.marker,
    iim.itemset.delim."""
    from ..association import itemsets as IT
    counters = Counters()
    length = cfg.must_get_int("iim.item.set.length",
                              "missing item set length")
    if length != 1:
        raise ValueError("expecting item set of length 1")
    contains_tid = cfg.get_boolean("iim.contains.trans.id", True)
    skip = cfg.get_int("iim.skip.field.count", 1)
    marker = cfg.get("iim.infreq.item.marker", "*")
    itemsets = IT.parse_itemset_lines(
        artifacts.read_text_input(
            cfg.must_get("iim.item.set.file.path", "missing item set file")),
        1, contains_tid, cfg.get("iim.itemset.delim", ","))
    freq = [s.items[0] for s in itemsets]
    rows = _read_rows(in_path, cfg.get("iim.field.delim.regex",
                                       cfg.field_delim_regex))
    marked = IT.mark_infrequent(rows, freq, marker, skip)
    delim_out = cfg.get("iim.field.delim.out", cfg.field_delim_out)
    # map-only job (reference emits from the mapper): per-process part-m
    # files under multi-process, like the other per-record transforms
    artifacts.write_text_output(out_path,
                                [delim_out.join(r) for r in marked],
                                role="m")
    # frequentItems is read from the replicated itemset model file, so it
    # is global-identical on every process: count it once (others add 0 to
    # keep the counter key set aligned for all_reduce_counters)
    import jax
    from ..parallel import distributed as D
    mine = (not D.is_multiprocess()) or jax.process_index() == 0
    counters.increment("Apriori", "frequentItems", len(freq) if mine else 0)
    return counters


@register("org.avenir.association.AssociationRuleMiner",
          "associationRuleMiner",
          dist="gather")
def association_rule_miner(cfg: Config, in_path: str, out_path: str
                           ) -> Counters:
    """Rule mining from frequent itemsets (AssociationRuleMiner.java).
    Keys: arm.conf.threshold, arm.max.ante.size, arm.input.has.count (set
    when the input is count-mode Apriori output with a count column),
    arm.input.itemset.length (set when the input is a single-level
    trans-id-mode Apriori output: first N fields are items, the rest
    transaction ids + support), arm.output.confidence (extension).

    The standard chained pipeline feeds this job Apriori output produced
    with ``fia.trans.id.output=false`` (items...,support lines), matching
    the reference's expected input (RuleMinerMapper :113-118)."""
    from ..association import rules as RU
    counters = Counters()
    threshold = cfg.must_get_float("arm.conf.threshold",
                                   "missing confidence threshold")
    max_ante = cfg.get_int("arm.max.ante.size", 3)
    frequent = RU.parse_frequent_lines(
        artifacts.read_text_input(in_path), cfg.field_delim_out,
        cfg.get_boolean("arm.input.has.count", False),
        cfg.get_int("arm.input.itemset.length"))
    lines = RU.mine_rules(frequent, threshold, max_ante,
                          cfg.field_delim_out,
                          cfg.get_boolean("arm.output.confidence", False))
    artifacts.write_text_output(out_path, lines)
    counters.increment("Apriori", "rules", len(lines))
    return counters
