"""Neural-net pack jobs: the reference's single-node NN trainer
(python/supv/basic_nn.py, invoked as ``basic_nn.py <num_hidden_units>
<data_set_size> <noise> <iteration_count> <learning_rate> <training_mode>``)
rebuilt as schema-driven CSV-in/CSV-out jobs with a saved model artifact.

Config keys (nn.* namespace, mirroring the script's arguments):
nn.hidden.units, nn.iteration.count, nn.learning.rate, nn.reg.lambda,
nn.training.mode (batch|incr|minibatch), nn.batch.size,
nn.validation.interval, nn.model.file.path, nn.validation.data.file.path.
"""

from __future__ import annotations

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters, ConfusionMatrix
from ..core import artifacts
from ..core.table import load_csv
from .jobs import register, _schema_path


def _xy(table):
    """Feature matrix + class codes, with unknown-label rows (code -1, e.g.
    typos outside the schema cardinality) dropped rather than silently
    trained as the last class (negative jnp indexing wraps)."""
    X = table.feature_matrix(dtype=np.float32)
    y = np.asarray(table.class_codes()).astype(np.int32)
    known = y >= 0
    return X[known], y[known]


@register("org.avenir.supv.NeuralNetworkTrainer", "neuralNetwork",
          dist="gather")
def neural_network_trainer(cfg: Config, in_path: str, out_path: str) -> Counters:
    from ..nn import mlp
    counters = Counters()
    schema = _schema_path(cfg, "feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    X, y = _xy(table)
    if len(y) == 0:
        raise ValueError("no trainable rows: every class label is unknown")
    n_classes = len(schema.class_attr_field.cardinality or []) or int(y.max()) + 1
    if n_classes < 2:
        raise ValueError(f"need >= 2 classes, got {n_classes}")
    mcfg = mlp.MLPConfig(
        hidden_dim=cfg.get_int("nn.hidden.units", 3),
        n_classes=n_classes,
        learning_rate=cfg.get_float("nn.learning.rate", 0.01),
        reg_lambda=cfg.get_float("nn.reg.lambda", 0.01),
        mode=cfg.get("nn.training.mode", "batch"),
        iterations=cfg.get_int("nn.iteration.count", 1000),
        batch_size=cfg.get_int("nn.batch.size", 64),
        seed=cfg.get_int("nn.random.seed", 0),
        validation_interval=cfg.get_int("nn.validation.interval", 50),
    )
    val_path = cfg.get("nn.validation.data.file.path")
    Xv = yv = None
    if val_path:
        vt = load_csv(val_path, schema, cfg.field_delim_regex)
        Xv, yv = _xy(vt)
        if len(yv) == 0:
            raise ValueError(
                f"validation file {val_path!r} has no known class labels")

    ckpt_dir = cfg.get("nn.checkpoint.dir.path")
    ckpt_interval = cfg.get_int("nn.checkpoint.interval", 0)
    if ckpt_dir and ckpt_interval > 0:
        # chunked training with durable per-chunk state: resume from the
        # latest checkpoint (the reference's iterate-via-durable-artifact
        # contract, SURVEY.md §5 checkpoint/resume)
        from ..core.checkpoint import CheckpointManager
        mgr = CheckpointManager(ckpt_dir)
        arch = {"hidden_dim": mcfg.hidden_dim, "n_classes": mcfg.n_classes,
                "n_features": int(X.shape[1]), "mode": mcfg.mode}
        done, params0 = 0, None
        latest = mgr.latest_step()
        if latest is not None:
            done, arrays, meta = mgr.restore(latest)
            saved_arch = meta.get("arch")
            if saved_arch is not None and saved_arch != arch:
                raise ValueError(
                    f"checkpoint in {ckpt_dir!r} was trained with "
                    f"{saved_arch}, current config is {arch}; use a fresh "
                    "checkpoint dir")
            params0 = dict(arrays)
        if done > mcfg.iterations:
            raise ValueError(
                f"checkpoint in {ckpt_dir!r} has {done} completed iterations "
                f"but nn.iteration.count is {mcfg.iterations}; use a fresh "
                "checkpoint dir to train a shorter run")
        if done >= mcfg.iterations and params0 is None:
            raise ValueError("nn.checkpoint.dir.path has no state yet "
                             "but nn.iteration.count is 0")
        params = params0  # already-complete resume: nothing left to train
        losses = np.zeros((0,))
        import dataclasses
        # align chunks to the validation grid so the recorded loss history
        # matches an unchunked run of the same config
        interval = max(mcfg.validation_interval, 1)
        ckpt_interval = max((ckpt_interval // interval) * interval, interval)
        while done < mcfg.iterations:
            chunk = min(ckpt_interval, mcfg.iterations - done)
            # fold progress into the seed: each chunk must continue the
            # PRNG stream, not replay the first chunk's shuffles
            ccfg = dataclasses.replace(mcfg, iterations=chunk,
                                       seed=mcfg.seed + done)
            params, chunk_losses = mlp.train(X, y, ccfg, X_val=Xv, y_val=yv,
                                             params0=params0)
            if chunk < interval and len(losses) and mcfg.mode == "batch":
                # batch mode records interval-end losses, so an unchunked run
                # never records the tail; incr/minibatch record epoch-start
                # samples ([::interval] from 0), so their tail entry matches
                chunk_losses = chunk_losses[:0]
            done += chunk
            params0 = {k: np.asarray(v) for k, v in params.items()}
            mgr.save(done, params0, {"iterations": done, "arch": arch})
            losses = np.concatenate([losses, chunk_losses])
    else:
        params, losses = mlp.train(X, y, mcfg, X_val=Xv, y_val=yv)
    od = cfg.field_delim_out
    lines = mlp.to_lines(params, od)
    artifacts.write_text_output(out_path, lines)
    model_path = cfg.get("nn.model.file.path")
    if model_path:
        with open(model_path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
    acc = float((np.asarray(mlp.predict(params, X)) == y).mean())
    counters.set("NeuralNetwork", "trainAccuracyPct", int(round(acc * 100)))
    if len(losses):
        counters.set("NeuralNetwork", "finalLossE6",
                     int(round(float(losses[-1]) * 1e6)))
    counters.set("NeuralNetwork", "lossEvaluations", len(losses))
    return counters


@register("org.avenir.supv.NeuralNetworkPredictor", "neuralNetworkPredictor",
          dist="map")
def neural_network_predictor(cfg: Config, in_path: str, out_path: str) -> Counters:
    from ..nn import mlp
    counters = Counters()
    schema = _schema_path(cfg, "feature.schema.file.path")
    od = cfg.field_delim_out
    params = mlp.from_lines(
        artifacts.read_text_input(cfg.must_get("nn.model.file.path")), od)
    table = load_csv(in_path, schema, cfg.field_delim_regex, keep_raw=True)
    X = table.feature_matrix(dtype=np.float32)
    pred = np.asarray(mlp.predict(params, X))
    probs = np.asarray(mlp.predict_proba(params, X))
    class_field = schema.class_attr_field
    values = class_field.cardinality or [str(i) for i in
                                         range(probs.shape[1])]
    lines = []
    for i, raw in enumerate(table.raw_rows):
        p = int(round(float(probs[i, pred[i]]) * 100))
        lines.append(od.join(raw + [values[pred[i]], str(p)]))
    artifacts.write_text_output(out_path, lines, role="m")
    if class_field.ordinal in table.columns:
        actual = np.asarray(table.class_codes())
        known = actual >= 0
        correct = int((pred[known] == actual[known]).sum())
        total = int(known.sum())
        counters.set("Validation", "Correct", correct)
        counters.set("Validation", "Incorrect", total - correct)
        if len(values) == 2:
            # export() owns the Accuracy/Precision/Recall counters
            cm = ConfusionMatrix(values[0], values[1])
            cm.report_batch(pred[known] == 1, actual[known] == 1,
                            actual[known] == 0)
            cm.export(counters)
        elif total:
            counters.set("Validation", "Accuracy",
                         int(100 * correct / total))
    return counters
