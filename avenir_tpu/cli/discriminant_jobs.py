"""Discriminant-pack jobs (org.avenir.discriminant.*).

Config keys follow the reference setup() methods: svm.* incl. the reference's
``svm.pnalty.factor`` typo (SupportVectorMachine.java:61-66) and the Fisher
job's reuse of the numeric-stats pipeline (FisherDiscriminant.java).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters, ConfusionMatrix
from ..core import artifacts
from ..core.table import load_csv
from .jobs import register, _schema_path


def _svm_xy(cfg: Config, table, schema):
    """Features + ±1 targets.  The reference expects the class column already
    numeric ±1 (parsed as double, SupportVectorMachine.java:97-100); we also
    accept a categorical class with svm.positive.class.value."""
    X = table.feature_matrix(dtype=np.float64)
    cf = schema.class_attr_field
    if cf.is_categorical:
        pos = cfg.must_get("svm.positive.class.value",
                           "categorical class needs svm.positive.class.value")
        y = np.where(table.class_codes() == cf.must_cat_code(pos), 1.0, -1.0)
    else:
        y = np.where(table.columns[cf.ordinal] > 0, 1.0, -1.0)
    return X, y


@register("org.avenir.discriminant.SupportVectorMachine",
          "supportVectorMachine",
          dist="gather")
def support_vector_machine(cfg: Config, in_path: str, out_path: str
                           ) -> Counters:
    """SMO training; emits support-vector rows (features..., target, alpha)
    plus a 'weights' model line for the linear predictor.  Keys:
    svm.feature.schema.file.path, svm.pnalty.factor, svm.tolerance, svm.eps,
    svm.kernel.type, svm.positive.class.value.

    ``svm.group.field.ordinals`` trains one SVM per distinct group key —
    the reference's per-mapper partitions (SupportVectorMachine.java:70-85)
    — with every output line prefixed by its group key.  ``svm.solver``
    picks the trainer: ``serial`` (Platt, the default) or ``batched`` (the
    lock-step maximal-violating-pair device SMO,
    discriminant/smo.py:train_groups_batched — ALL groups advance in one
    jitted while_loop; same optimum, so per-group weights/threshold/
    predictions agree with serial to optimization tolerance, though the
    support-vector line SETS may differ on degenerate margins)."""
    from ..discriminant import smo as S
    counters = Counters()
    schema = _schema_path(cfg, "svm.feature.schema.file.path")
    group_ords = [int(o) for o in
                  cfg.get_list("svm.group.field.ordinals") or []]
    solver = cfg.get("svm.solver", "serial")
    if solver not in ("serial", "batched"):
        raise ValueError(f"svm.solver must be serial|batched, got {solver!r}")
    table = load_csv(in_path, schema, cfg.field_delim_regex,
                     keep_raw=bool(group_ords))
    params = S.SMOParams(
        penalty_factor=cfg.get_float("svm.pnalty.factor",
                                     cfg.get_float("svm.penalty.factor", 0.05)),
        tolerance=cfg.get_float("svm.tolerance", 1e-3),
        eps=cfg.get_float("svm.eps", 1e-3),
        kernel_type=cfg.get("svm.kernel.type", S.KERNEL_LINEAR),
        seed=cfg.get_int("svm.random.seed", 0),
    )
    X, y = _svm_xy(cfg, table, schema)
    od = cfg.field_delim_out

    def weights_line(model, prefix=()):
        return od.join([*prefix, "weights"] +
                       [f"{w:.9g}" for w in model.weights] +
                       [f"{model.threshold:.9g}"])

    lines: List[str] = []
    if group_ords:
        row_idx: dict = {}
        for i, r in enumerate(table.raw_rows):
            row_idx.setdefault(od.join(r[o] for o in group_ords),
                               []).append(i)
        gxy = {g: (X[idx], y[idx]) for g, idx in row_idx.items()}
        models = S.train_groups(gxy, params, batched=(solver == "batched"))
        n_sv = 0
        for g in sorted(models):
            m = models[g]
            n_sv += len(m.sup_vec_idx)
            lines.extend(od.join([g, sv])
                         for sv in m.support_vector_lines(od))
            lines.append(weights_line(m, prefix=(g,)))
        counters.set("SVM", "groups", len(models))
        counters.set("SVM", "supportVectors", n_sv)
    else:
        model = S.train_groups({"": (X, y)}, params,
                               batched=(solver == "batched"))[""]
        lines = model.support_vector_lines(od)
        lines.append(weights_line(model))
        counters.set("SVM", "supportVectors", len(model.sup_vec_idx))
    artifacts.write_text_output(out_path, lines)
    counters.set("SVM", "rows", table.n_rows)
    return counters


@register("org.avenir.discriminant.SupportVectorPredictor",
          "supportVectorPredictor",
          dist="map")
def support_vector_predictor(cfg: Config, in_path: str, out_path: str
                             ) -> Counters:
    """Map-only linear-SVM prediction from the trained model's weights line;
    validation mode exports a confusion matrix.  Keys: svm.model.file.path
    plus the training keys."""
    from ..discriminant import smo as S
    counters = Counters()
    schema = _schema_path(cfg, "svm.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex, keep_raw=True)
    od = cfg.field_delim_out
    w = b = None
    for line in artifacts.read_text_input(cfg.must_get("svm.model.file.path")):
        parts = line.strip().split(od)
        if parts and parts[0] == "weights":
            vals = [float(v) for v in parts[1:]]
            w, b = np.array(vals[:-1]), vals[-1]
    if w is None:
        raise ValueError("model file has no weights line")
    model = S.SVMModel(weights=w, threshold=b,
                       sup_vec_idx=np.zeros(0, int),
                       alphas=np.zeros(0), X=np.zeros((0, len(w))),
                       y=np.zeros(0))
    X, _ = _svm_xy(cfg, table, schema)
    pred = S.predict(model, X)
    cf = schema.class_attr_field
    pos = cfg.get("svm.positive.class.value")
    card = cf.cardinality or []
    neg = next((c for c in card if c != pos), "-1")
    labels = np.where(pred > 0, pos if pos else "1", neg)
    lines = [od.join(row + [str(labels[i])])
             for i, row in enumerate(table.raw_rows)]
    artifacts.write_text_output(out_path, lines, role="m")
    if cfg.get_boolean("validation.mode", False) and pos:
        cm = ConfusionMatrix(neg_class=neg, pos_class=pos)
        actual = [row[cf.ordinal] for row in table.raw_rows]
        cm.report_batch(pred > 0,
                        np.array([a == pos for a in actual]),
                        np.array([a == neg for a in actual]))
        cm.export(counters)
    return counters


@register("org.avenir.discriminant.FisherDiscriminant", "fisherDiscriminant",
          dist="gather")
def fisher_discriminant_job(cfg: Config, in_path: str, out_path: str
                            ) -> Counters:
    """Per-attribute two-class boundary lines
    ``attr,logOddsPrior,pooledVariance,discrimValue``
    (FisherDiscriminant.java:44-55).  Key: fid.feature.schema.file.path
    (falls back to feature.schema.file.path)."""
    from ..discriminant import fisher as F
    counters = Counters()
    key = ("fid.feature.schema.file.path"
           if cfg.get("fid.feature.schema.file.path")
           else "feature.schema.file.path")
    schema = _schema_path(cfg, key)
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    res = F.fisher_discriminant(table)
    artifacts.write_text_output(out_path, res.to_lines(cfg.field_delim_out))
    counters.set("Fisher", "attributes", len(res.attr_ordinals))
    return counters
