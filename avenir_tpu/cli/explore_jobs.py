"""Explore-pack job registrations (org.avenir.explore.*).

Each wraps the avenir_tpu.explore implementations with the reference's
config-key namespaces (crc.*, nuc.*, hrc.*, mut.*, coe.*, cbos.*, usb.*,
ffr.*, abe.*, abu.* — see the setup() methods of the matching reference
classes under explore/)."""

from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from ..core.table import load_csv
from ..parallel.mesh import runtime_context
from .jobs import register, _schema_path, _splitter


@register("org.avenir.explore.MutualInformation", "mutualInformation",
          dist="sharded")
def mutual_information(cfg: Config, in_path: str, out_path: str) -> Counters:
    """MI distributions + selection scores (explore/MutualInformation.java).
    Keys: mut.feature.schema.file.path, mut.mutual.info.score.algorithms,
    mut.mutual.info.redundancy.factor, mut.output.mutual.info."""
    from ..explore import mutual_info as MI
    counters = Counters()
    schema = _schema_path(cfg, "mut.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    stats = MI.compute_stats(table, runtime_context())
    od = cfg.field_delim_out
    lines: List[str] = []
    if cfg.get_boolean("mut.output.mutual.info", True):
        lines.append(f"classEntropy{od}{stats.class_entropy():.6f}")
        for i, o in enumerate(stats.feature_ordinals):
            lines.append(f"entropy{od}{o}{od}{stats.feature_entropy(i):.6f}")
            lines.append(f"mutualInfo{od}{o}{od}{stats.feature_class_mi(i):.6f}")
        for i in range(len(stats.feature_ordinals)):
            for j in range(i + 1, len(stats.feature_ordinals)):
                oi, oj = stats.feature_ordinals[i], stats.feature_ordinals[j]
                lines.append(f"pairMutualInfo{od}{oi}{od}{oj}{od}"
                             f"{stats.pair_mi(i, j):.6f}")
                lines.append(f"pairClassMutualInfo{od}{oi}{od}{oj}{od}"
                             f"{stats.pair_class_mi(i, j):.6f}")
    algs = cfg.get_list("mut.mutual.info.score.algorithms",
                        ["mutual.info.maximization"])
    rf = cfg.get_float("mut.mutual.info.redundancy.factor", 1.0)
    for alg in algs:
        fn = MI.SCORE_ALGORITHMS.get(alg)
        if fn is None:
            raise ValueError(f"unknown MI score algorithm {alg!r}; known: "
                             f"{sorted(MI.SCORE_ALGORITHMS)}")
        for o, score in fn(stats, rf):
            lines.append(f"score{od}{alg}{od}{o}{od}{score:.6f}")
    artifacts.write_text_output(out_path, lines)
    return counters


@register("org.avenir.explore.CramerCorrelation", "cramerCorrelation",
          dist="gather")
def cramer_correlation(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Cramér index between source and dest categorical attrs
    (explore/CramerCorrelation.java; crc.* keys).  Output scaled ints."""
    from ..explore.correlations import categorical_pair_matrix
    counters = Counters()
    schema = _schema_path(cfg, "crc.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    src = cfg.must_get_list("crc.source.attributes")
    dst = cfg.must_get_list("crc.dest.attributes")
    scale = cfg.get_int("crc.correlation.scale", 1000)
    od = cfg.field_delim_out
    lines = []
    for a in map(int, src):
        for b in map(int, dst):
            v = categorical_pair_matrix(table, a, b).cramer_index()
            lines.append(f"{a}{od}{b}{od}{int(v * scale)}")
    artifacts.write_text_output(out_path, lines)
    return counters


@register("org.avenir.explore.NumericalCorrelation", "numericalCorrelation",
          dist="sharded")
def numerical_correlation(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Pearson correlation for attr pairs (explore/NumericalCorrelation.java;
    nuc.attr.pairs = 'a:b,c:d' style pair list, or all feature pairs)."""
    from ..explore.correlations import numerical_correlations
    counters = Counters()
    schema = _schema_path(cfg, "nuc.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    pairs_cfg = cfg.get("nuc.attr.pairs")
    od = cfg.field_delim_out
    if pairs_cfg:
        pairs = [tuple(map(int, p.split(":"))) for p in pairs_cfg.split(",")]
        ordinals = sorted({o for p in pairs for o in p})
    else:
        ordinals = [f.ordinal for f in schema.feature_fields if f.is_numeric]
        pairs = None
    corr = numerical_correlations(table, ordinals, runtime_context())
    lines = []
    for a, b, v in corr:
        if pairs is None or (a, b) in pairs or (b, a) in pairs:
            lines.append(f"{a}{od}{b}{od}{v:.6f}")
    artifacts.write_text_output(out_path, lines)
    return counters


@register("org.avenir.explore.HeterogeneityReductionCorrelation",
          "heterogeneityReductionCorrelation",
          dist="gather")
def heterogeneity_correlation(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Concentration/uncertainty coefficient per categorical pair
    (hrc.heterogeneity.algorithm = gini | entropy)."""
    from ..explore.correlations import heterogeneity_correlations
    counters = Counters()
    schema = _schema_path(cfg, "hrc.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    algo = cfg.get("hrc.heterogeneity.algorithm", "gini")
    ordinals = cfg.get_int_list("hrc.attributes") or \
        [f.ordinal for f in schema.feature_fields if f.is_categorical]
    od = cfg.field_delim_out
    lines = [f"{a}{od}{b}{od}{v:.6f}"
             for a, b, v in heterogeneity_correlations(table, ordinals, algo)]
    artifacts.write_text_output(out_path, lines)
    return counters


@register("org.avenir.explore.CategoricalClassAffinity",
          "categoricalClassAffinity",
          dist="gather")
def categorical_class_affinity(cfg: Config, in_path: str, out_path: str) -> Counters:
    """value -> class affinity scores (explore/CategoricalClassAffinity.java)."""
    from ..explore.correlations import class_affinity
    counters = Counters()
    schema = _schema_path(cfg, "cca.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    ordinals = cfg.get_int_list("cca.attributes") or \
        [f.ordinal for f in schema.feature_fields if f.is_categorical]
    aff = class_affinity(table, ordinals)
    cls_vals = schema.class_attr_field.cardinality or []
    od = cfg.field_delim_out
    lines = []
    for o in ordinals:
        f = schema.find_field_by_ordinal(o)
        for vi, value in enumerate(f.cardinality or []):
            parts = [str(o), value]
            for ci, cv in enumerate(cls_vals):
                parts.append(cv)
                parts.append(f"{aff[o][vi, ci]:.6f}")
            lines.append(od.join(parts))
    artifacts.write_text_output(out_path, lines)
    return counters


@register("org.avenir.explore.CategoricalContinuousEncoding",
          "categoricalContinuousEncoding",
          dist="gather")
def categorical_continuous_encoding_job(cfg: Config, in_path: str,
                                        out_path: str) -> Counters:
    """Supervised encoding (coe.* keys; output 'ordinal,value,encoded')."""
    from ..explore.encoders import categorical_continuous_encoding
    counters = Counters()
    schema = _schema_path(cfg, "coe.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    enc = categorical_continuous_encoding(
        table,
        attr_ordinals=[int(o) for o in
                       cfg.must_get_list("coe.cat.attribute.ordinals")],
        class_attr_ordinal=cfg.must_get_int("coe.class.attr.ordinal"),
        pos_class_value=cfg.must_get("coe.pos.class.attr.value"),
        strategy=cfg.must_get("coe.encoding.strategy"),
        scale=cfg.must_get_int("coe.output.scale"))
    od = cfg.field_delim_out
    artifacts.write_text_output(
        out_path, (f"{o}{od}{v}{od}{e}" for o, v, e in enc))
    return counters


@register("org.avenir.explore.ClassBasedOverSampler", "classBasedOverSampler",
          dist="gather")
def class_based_over_sampler(cfg: Config, in_path: str, out_path: str) -> Counters:
    """SMOTE oversampling of a minority class (cbos.* keys)."""
    from ..explore.samplers import smote_oversample
    counters = Counters()
    schema = _schema_path(cfg, "cbos.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex, keep_raw=True)
    syn = smote_oversample(
        table, cfg.must_get("cbos.minority.class.value"),
        k=cfg.get_int("cbos.neighbor.count", 5),
        multiplier=cfg.get_int("cbos.over.sampling.multiplier", 1),
        seed=cfg.get_int("cbos.random.seed", 0))
    od = cfg.field_delim_out
    lines = [od.join(r) for r in table.raw_rows] + [od.join(r) for r in syn]
    artifacts.write_text_output(out_path, lines)
    counters.increment("Sampling", "Synthetic records", len(syn))
    return counters


@register("org.avenir.explore.UnderSamplingBalancer", "underSamplingBalancer",
          dist="gather")
def under_sampling_balancer(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Majority-class undersampling (usb.* keys)."""
    from ..explore.samplers import under_sample
    counters = Counters()
    schema = _schema_path(cfg, "usb.feature.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex, keep_raw=True)
    keep = under_sample(table, cfg.must_get("usb.majority.class.value"),
                        rate=cfg.must_get_float("usb.sampling.rate"),
                        seed=cfg.get_int("usb.random.seed", 0))
    od = cfg.field_delim_out
    lines = [od.join(r) for r, k in zip(table.raw_rows, keep) if k]
    artifacts.write_text_output(out_path, lines)
    counters.increment("Sampling", "Kept", len(lines))
    counters.increment("Sampling", "Dropped", table.n_rows - len(lines))
    return counters


@register("org.avenir.explore.ReliefFeatureRelevance", "reliefFeatureRelevance",
          dist="gather")
def relief_feature_relevance(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Relief relevance scores (ffr.* keys; output 'ordinal,score')."""
    from ..explore.samplers import relief_relevance
    counters = Counters()
    schema = _schema_path(cfg, "ffr.attr.schema.file.path")
    table = load_csv(in_path, schema, cfg.field_delim_regex)
    ordinals = cfg.must_get_list("ffr.attr.ordinals")
    scores = relief_relevance(table, [int(o) for o in ordinals],
                              sample_count=cfg.get_int("ffr.sample.count"),
                              seed=cfg.get_int("ffr.random.seed", 0))
    od = cfg.field_delim_out
    artifacts.write_text_output(
        out_path, (f"{o}{od}{scores[int(o)]:.3f}" for o in ordinals))
    return counters


@register("org.avenir.explore.AdaBoostError", "adaBoostError",
          dist="gather")
def adaboost_error_job(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Weighted boosting error (abe.* keys: actual/pred/boost ordinals)."""
    from ..explore.encoders import adaboost_error
    counters = Counters()
    delim = cfg.field_delim_regex
    lines_in = artifacts.read_text_input(in_path)
    a_ord = cfg.must_get_int("abe.actual.class.attr.ordinal")
    p_ord = cfg.must_get_int("abe.pred.class.attr.ordinal")
    b_ord = cfg.must_get_int("abe.boost.attr.ordinal")
    split_line = _splitter(delim)
    actual, pred, w = [], [], []
    for l in lines_in:
        it = split_line(l)
        actual.append(it[a_ord]); pred.append(it[p_ord])
        w.append(float(it[b_ord]))
    err = adaboost_error(actual, pred, np.asarray(w),
                         cfg.get_boolean("abe.weight.normalized", True))
    prec = cfg.get_int("abe.output.precision", 6)
    artifacts.write_text_output(out_path, [f"error={err:.{prec}f}"])
    return counters


@register("org.avenir.explore.AdaBoostUpdate", "adaBoostUpdate",
          dist="gather")
def adaboost_update_job(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Boosting weight update pass (abu.* keys) emitting records with the
    boost column rewritten (AdaBoostUpdate.java:117-137)."""
    from ..explore.encoders import adaboost_update
    counters = Counters()
    delim = cfg.field_delim_regex
    lines_in = artifacts.read_text_input(in_path)
    a_ord = cfg.must_get_int("abu.actual.class.attr.ordinal")
    p_ord = cfg.must_get_int("abu.pred.class.attr.ordinal")
    b_ord = cfg.must_get_int("abu.boost.attr.ordinal")
    error = cfg.must_get_float("abu.iteration.error")
    initial = cfg.get_float("abu.initial.weight", 1.0)
    prec = cfg.get_int("abu.output.precision", 6)
    rows = [_splitter(delim)(l) for l in lines_in]
    actual = [r[a_ord] for r in rows]
    pred = [r[p_ord] for r in rows]
    w = np.asarray([float(r[b_ord]) for r in rows])
    w2 = adaboost_update(w, actual, pred, error, initial)
    out = []
    for r, nw in zip(rows, w2):
        r[b_ord] = f"{nw:.{prec}f}"
        out.append(delim.join(r))
    artifacts.write_text_output(out_path, out)
    return counters


@register("org.avenir.explore.BaggingSampler", "baggingSampler",
          dist="gather")
def bagging_sampler(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Per-batch bagging (explore/BaggingSampler.java:90-124): stream rows in
    batches of bas.batch.size, emit batchSize uniform with-replacement draws
    from each batch (whole-dataset sampling would need global state the
    streaming reference cannot hold)."""
    from ..explore.samplers import bagging_sample
    counters = Counters()
    batch_size = cfg.get_int("bas.batch.size", 10000)
    seed = cfg.get_int("bas.random.seed", 0)
    lines_in = artifacts.read_text_input(in_path)
    out = []
    for b, start in enumerate(range(0, len(lines_in), batch_size)):
        batch = lines_in[start:start + batch_size]
        idx = bagging_sample(len(batch), 1.0, with_replacement=True,
                             seed=seed + b)
        out.extend(batch[i] for i in idx)
    artifacts.write_text_output(out_path, out)
    counters.set("Bagging", "inputRows", len(lines_in))
    counters.set("Bagging", "sampledRows", len(out))
    return counters


@register("org.avenir.explore.TopMatchesByClass", "topMatchesByClass",
          dist="gather")
def top_matches_by_class(cfg: Config, in_path: str, out_path: str) -> Counters:
    """Per-record top-k nearest SAME-class neighbors, the SMOTE precursor
    (explore/TopMatchesByClass.java).  Input: pair-distance lines from the
    sameTypeSimilarity job (id1,id2,distance,class1,class2 — divergence: the
    reference reads sifarish's rank-last layout); each unordered pair feeds
    both directions (TopMatchesByClass.java:183-209).  Keys:
    tmc.top.match.count, tmc.nearest.by.count (false -> keep matches within
    tmc.match.distance), tmc.filer.class.value (reference key spelling)."""
    counters = Counters()
    delim = cfg.field_delim_regex
    od = cfg.field_delim_out
    by_count = cfg.get_boolean("tmc.nearest.by.count", True)
    top_k = cfg.get_int("tmc.top.match.count", 10)
    max_dist = cfg.get_int("tmc.match.distance", 200)
    filter_class = cfg.get("tmc.filer.class.value")
    split = _splitter(delim)
    neighbors: Dict[str, list] = {}
    classes: Dict[str, str] = {}
    for line in artifacts.read_text_input(in_path):
        it = split(line)
        id1, id2, dist, cls1, cls2 = it[0], it[1], int(it[2]), it[3], it[4]
        if cls1 != cls2:
            continue
        if filter_class is not None and cls1 != filter_class:
            continue
        classes[id1] = cls1
        classes[id2] = cls2
        neighbors.setdefault(id1, []).append((dist, id2))
        neighbors.setdefault(id2, []).append((dist, id1))
    out = []
    for src in sorted(neighbors):
        ranked = sorted(neighbors[src])
        if by_count:
            kept = ranked[:top_k]
        else:
            kept = [r for r in ranked if r[0] <= max_dist]
        for dist, trg in kept:
            out.append(od.join([src, classes[src], trg, str(dist)]))
        counters.increment("TopMatches", "records")
    artifacts.write_text_output(out_path, out)
    counters.set("TopMatches", "pairsEmitted", len(out))
    return counters
