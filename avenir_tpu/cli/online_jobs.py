"""The online learning job (org.avenir.online.*).

``onlineLearner`` replays a file of mixed wire messages through the
fused serve-and-learn plane (ISSUE 19): every served window runs
absorb-rewards -> gradient-step -> predict as ONE cached device program
(the ``online.window`` ledger site), learner state device-resident
between windows via donated carries.  Config keys (``ps.online.``
namespace; the shared ``ps.`` wire/transport keys keep their serving
meanings):

  ps.online.actions         comma list of bandit arm names (required)
  ps.online.algorithm       ucb1 | softMax | sampsonSampler (default
                            ucb1) — the device forms sharing the host
                            learners' scoring bodies bit for bit
  ps.online.head            bandit | logistic | mlp (default bandit):
                            which head labels replies.  logistic/mlp
                            ALSO require ps.online.features > 0
  ps.online.features        numeric features per predict row (default 0)
  ps.online.learning.rate   SGD step size (default 0.05)
  ps.online.l2              L2 regularization (default 0)
  ps.online.temp            softMax temperature constant (default 0.1)
  ps.online.mlp.hidden      > 0 adds the MLP head (default 0)
  ps.online.mlp.classes     MLP output classes (default 2)
  ps.online.threshold       positive-class threshold for the logistic
                            head AND the outcome labeler (default 0.5)
  ps.online.window.size     messages drained per window (default 64)
  ps.online.seed            PRNG seed (default 42)
  ps.online.pending.capacity   bounded pending-outcome table size
                            (default 4096; full -> oldest evicted)
  ps.online.pending.ttl.s   decision TTL before shedding (default 300)
  ps.online.snapshot.every  supervised windows between registry
                            snapshots (default 32)
  ps.online.accuracy.floor  integer-percent probation floor; breached
                            for ps.online.floor.consecutive windows of
                            ps.online.floor.window outcomes -> rollback
                            to the pinned snapshot (default 0 = off)
  ps.online.floor.window    outcomes per probation window (default 256)
  ps.online.floor.consecutive  breach streak before rollback (default 2)
  ps.online.state.dir       supervisor journal directory (default: a
                            job temp dir — pass a real one to resume)
  ps.model.registry.dir     registry for snapshot/rollback versions;
                            with ps.model.name it enables the
                            supervisor (omit both = unsupervised)
  ps.model.name             the snapshot lineage name
  ps.bucket.sizes           window shape buckets (default 8,64,256)
  ps.transport              inprocess | resp (default inprocess): resp
                            runs the loop against an embedded broker
                            with leased delivery — predicts acked by
                            the reply push, rewards by snapshot-gated
                            ``reward:<id>`` tokens on
                            redis.rewardack.queue
  ps.broker.lease.timeout.s   lease expiry on the resp path (default 30)
  redis.request.queue / redis.prediction.queue / redis.rewardack.queue
                            resp-queue names

The input file holds one WIRE message per line —
``predict,<id>,<f1>,...,<fN>`` and ``reward,<id>,<value>`` interleaved
(a ``stop`` line ends the resp drain early); the output is one
``<id><delim><label>`` line per served prediction, in arrival order.
Counters land in the Online / OnlineProgramCache groups plus the usual
ledger rows.
"""

from __future__ import annotations

from typing import List

from ..core.config import Config
from ..core.metrics import Counters
from ..core import artifacts
from .jobs import register


@register("org.avenir.online.OnlineLearner", "onlineLearner",
          dist="refuse")
def online_learner(cfg: Config, in_path: str, out_path: str) -> Counters:
    import os
    import shutil
    import tempfile
    from ..online.plane import (DEFAULT_WINDOW_BUCKETS,
                                OnlineWindowPlane)
    from ..online.service import OnlineLearnerService, OnlineRespLoop
    from ..online.state import OnlineLearnerConfig

    counters = Counters()
    actions = tuple(s.strip() for s in
                    cfg.must_get("ps.online.actions").split(",")
                    if s.strip())
    ocfg = OnlineLearnerConfig(
        actions=actions,
        n_features=cfg.get_int("ps.online.features", 0),
        algorithm=cfg.get("ps.online.algorithm", "ucb1"),
        head=cfg.get("ps.online.head", "bandit"),
        temp_constant=cfg.get_float("ps.online.temp", 0.1),
        learning_rate=cfg.get_float("ps.online.learning.rate", 0.05),
        l2=cfg.get_float("ps.online.l2", 0.0),
        mlp_hidden=cfg.get_int("ps.online.mlp.hidden", 0),
        mlp_classes=cfg.get_int("ps.online.mlp.classes", 2),
        threshold=cfg.get_float("ps.online.threshold", 0.5),
        seed=cfg.get_int("ps.online.seed", 42))
    if ocfg.head in ("logistic", "mlp") and ocfg.n_features <= 0:
        raise ValueError(f"ps.online.head={ocfg.head} requires "
                         f"ps.online.features > 0")
    buckets = tuple(cfg.get_int_list("ps.bucket.sizes",
                                     list(DEFAULT_WINDOW_BUCKETS)))
    plane = OnlineWindowPlane(
        ocfg, buckets=buckets,
        pending_capacity=cfg.get_int("ps.online.pending.capacity", 4096),
        pending_ttl_s=cfg.get_float("ps.online.pending.ttl.s", 300.0))

    supervisor = None
    tmp_state = None
    reg_dir = cfg.get("ps.model.registry.dir")
    if reg_dir:
        from ..control.controller import (OnlineSupervisor,
                                          OnlineSupervisorPolicy)
        from ..serving.registry import ModelRegistry
        state_dir = cfg.get("ps.online.state.dir")
        if not state_dir:
            state_dir = tmp_state = tempfile.mkdtemp(
                prefix="avenir-online-state-")
        supervisor = OnlineSupervisor(
            ModelRegistry(reg_dir), cfg.must_get("ps.model.name"),
            state_dir,
            policy=OnlineSupervisorPolicy(
                snapshot_every=cfg.get_int("ps.online.snapshot.every",
                                           32),
                accuracy_floor=cfg.get_int("ps.online.accuracy.floor",
                                           0),
                floor_window=cfg.get_int("ps.online.floor.window", 256),
                floor_consecutive=cfg.get_int(
                    "ps.online.floor.consecutive", 2)),
            counters=counters)

    delim = cfg.field_delim_out
    service = OnlineLearnerService(plane, delim=delim,
                                   counters=counters,
                                   supervisor=supervisor)
    window = cfg.get_int("ps.online.window.size", 64)
    if window < 1:
        raise ValueError(f"ps.online.window.size must be >= 1, "
                         f"got {window}")
    messages = list(artifacts.read_text_input(in_path))
    transport = cfg.get("ps.transport", "inprocess")
    replies: List[str] = []
    try:
        if transport == "resp":
            from ..io.respq import RespServer, make_queue_client
            server = RespServer(counters=counters).start()
            client = make_queue_client(
                {"redis.server.host": "127.0.0.1",
                 "redis.server.port": server.port}, delim=delim,
                counters=counters)
            req_q = cfg.get("redis.request.queue", "requestQueue")
            pred_q = cfg.get("redis.prediction.queue", "predictionQueue")
            ack_q = cfg.get("redis.rewardack.queue", "rewardAckQueue")
            loop = OnlineRespLoop(
                service, client, request_queue=req_q,
                reply_queue=pred_q, reward_ack_queue=ack_q,
                batch=window,
                lease_s=cfg.get_float("ps.broker.lease.timeout.s",
                                      30.0))
            try:
                client.lpush_many(req_q, messages)
                loop.run()
                while True:
                    v = client.rpop(pred_q)
                    if v is None:
                        break
                    replies.append(v)   # lpush+rpop drains FIFO
            finally:
                client.close()
                server.stop()
        elif transport == "inprocess":
            for i in range(0, len(messages), window):
                out, _acks = service.process_window(
                    messages[i:i + window])
                replies.extend(out)
            service.flush_acks()
        else:
            raise ValueError(f"ps.transport must be inprocess or resp, "
                             f"got {transport!r}")
        artifacts.write_text_output(out_path, replies)
        service.export(counters)
    finally:
        if tmp_state:
            shutil.rmtree(tmp_state, ignore_errors=True)
    return counters
