"""Multi-arm bandit learners: the 11 algorithms of the reference.

Parity target: the MultiArmBanditLearner hierarchy
(reinforce/MultiArmBanditLearner.java:36-184) and its factory
(reinforce/MultiArmBanditLearnerFactory.java:30-41) with algorithm names:

  intervalEstimator, sampsonSampler, optimisticSampsonSampler, randomGreedy,
  ucb1, ucb2, softMax, actionPursuit, rewardComparison, exponentialWeight,
  exponentialWeightExpert

Each learner keeps per-action reward statistics (count, mean, std — chombo
SimpleStat), exposes ``next_action`` / ``next_actions(batch)`` /
``set_reward`` and round-trips its state through ``get_model`` /
``build_model`` text lines, the contract the batch jobs and the serving
loop rely on (:113,138,184).  ``merge`` combines distributed partials.

State lines: ``actionId,count,sum,sumSq`` (+ algorithm-specific extra
lines prefixed with '#<name>').
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Sequence, Tuple


# ---- shared score bodies (host + device twins) -------------------------
#
# The online plane (avenir_tpu/online/) keeps these learners' arm
# statistics device-resident and re-evaluates the SAME selection math
# inside a fused XLA program.  To make host-vs-device parity a pin
# rather than a hope, the scoring formulas live here as xp-agnostic
# functions of plain arguments: the host learners call them with python
# scalars and ``math.*``, the device forms (reinforce/online_forms.py)
# call them with ``jnp`` arrays and ``jnp.*``.  One body, two callers —
# a drifting reimplementation cannot pass the parity tests.

def ucb1_upper_bound(mean, count, total_count, *, log=math.log,
                     sqrt=math.sqrt):
    """UCB1 upper bound: mean + sqrt(2 ln N / n)
    (UpperConfidenceBoundOneLearner.java)."""
    return mean + sqrt(2.0 * log(total_count) / count)


def softmax_weight(mean, temp_constant, *, exp=math.exp, minimum=min):
    """Boltzmann sampling weight: exp(mean / tau), argument clamped at
    700 before exponentiation (SoftMaxLearner.java:62-90)."""
    return exp(minimum(mean / temp_constant, 700))


def sampson_sample(mean, sigma, count, unit_normal, *, sqrt=math.sqrt):
    """Thompson posterior draw: mean + (sigma / sqrt(n)) * z, with
    ``sigma`` the observed std dev already floored at 1.0 for the
    degenerate no-variance arm (SampsonSamplerLearner.java).  ``z`` is a
    unit-normal draw supplied by the caller — the host learner feeds
    ``random.Random.gauss(0, 1)``, the device form a normal from a
    threaded PRNG key — so the deterministic body stays shared while
    each side owns its randomness."""
    return mean + (sigma / sqrt(count)) * unit_normal


class ActionStat:
    """chombo SimpleStat equivalent: count / sum / sum of squares."""

    __slots__ = ("count", "total", "total_sq")

    def __init__(self, count=0, total=0.0, total_sq=0.0):
        self.count = count
        self.total = total
        self.total_sq = total_sq

    def add(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.total_sq += value * value

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    @property
    def std_dev(self) -> float:
        if self.count < 2:
            return 0.0
        var = (self.total_sq - self.count * self.mean ** 2) / (self.count - 1)
        return math.sqrt(max(var, 0.0))


class MultiArmBanditLearner:
    """Base learner (MultiArmBanditLearner.java surface)."""

    name = "base"

    def __init__(self, actions: Sequence[str], config: Optional[Dict] = None):
        config = config or {}
        self.actions = list(actions)
        self.stats: Dict[str, ActionStat] = {a: ActionStat() for a in actions}
        self.min_trial = int(config.get("min.trial", -1))
        self.batch_size = int(config.get("decision.batch.size", 1))
        self.reward_scale = int(config.get("reward.scale", 1))
        self.round_num = int(config.get("current.decision.round", 1))
        self.total_trial_count = (self.round_num - 1) * self.batch_size
        self.rng = random.Random(config.get("random.seed"))
        self.rewarded = False

    # ---- selection ----
    def next_action(self) -> str:
        raise NotImplementedError

    def next_actions(self) -> List[str]:
        return [self.next_action() for _ in range(self.batch_size)]

    def _min_trial_action(self) -> Optional[str]:
        """Any action below the min trial count gets tried first
        (selectActionBasedOnMinTrial)."""
        if self.min_trial > 0:
            for a in self.actions:
                if self.stats[a].count < self.min_trial:
                    return a
        return None

    # ---- learning ----
    def set_reward(self, action: str, reward: float) -> None:
        self.stats[action].add(reward)
        self.rewarded = True

    def set_reward_stats(self, action: str, count: int, mean: float,
                         std_dev: float) -> None:
        """Batch learning path (:162-170)."""
        s = self.stats[action]
        s.count = count
        s.total = mean * count
        s.total_sq = (std_dev ** 2) * max(count - 1, 0) + count * mean * mean

    def merge(self, other: "MultiArmBanditLearner") -> None:
        for a, st in other.stats.items():
            self.stats[a] = st

    # ---- state round trip ----
    def get_model(self) -> List[str]:
        lines = [f"{a},{s.count},{s.total},{s.total_sq}"
                 for a, s in self.stats.items()]
        return lines + self._extra_state()

    def build_model(self, lines: Sequence[str]) -> None:
        for line in lines:
            if line.startswith("#"):
                self._load_extra(line)
                continue
            a, c, t, tsq = line.split(",")
            self.stats[a] = ActionStat(int(c), float(t), float(tsq))
        self.rewarded = any(s.count > 0 for s in self.stats.values())

    def _extra_state(self) -> List[str]:
        return []

    def _load_extra(self, line: str) -> None:
        pass

    # helpers
    def _greedy(self) -> str:
        return max(self.actions, key=lambda a: self.stats[a].mean)

    def _random(self) -> str:
        return self.rng.choice(self.actions)

    def _sample_distr(self, probs: Dict[str, float]) -> str:
        total = sum(probs.values())
        r = self.rng.random() * total
        acc = 0.0
        for a in self.actions:
            acc += probs[a]
            if r <= acc:
                return a
        return self.actions[-1]


class IntervalEstimatorLearner(MultiArmBanditLearner):
    """Upper bound of the reward confidence interval
    (reinforce/IntervalEstimatorLearner.java)."""
    name = "intervalEstimator"

    def __init__(self, actions, config=None):
        super().__init__(actions, config)
        cfg = config or {}
        self.bias_factor = float(cfg.get("confidence.factor", 2.0))

    def next_action(self) -> str:
        self.total_trial_count += 1
        a = self._min_trial_action()
        if a:
            return a
        def ub(a):
            s = self.stats[a]
            if s.count == 0:
                return float("inf")
            return s.mean + self.bias_factor * s.std_dev / math.sqrt(s.count)
        return max(self.actions, key=ub)


class SampsonSamplerLearner(MultiArmBanditLearner):
    """Thompson sampling from the per-action reward posterior
    (reinforce/SampsonSamplerLearner.java)."""
    name = "sampsonSampler"
    optimistic = False

    def next_action(self) -> str:
        self.total_trial_count += 1
        a = self._min_trial_action()
        if a:
            return a
        best, best_v = None, -float("inf")
        for act in self.actions:
            s = self.stats[act]
            if s.count == 0:
                v = float("inf") if not self.optimistic else 1e12
            else:
                v = sampson_sample(s.mean, s.std_dev or 1.0, s.count,
                                   self.rng.gauss(0.0, 1.0))
                if self.optimistic:
                    v = max(v, s.mean)
            if v > best_v:
                best, best_v = act, v
        return best


class OptimisticSampsonSamplerLearner(SampsonSamplerLearner):
    """Optimistic variant: sampled value floored at the observed mean
    (reinforce/OptimisticSampsonSamplerLearner.java)."""
    name = "optimisticSampsonSampler"
    optimistic = True


class RandomGreedyLearner(MultiArmBanditLearner):
    """epsilon-greedy with none/linear/logLinear epsilon decay and the Auer
    greedy variant (reinforce/RandomGreedyLearner.java:57-95,
    GreedyRandomBandit.java:150-205)."""
    name = "randomGreedy"
    PROB_RED_NONE = "none"
    PROB_RED_LINEAR = "linear"
    PROB_RED_LOG_LINEAR = "logLinear"

    AUER_GREEDY = "auerGreedy"

    def __init__(self, actions, config=None):
        super().__init__(actions, config)
        cfg = config or {}
        self.random_selection_prob = float(cfg.get("random.selection.prob", 0.1))
        self.prob_red_algorithm = cfg.get("prob.reduction.algorithm", "none")
        self.prob_red_constant = float(cfg.get("prob.reduction.constant", 1.0))
        self.auer_constant = float(cfg.get("auer.greedy.constant", 1.0))

    def _current_prob(self) -> float:
        if self.prob_red_algorithm == self.PROB_RED_NONE:
            p = self.random_selection_prob
        elif self.prob_red_algorithm == self.PROB_RED_LINEAR:
            p = self.random_selection_prob * self.prob_red_constant / \
                max(self.total_trial_count, 1)
        elif self.prob_red_algorithm == self.PROB_RED_LOG_LINEAR:
            t = max(self.total_trial_count, 2)
            p = self.random_selection_prob * self.prob_red_constant * \
                math.log(t) / t
        else:
            raise ValueError("Invalid probability reduction algorithms")
        return min(p, self.random_selection_prob)

    def next_action(self) -> str:
        self.total_trial_count += 1
        a = self._min_trial_action()
        if a:
            return a
        if self.prob_red_algorithm == self.AUER_GREEDY:
            return self._auer_next()
        if self.rng.random() < self._current_prob():
            return self._random()
        return self._greedy()

    def _auer_next(self) -> str:
        """Auer's epsilon_n = min(1, cK/(d^2 n)) with d the normalized gap
        between the two best rewards (GreedyRandomBandit.greedyAuerSelect
        :270-310; equal top rewards force exploration)."""
        means = sorted((self.stats[a].mean for a in self.actions), reverse=True)
        max_r, next_r = means[0], means[1] if len(means) > 1 else means[0]
        if max_r <= 0 or max_r == next_r:
            prob = 1.0
        else:
            d = (max_r - next_r) / max_r
            prob = min(1.0, self.auer_constant * len(self.actions) /
                       (d * d * max(self.total_trial_count, 1)))
        if self.rng.random() < prob:
            return self._random()
        return self._greedy()


class UpperConfidenceBoundOneLearner(MultiArmBanditLearner):
    """UCB1: mean + sqrt(2 ln N / n)
    (reinforce/UpperConfidenceBoundOneLearner.java)."""
    name = "ucb1"

    def next_action(self) -> str:
        self.total_trial_count += 1
        a = self._min_trial_action()
        if a:
            return a
        N = max(self.total_trial_count, 1)
        def ub(act):
            s = self.stats[act]
            if s.count == 0:
                return float("inf")
            return ucb1_upper_bound(s.mean, s.count, N)
        return max(self.actions, key=ub)


class UpperConfidenceBoundTwoLearner(MultiArmBanditLearner):
    """UCB2 with epoch lengths tau(r) = ceil((1+alpha)^r)
    (reinforce/UpperConfidenceBoundTwoLearner.java)."""
    name = "ucb2"

    def __init__(self, actions, config=None):
        super().__init__(actions, config)
        cfg = config or {}
        self.alpha = float(cfg.get("alpha", 0.1))
        self.epochs: Dict[str, int] = {a: 0 for a in actions}
        self.remaining = 0
        self.current: Optional[str] = None

    def _tau(self, r: int) -> int:
        return int(math.ceil((1 + self.alpha) ** r))

    def next_action(self) -> str:
        self.total_trial_count += 1
        if self.current is not None and self.remaining > 0:
            self.remaining -= 1
            return self.current
        N = max(self.total_trial_count, 2)
        def ub(act):
            s = self.stats[act]
            if s.count == 0:
                return float("inf")
            tau = self._tau(self.epochs[act])
            bonus = math.sqrt((1 + self.alpha) * math.log(math.e * N / tau)
                              / (2 * tau))
            return s.mean + bonus
        best = max(self.actions, key=ub)
        r = self.epochs[best]
        self.remaining = max(self._tau(r + 1) - self._tau(r) - 1, 0)
        self.epochs[best] = r + 1
        self.current = best
        return best

    def _extra_state(self):
        ep = ",".join(f"{a}:{self.epochs[a]}" for a in self.actions)
        return [f"#ucb2,{ep}"]

    def _load_extra(self, line):
        if line.startswith("#ucb2,"):
            for tok in line.split(",", 1)[1].split(","):
                a, r = tok.split(":")
                self.epochs[a] = int(r)


class SoftMaxLearner(MultiArmBanditLearner):
    """Boltzmann exploration: p ~ exp(mean / tempConstant)
    (reinforce/SoftMaxLearner.java:62-90)."""
    name = "softMax"

    def __init__(self, actions, config=None):
        super().__init__(actions, config)
        cfg = config or {}
        self.temp_constant = float(cfg.get("temp.constant", 0.1))

    def next_action(self) -> str:
        self.total_trial_count += 1
        a = self._min_trial_action()
        if a:
            return a
        probs = {}
        for act in self.actions:
            mean = self.stats[act].mean
            probs[act] = softmax_weight(mean, self.temp_constant)
        return self._sample_distr(probs)


class ActionPursuitLearner(MultiArmBanditLearner):
    """Pursuit: probability of the greedy action pursued toward 1
    (reinforce/ActionPursuitLearner.java)."""
    name = "actionPursuit"

    def __init__(self, actions, config=None):
        super().__init__(actions, config)
        cfg = config or {}
        self.learning_rate = float(cfg.get("learning.rate", 0.05))
        self.probs: Dict[str, float] = {a: 1.0 / len(actions) for a in actions}

    def next_action(self) -> str:
        self.total_trial_count += 1
        a = self._min_trial_action()
        if a:
            return a
        greedy = self._greedy()
        for act in self.actions:
            p = self.probs[act]
            if act == greedy:
                self.probs[act] = p + self.learning_rate * (1.0 - p)
            else:
                self.probs[act] = p - self.learning_rate * p
        return self._sample_distr(self.probs)

    def _extra_state(self):
        pr = ",".join(f"{a}:{self.probs[a]}" for a in self.actions)
        return [f"#pursuit,{pr}"]

    def _load_extra(self, line):
        if line.startswith("#pursuit,"):
            for tok in line.split(",", 1)[1].split(","):
                a, p = tok.split(":")
                self.probs[a] = float(p)


class RewardComparisonLearner(MultiArmBanditLearner):
    """Preference learning vs a moving reference reward; softmax over
    preferences (reinforce/RewardComparisonLearner.java)."""
    name = "rewardComparison"

    def __init__(self, actions, config=None):
        super().__init__(actions, config)
        cfg = config or {}
        self.pref_step = float(cfg.get("preference.step", 0.1))
        self.ref_step = float(cfg.get("reference.reward.step", 0.1))
        self.ref_reward = float(cfg.get("initial.reference.reward", 0.0))
        self.prefs: Dict[str, float] = {a: 0.0 for a in actions}

    def next_action(self) -> str:
        self.total_trial_count += 1
        a = self._min_trial_action()
        if a:
            return a
        probs = {a: math.exp(min(self.prefs[a], 700)) for a in self.actions}
        return self._sample_distr(probs)

    def set_reward(self, action: str, reward: float) -> None:
        super().set_reward(action, reward)
        self.prefs[action] += self.pref_step * (reward - self.ref_reward)
        self.ref_reward += self.ref_step * (reward - self.ref_reward)

    def _extra_state(self):
        pr = ",".join(f"{a}:{self.prefs[a]}" for a in self.actions)
        return [f"#prefs,{pr}", f"#refReward,{self.ref_reward}"]

    def _load_extra(self, line):
        if line.startswith("#prefs,"):
            for tok in line.split(",", 1)[1].split(","):
                a, p = tok.split(":")
                self.prefs[a] = float(p)
        elif line.startswith("#refReward,"):
            self.ref_reward = float(line.split(",")[1])


class ExponentialWeightLearner(MultiArmBanditLearner):
    """EXP3 (reinforce/ExponentialWeightLearner.java:56-90): sampling
    distribution (1-g) w/sum(w) + g/K; weight update
    w *= exp(g * (r/p) / K)."""
    name = "exponentialWeight"

    def __init__(self, actions, config=None):
        super().__init__(actions, config)
        cfg = config or {}
        self.distr_constant = float(cfg.get("distr.constant", 0.1))
        self.weights: Dict[str, float] = {a: 1.0 for a in actions}
        self.last_probs: Dict[str, float] = {a: 1.0 / len(actions)
                                             for a in actions}

    def _probs(self) -> Dict[str, float]:
        sw = sum(self.weights.values())
        K = len(self.actions)
        g = self.distr_constant
        return {a: (1 - g) * self.weights[a] / sw + g / K for a in self.actions}

    def next_action(self) -> str:
        self.total_trial_count += 1
        self.last_probs = self._probs()
        return self._sample_distr(self.last_probs)

    def set_reward(self, action: str, reward: float) -> None:
        super().set_reward(action, reward)
        K = len(self.actions)
        g = self.distr_constant
        p = max(self.last_probs.get(action, 1.0 / K), 1e-12)
        x = reward / p
        self.weights[action] *= math.exp(min(g * x / K, 700))

    def _extra_state(self):
        w = ",".join(f"{a}:{self.weights[a]}" for a in self.actions)
        return [f"#weights,{w}"]

    def _load_extra(self, line):
        if line.startswith("#weights,"):
            for tok in line.split(",", 1)[1].split(","):
                a, wv = tok.split(":")
                self.weights[a] = float(wv)


class ExponentialWeightExpertLearner(ExponentialWeightLearner):
    """EXP4 (reinforce/ExponentialWeightExpertLearner.java): expert advice
    vectors mixed by expert weights.  Experts are provided as a matrix of
    per-action probabilities via config 'experts' (list of lists); expert
    weights updated by the estimated reward of their advice."""
    name = "exponentialWeightExpert"

    def __init__(self, actions, config=None):
        super().__init__(actions, config)
        cfg = config or {}
        experts = cfg.get("experts")
        if experts is None:
            # default experts: one uniform + one per action (pure strategies)
            K = len(actions)
            experts = [[1.0 / K] * K]
            for i in range(K):
                experts.append([1.0 if j == i else 0.0 for j in range(K)])
        self.experts = [list(map(float, e)) for e in experts]
        self.expert_weights = [1.0] * len(self.experts)

    def _probs(self) -> Dict[str, float]:
        sw = sum(self.expert_weights)
        K = len(self.actions)
        g = self.distr_constant
        mixed = [0.0] * K
        for wi, advice in zip(self.expert_weights, self.experts):
            for j in range(K):
                mixed[j] += wi * advice[j] / sw
        return {a: (1 - g) * mixed[j] + g / K
                for j, a in enumerate(self.actions)}

    def set_reward(self, action: str, reward: float) -> None:
        MultiArmBanditLearner.set_reward(self, action, reward)
        K = len(self.actions)
        g = self.distr_constant
        j = self.actions.index(action)
        p = max(self.last_probs.get(action, 1.0 / K), 1e-12)
        xhat = reward / p
        for ei, advice in enumerate(self.experts):
            yhat = advice[j] * xhat
            self.expert_weights[ei] *= math.exp(min(g * yhat / K, 700))

    def _extra_state(self):
        w = ",".join(str(v) for v in self.expert_weights)
        return [f"#expertWeights,{w}"]

    def _load_extra(self, line):
        if line.startswith("#expertWeights,"):
            self.expert_weights = [float(v)
                                   for v in line.split(",", 1)[1].split(",")]


LEARNERS = {
    cls.name: cls for cls in [
        IntervalEstimatorLearner, SampsonSamplerLearner,
        OptimisticSampsonSamplerLearner, RandomGreedyLearner,
        UpperConfidenceBoundOneLearner, UpperConfidenceBoundTwoLearner,
        SoftMaxLearner, ActionPursuitLearner, RewardComparisonLearner,
        ExponentialWeightLearner, ExponentialWeightExpertLearner,
    ]
}


def create_learner(algorithm: str, actions: Sequence[str],
                   config: Optional[Dict] = None) -> MultiArmBanditLearner:
    """MultiArmBanditLearnerFactory.create (:30-41)."""
    cls = LEARNERS.get(algorithm)
    if cls is None:
        raise ValueError(f"unknown bandit algorithm {algorithm!r}; known: "
                         f"{sorted(LEARNERS)}")
    return cls(actions, config)


class ExplorationCounter:
    """Round-based exploration scheduling (reinforce/ExplorationCounter
    .java:27-118): a group of ``count`` items is force-explored for the
    first ``exploration_count`` selections, ``batch_size`` per round,
    sweeping item-index windows (wrapping at the group boundary) until the
    budget is spent."""

    def __init__(self, group_id: str, count: int, exploration_count: int,
                 batch_size: int):
        self.group_id = group_id
        self.count = count
        self.exploration_count = exploration_count
        self.batch_size = batch_size
        self.selections: List[Tuple[int, int]] = []

    def select_next_round(self, round_num: int) -> None:
        remaining = self.exploration_count - (round_num - 1) * self.batch_size
        self.selections = []
        if remaining > 0:
            beg = remaining % self.count
            end = beg + self.batch_size - 1
            if end >= self.count:  # batch wraps the item-set boundary
                self.selections.append((beg, self.count - 1))
                self.selections.append((0, end - self.count))
            else:
                self.selections.append((beg, end))

    def is_in_exploration(self) -> bool:
        return bool(self.selections)

    def should_explore(self, item_index: int) -> bool:
        return any(lo <= item_index <= hi for lo, hi in self.selections)
