"""Group-wise batch bandit decisioning + the vectorized device path.

Parity targets (SURVEY.md §2.6):
  * Spark MultiArmBandit (spark/.../reinforce/MultiArmBandit.scala:61-146):
    per group, build a learner from saved model state, apply reward
    feedback, emit a batch of actions, save state back out.  GroupedBandits
    is that combineByKey/cogroup flow with plain dicts.
  * Hadoop GreedyRandomBandit / SoftMaxBandit etc. batch jobs: covered by
    the same flow with the matching algorithm.
  * The device path (VectorBandits) is the TPU-native scale story: state as
    (groups, actions) arrays, one jitted pass selecting actions for every
    group at once — the reference's per-group JVM loops become gathers.

State file lines:   group,<learner state line>
Reward file lines:  group,action,reward
Action out lines:   group,action[,action...]
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .learners import MultiArmBanditLearner, create_learner


class GroupedBandits:
    def __init__(self, algorithm: str, actions: Sequence[str],
                 config: Optional[Dict] = None):
        self.algorithm = algorithm
        self.actions = list(actions)
        self.config = dict(config or {})
        self.learners: Dict[str, MultiArmBanditLearner] = {}

    def learner(self, group: str) -> MultiArmBanditLearner:
        if group not in self.learners:
            cfg = dict(self.config)
            if cfg.get("random.seed") is not None:
                # distinct deterministic stream per group: string seeds hash
                # via sha512 inside random.Random — stable across processes
                # (builtin hash() is salted per process and must not be used)
                cfg["random.seed"] = f"{cfg['random.seed']}:{group}"
            self.learners[group] = create_learner(self.algorithm, self.actions,
                                                  cfg)
        return self.learners[group]

    # ---- state round trip (MultiArmBandit.scala:57-58,133-146) ----
    def load_state(self, lines: Sequence[str], delim: str = ",") -> None:
        per_group: Dict[str, List[str]] = {}
        for line in lines:
            group, _, rest = line.partition(delim)
            per_group.setdefault(group, []).append(rest)
        for group, state in per_group.items():
            learner = self.learner(group)
            learner.build_model(state)
            # advance the per-group stream past prior rounds so a restarted
            # job doesn't replay the identical random draws each round
            trials = sum(s.count for s in learner.stats.values())
            learner.total_trial_count = max(learner.total_trial_count, trials)
            if self.config.get("random.seed") is not None:
                learner.rng.seed(
                    f"{self.config['random.seed']}:{group}:{trials}")

    def save_state(self, delim: str = ",") -> List[str]:
        out = []
        for group in sorted(self.learners):
            for line in self.learners[group].get_model():
                out.append(f"{group}{delim}{line}")
        return out

    # ---- reward feedback ----
    def apply_rewards(self, lines: Sequence[str], delim: str = ",") -> None:
        for line in lines:
            group, action, reward = line.split(delim)[:3]
            self.learner(group).set_reward(action, float(reward))

    # ---- decisions ----
    def next_actions(self, groups: Optional[Sequence[str]] = None,
                     delim: str = ",") -> List[str]:
        groups = list(groups) if groups is not None else sorted(self.learners)
        out = []
        for g in groups:
            acts = self.learner(g).next_actions()
            out.append(delim.join([g] + acts))
        return out


class VectorBandits:
    """Device-vectorized bandits over (groups, actions) state arrays —
    ALL 11 factory algorithms (MultiArmBanditLearnerFactory.java:30-41).
    One jitted call selects an action for every group simultaneously; the
    stateful algorithms (ucb2 epochs, pursuit probabilities, exp3/exp4
    weights, rewardComparison preferences) carry their extra state as
    (G, A)/(G, E) arrays updated by the same call or by ``set_rewards``.

    This is the scale path (the reference's per-group JVM loops become one
    array program); it shares algorithm structure, not RNG streams, with
    the scalar ``learners`` module.  Reward updates that are order
    -sensitive within a batch (rewardComparison's moving reference, exp3/
    exp4's importance weights) are applied in event order on host —
    selection is the per-round hot path, updates are O(batch).
    """

    ALGORITHMS = ("randomGreedy", "ucb1", "ucb2", "softMax",
                  "sampsonSampler", "optimisticSampsonSampler",
                  "intervalEstimator", "actionPursuit", "rewardComparison",
                  "exponentialWeight", "exponentialWeightExpert")

    def __init__(self, algorithm: str, n_groups: int, n_actions: int,
                 config: Optional[Dict] = None, seed: int = 0):
        if algorithm not in self.ALGORITHMS:
            raise ValueError(f"unknown bandit algorithm {algorithm!r}; "
                             f"known: {sorted(self.ALGORITHMS)}")
        self.algorithm = algorithm
        cfg = config or {}
        self.G, self.A = G, A = n_groups, n_actions
        self.counts = np.zeros((G, A), dtype=np.float32)
        self.sums = np.zeros((G, A), dtype=np.float32)
        self.sum_sqs = np.zeros((G, A), dtype=np.float32)
        self.epsilon = float(cfg.get("random.selection.prob", 0.1))
        self.temp = float(cfg.get("temp.constant", 0.1))
        self.bias = float(cfg.get("confidence.factor", 2.0))
        self.alpha = float(cfg.get("alpha", 0.1))
        self.learning_rate = float(cfg.get("learning.rate", 0.05))
        self.pref_step = float(cfg.get("preference.step", 0.1))
        self.ref_step = float(cfg.get("reference.reward.step", 0.1))
        self.distr_constant = float(cfg.get("distr.constant", 0.1))
        # per-algorithm extra state
        if algorithm == "ucb2":
            self.epochs = np.zeros((G, A), dtype=np.float32)
            self.remaining = np.zeros((G,), dtype=np.float32)
            self.current = np.zeros((G,), dtype=np.int32)
            # per-group trial counter: like the scalar learner's
            # total_trial_count, N grows with SELECTIONS, not rewards, so
            # tau can never outrun e*N under delayed feedback
            self.trials = np.zeros((G,), dtype=np.float32)
        elif algorithm == "actionPursuit":
            self.probs = np.full((G, A), 1.0 / A, dtype=np.float32)
        elif algorithm == "rewardComparison":
            self.prefs = np.zeros((G, A), dtype=np.float32)
            self.ref_reward = np.full(
                (G,), float(cfg.get("initial.reference.reward", 0.0)),
                dtype=np.float32)
        elif algorithm == "exponentialWeight":
            self.weights = np.ones((G, A), dtype=np.float32)
            self.last_probs = np.full((G, A), 1.0 / A, dtype=np.float32)
        elif algorithm == "exponentialWeightExpert":
            experts = cfg.get("experts")
            if experts is None:  # same default panel as the scalar learner
                experts = [[1.0 / A] * A]
                experts += [[1.0 if j == i else 0.0 for j in range(A)]
                            for i in range(A)]
            self.experts = np.asarray(experts, dtype=np.float32)   # (E, A)
            self.expert_weights = np.ones((G, self.experts.shape[0]),
                                          dtype=np.float32)
            self.last_probs = np.full((G, A), 1.0 / A, dtype=np.float32)
        self.key = jax.random.PRNGKey(seed)
        self._select = jax.jit(self._make_select())

    def _make_select(self):
        algo = self.algorithm
        eps, temp, bias = self.epsilon, self.temp, self.bias
        alpha, lr, g = self.alpha, self.learning_rate, self.distr_constant

        def posterior_sample(key, counts, sums, sum_sqs):
            mean = sums / jnp.maximum(counts, 1.0)
            var = (sum_sqs - counts * mean * mean) / \
                jnp.maximum(counts - 1.0, 1.0)
            sd = jnp.sqrt(jnp.maximum(var, 1e-12))
            z = jax.random.normal(key, counts.shape)
            return mean, mean + z * sd / jnp.sqrt(jnp.maximum(counts, 1.0))

        def select(key, counts, sums, sum_sqs, extra):
            mean = sums / jnp.maximum(counts, 1.0)
            untried = counts == 0
            if algo == "randomGreedy":
                k1, k2 = jax.random.split(key)
                greedy = jnp.argmax(jnp.where(untried, jnp.inf, mean), axis=1)
                rand = jax.random.randint(k1, (counts.shape[0],), 0,
                                          counts.shape[1])
                explore = jax.random.uniform(k2, (counts.shape[0],)) < eps
                return jnp.where(explore, rand, greedy), ()
            if algo == "ucb1":
                N = jnp.maximum(counts.sum(axis=1, keepdims=True), 1.0)
                ub = mean + jnp.sqrt(2.0 * jnp.log(N) /
                                     jnp.maximum(counts, 1.0))
                return jnp.argmax(jnp.where(untried, jnp.inf, ub), axis=1), ()
            if algo == "ucb2":
                # epoch-committed UCB (UpperConfidenceBoundTwoLearner):
                # while remaining > 0 replay the committed arm; else pick by
                # the (1+a) bonus and commit for tau(r+1)-tau(r)-1 rounds.
                # N counts SELECTIONS (the scalar learner's
                # total_trial_count) and the log argument is clamped >= 1,
                # so delayed rewards can never drive the bonus NaN.
                epochs, remaining, current, trials = extra
                tau = jnp.ceil((1 + alpha) ** epochs)
                N = jnp.maximum(trials, 2.0)[:, None]
                bonus = jnp.sqrt((1 + alpha) *
                                 jnp.log(jnp.maximum(jnp.e * N / tau, 1.0))
                                 / (2.0 * tau))
                ub = jnp.where(untried, jnp.inf, mean + bonus)
                best = jnp.argmax(ub, axis=1).astype(jnp.int32)
                sticky = remaining > 0
                action = jnp.where(sticky, current, best)
                r_best = jnp.take_along_axis(
                    epochs, best[:, None], axis=1)[:, 0]
                span = jnp.ceil((1 + alpha) ** (r_best + 1)) - \
                    jnp.ceil((1 + alpha) ** r_best) - 1.0
                new_remaining = jnp.where(sticky, remaining - 1.0,
                                          jnp.maximum(span, 0.0))
                bump = jax.nn.one_hot(best, counts.shape[1],
                                      dtype=jnp.float32) * \
                    (~sticky)[:, None].astype(jnp.float32)
                return action, (epochs + bump, new_remaining,
                                action.astype(jnp.int32), trials + 1.0)
            if algo == "softMax":
                return jax.random.categorical(key, mean / temp, axis=1), ()
            if algo in ("sampsonSampler", "optimisticSampsonSampler"):
                mean, sample = posterior_sample(key, counts, sums, sum_sqs)
                if algo == "optimisticSampsonSampler":
                    sample = jnp.maximum(sample, mean)  # floored at the mean
                return jnp.argmax(jnp.where(untried, jnp.inf, sample),
                                  axis=1), ()
            if algo == "intervalEstimator":
                var = (sum_sqs - counts * mean * mean) / \
                    jnp.maximum(counts - 1.0, 1.0)
                sd = jnp.sqrt(jnp.maximum(var, 0.0))
                ub = mean + bias * sd / jnp.sqrt(jnp.maximum(counts, 1.0))
                return jnp.argmax(jnp.where(untried, jnp.inf, ub), axis=1), ()
            if algo == "actionPursuit":
                # pursue the greedy arm toward probability 1, then sample
                (probs,) = extra
                greedy = jnp.argmax(jnp.where(untried, jnp.inf, mean), axis=1)
                oh = jax.nn.one_hot(greedy, counts.shape[1],
                                    dtype=jnp.float32)
                new_probs = probs + lr * (oh - probs)
                action = jax.random.categorical(
                    key, jnp.log(jnp.maximum(new_probs, 1e-30)), axis=1)
                return action, (new_probs,)
            if algo == "rewardComparison":
                # softmax over preferences (prefs updated in set_rewards)
                (prefs,) = extra
                return jax.random.categorical(
                    key, jnp.minimum(prefs, 700.0), axis=1), ()
            if algo == "exponentialWeight":
                (weights,) = extra
                sw = weights.sum(axis=1, keepdims=True)
                K = counts.shape[1]
                probs = (1 - g) * weights / sw + g / K
                action = jax.random.categorical(key, jnp.log(probs), axis=1)
                return action, (probs,)
            if algo == "exponentialWeightExpert":
                expert_weights, experts = extra
                sw = expert_weights.sum(axis=1, keepdims=True)
                mixed = (expert_weights / sw) @ experts          # (G, A)
                K = counts.shape[1]
                probs = (1 - g) * mixed + g / K
                action = jax.random.categorical(key, jnp.log(probs), axis=1)
                return action, (probs,)
            raise ValueError(f"algorithm {algo!r} has no vectorized form")

        return select

    def _extra(self):
        a = self.algorithm
        if a == "ucb2":
            return (jnp.asarray(self.epochs), jnp.asarray(self.remaining),
                    jnp.asarray(self.current), jnp.asarray(self.trials))
        if a == "actionPursuit":
            return (jnp.asarray(self.probs),)
        if a == "rewardComparison":
            return (jnp.asarray(self.prefs),)
        if a == "exponentialWeight":
            return (jnp.asarray(self.weights),)
        if a == "exponentialWeightExpert":
            return (jnp.asarray(self.expert_weights),
                    jnp.asarray(self.experts))
        return ()

    def next_actions(self) -> np.ndarray:
        """(G,) action indices for every group."""
        self.key, sub = jax.random.split(self.key)
        action, new_extra = self._select(
            sub, jnp.asarray(self.counts), jnp.asarray(self.sums),
            jnp.asarray(self.sum_sqs), self._extra())
        a = self.algorithm
        if a == "ucb2":
            self.epochs, self.remaining, self.current, self.trials = \
                (np.asarray(x) for x in new_extra)
        elif a == "actionPursuit":
            self.probs = np.asarray(new_extra[0])
        elif a in ("exponentialWeight", "exponentialWeightExpert"):
            self.last_probs = np.asarray(new_extra[0])
        return np.asarray(action)

    def set_rewards(self, group_idx: np.ndarray, action_idx: np.ndarray,
                    rewards: np.ndarray) -> None:
        np.add.at(self.counts, (group_idx, action_idx), 1.0)
        np.add.at(self.sums, (group_idx, action_idx), rewards)
        np.add.at(self.sum_sqs, (group_idx, action_idx), rewards ** 2)
        a = self.algorithm
        if a == "rewardComparison":
            # moving reference: order within the batch matters, like the
            # scalar learner's per-event updates
            for gi, ai, r in zip(group_idx, action_idx, rewards):
                delta = r - self.ref_reward[gi]
                self.prefs[gi, ai] += self.pref_step * delta
                self.ref_reward[gi] += self.ref_step * delta
        elif a == "exponentialWeight":
            g, K = self.distr_constant, self.A
            for gi, ai, r in zip(group_idx, action_idx, rewards):
                p = max(float(self.last_probs[gi, ai]), 1e-12)
                self.weights[gi, ai] *= np.exp(min(g * (r / p) / K, 60.0))
            # EXP3 sampling is invariant under per-group weight scaling;
            # renormalize so f32 weights can never overflow to inf over a
            # long serving run (they otherwise hit inf in ~2.5k rounds)
            self.weights /= np.maximum(
                self.weights.max(axis=1, keepdims=True), 1e-30)
        elif a == "exponentialWeightExpert":
            g, K = self.distr_constant, self.A
            for gi, ai, r in zip(group_idx, action_idx, rewards):
                p = max(float(self.last_probs[gi, ai]), 1e-12)
                xhat = r / p
                yhat = self.experts[:, ai] * xhat                # (E,)
                self.expert_weights[gi] *= np.exp(
                    np.minimum(g * yhat / K, 60.0))
            self.expert_weights /= np.maximum(
                self.expert_weights.max(axis=1, keepdims=True), 1e-30)
