"""Group-wise batch bandit decisioning + the vectorized device path.

Parity targets (SURVEY.md §2.6):
  * Spark MultiArmBandit (spark/.../reinforce/MultiArmBandit.scala:61-146):
    per group, build a learner from saved model state, apply reward
    feedback, emit a batch of actions, save state back out.  GroupedBandits
    is that combineByKey/cogroup flow with plain dicts.
  * Hadoop GreedyRandomBandit / SoftMaxBandit etc. batch jobs: covered by
    the same flow with the matching algorithm.
  * The device path (VectorBandits) is the TPU-native scale story: state as
    (groups, actions) arrays, one jitted pass selecting actions for every
    group at once — the reference's per-group JVM loops become gathers.

State file lines:   group,<learner state line>
Reward file lines:  group,action,reward
Action out lines:   group,action[,action...]
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .learners import MultiArmBanditLearner, create_learner


class GroupedBandits:
    def __init__(self, algorithm: str, actions: Sequence[str],
                 config: Optional[Dict] = None):
        self.algorithm = algorithm
        self.actions = list(actions)
        self.config = dict(config or {})
        self.learners: Dict[str, MultiArmBanditLearner] = {}

    def learner(self, group: str) -> MultiArmBanditLearner:
        if group not in self.learners:
            cfg = dict(self.config)
            if cfg.get("random.seed") is not None:
                # distinct deterministic stream per group: string seeds hash
                # via sha512 inside random.Random — stable across processes
                # (builtin hash() is salted per process and must not be used)
                cfg["random.seed"] = f"{cfg['random.seed']}:{group}"
            self.learners[group] = create_learner(self.algorithm, self.actions,
                                                  cfg)
        return self.learners[group]

    # ---- state round trip (MultiArmBandit.scala:57-58,133-146) ----
    def load_state(self, lines: Sequence[str], delim: str = ",") -> None:
        per_group: Dict[str, List[str]] = {}
        for line in lines:
            group, _, rest = line.partition(delim)
            per_group.setdefault(group, []).append(rest)
        for group, state in per_group.items():
            learner = self.learner(group)
            learner.build_model(state)
            # advance the per-group stream past prior rounds so a restarted
            # job doesn't replay the identical random draws each round
            trials = sum(s.count for s in learner.stats.values())
            learner.total_trial_count = max(learner.total_trial_count, trials)
            if self.config.get("random.seed") is not None:
                learner.rng.seed(
                    f"{self.config['random.seed']}:{group}:{trials}")

    def save_state(self, delim: str = ",") -> List[str]:
        out = []
        for group in sorted(self.learners):
            for line in self.learners[group].get_model():
                out.append(f"{group}{delim}{line}")
        return out

    # ---- reward feedback ----
    def apply_rewards(self, lines: Sequence[str], delim: str = ",") -> None:
        for line in lines:
            group, action, reward = line.split(delim)[:3]
            self.learner(group).set_reward(action, float(reward))

    # ---- decisions ----
    def next_actions(self, groups: Optional[Sequence[str]] = None,
                     delim: str = ",") -> List[str]:
        groups = list(groups) if groups is not None else sorted(self.learners)
        out = []
        for g in groups:
            acts = self.learner(g).next_actions()
            out.append(delim.join([g] + acts))
        return out


class VectorBandits:
    """Device-vectorized bandits over (groups, actions) state arrays.

    Supported algorithms (the ones whose selection is a pure array op):
    randomGreedy (epsilon-greedy), ucb1, softMax, sampsonSampler (gaussian
    Thompson), intervalEstimator.  One jitted call selects an action for
    every group simultaneously.
    """

    def __init__(self, algorithm: str, n_groups: int, n_actions: int,
                 config: Optional[Dict] = None, seed: int = 0):
        self.algorithm = algorithm
        cfg = config or {}
        self.G, self.A = n_groups, n_actions
        self.counts = np.zeros((n_groups, n_actions), dtype=np.float32)
        self.sums = np.zeros((n_groups, n_actions), dtype=np.float32)
        self.sum_sqs = np.zeros((n_groups, n_actions), dtype=np.float32)
        self.epsilon = float(cfg.get("random.selection.prob", 0.1))
        self.temp = float(cfg.get("temp.constant", 0.1))
        self.bias = float(cfg.get("confidence.factor", 2.0))
        self.key = jax.random.PRNGKey(seed)
        self._select = jax.jit(self._make_select())

    def _make_select(self):
        algo = self.algorithm
        eps, temp, bias = self.epsilon, self.temp, self.bias

        def select(key, counts, sums, sum_sqs):
            mean = sums / jnp.maximum(counts, 1.0)
            untried = counts == 0
            if algo == "randomGreedy":
                k1, k2 = jax.random.split(key)
                greedy = jnp.argmax(jnp.where(untried, jnp.inf, mean), axis=1)
                rand = jax.random.randint(k1, (counts.shape[0],), 0,
                                          counts.shape[1])
                explore = jax.random.uniform(k2, (counts.shape[0],)) < eps
                return jnp.where(explore, rand, greedy)
            if algo == "ucb1":
                N = jnp.maximum(counts.sum(axis=1, keepdims=True), 1.0)
                ub = mean + jnp.sqrt(2.0 * jnp.log(N) /
                                     jnp.maximum(counts, 1.0))
                return jnp.argmax(jnp.where(untried, jnp.inf, ub), axis=1)
            if algo == "softMax":
                logits = mean / temp
                return jax.random.categorical(key, logits, axis=1)
            if algo == "sampsonSampler":
                var = (sum_sqs - counts * mean * mean) / \
                    jnp.maximum(counts - 1.0, 1.0)
                sd = jnp.sqrt(jnp.maximum(var, 1e-12))
                z = jax.random.normal(key, counts.shape)
                sample = mean + z * sd / jnp.sqrt(jnp.maximum(counts, 1.0))
                return jnp.argmax(jnp.where(untried, jnp.inf, sample), axis=1)
            if algo == "intervalEstimator":
                var = (sum_sqs - counts * mean * mean) / \
                    jnp.maximum(counts - 1.0, 1.0)
                sd = jnp.sqrt(jnp.maximum(var, 0.0))
                ub = mean + bias * sd / jnp.sqrt(jnp.maximum(counts, 1.0))
                return jnp.argmax(jnp.where(untried, jnp.inf, ub), axis=1)
            raise ValueError(f"algorithm {algo!r} has no vectorized form")

        return select

    def next_actions(self) -> np.ndarray:
        """(G,) action indices for every group."""
        self.key, sub = jax.random.split(self.key)
        return np.asarray(self._select(sub, jnp.asarray(self.counts),
                                       jnp.asarray(self.sums),
                                       jnp.asarray(self.sum_sqs)))

    def set_rewards(self, group_idx: np.ndarray, action_idx: np.ndarray,
                    rewards: np.ndarray) -> None:
        np.add.at(self.counts, (group_idx, action_idx), 1.0)
        np.add.at(self.sums, (group_idx, action_idx), rewards)
        np.add.at(self.sum_sqs, (group_idx, action_idx), rewards ** 2)
