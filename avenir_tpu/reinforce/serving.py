"""Online bandit serving loop: the Storm topology, in-process.

Parity target (SURVEY.md §2.6, §3.5): storm/ReinforcementLearnerTopology
.java:46-87 + ReinforcementLearnerBolt.java:97-135 — a spout feeding event
and reward messages from Redis queues into a bolt wrapping any factory
learner, actions written back to an action queue.

Two transports share the same message semantics:
  * in-process queue.Queue (ReinforcementLearnerService.start) — unit
    tests and single-process demos;
  * the wire (RedisServingLoop): Redis-list queues polled exactly like
    the reference spout (``rpop`` event/reward queues, actions
    ``lpush``ed — RedisSpout.java:83-95, RedisActionWriter.java:47-61),
    against io/respq.RespServer or a real Redis, with the reference's
    config keys (redis.server.host/port, redis.event.queue,
    redis.reward.queue, redis.action.queue).

Message formats:
  event:  'round,<roundNum>'  -> respond with next_actions on action queue
  reward: 'reward,<action>,<value>' -> learner.set_reward
Processing is synchronous per message like the bolt's execute()."""

from __future__ import annotations

import queue
import threading
from typing import Dict, Optional, Sequence

import numpy as np

from .learners import create_learner


class ReinforcementLearnerService:
    def __init__(self, algorithm: str, actions: Sequence[str],
                 config: Optional[Dict] = None):
        self.learner = create_learner(algorithm, actions, config)
        self.event_queue: "queue.Queue[str]" = queue.Queue()
        self.action_queue: "queue.Queue[str]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.delim = ","

    # ---- the bolt's execute() (:97-135) ----
    def process(self, message: str) -> Optional[str]:
        parts = message.split(self.delim)
        if parts[0] == "round":
            actions = self.learner.next_actions()
            out = self.delim.join([parts[1]] + actions)
            self.action_queue.put(out)
            return out
        if parts[0] == "reward":
            self.learner.set_reward(parts[1], float(parts[2]))
            return None
        raise ValueError(f"unknown message type {parts[0]!r}")

    # ---- async loop (the topology submit) ----
    def start(self) -> None:
        def loop():
            while not self._stop.is_set():
                try:
                    msg = self.event_queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                self.process(msg)
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class VectorLearnerService:
    """Many-group real-time serving over the device-vectorized path: where
    the reference topology distributes one bolt per learner across Storm
    workers, one instance here serves EVERY group per round message with a
    single jitted selection (reinforce/batch.VectorBandits, all 11
    algorithms).  Action names map through ``actions`` like the scalar
    service.

    Messages:
      event:  'round,<roundNum>' -> one '<roundNum>,<group>,<action>' line
              per group on the action queue (returned joined by newlines)
      reward: 'reward,<groupIdx>,<action>,<value>'
    """

    def __init__(self, algorithm: str, actions: Sequence[str],
                 n_groups: int, config: Optional[Dict] = None,
                 seed: int = 0):
        from .batch import VectorBandits
        self.actions = list(actions)
        self.bandits = VectorBandits(algorithm, n_groups, len(self.actions),
                                     config, seed=seed)
        self.action_queue: "queue.Queue[str]" = queue.Queue()
        self.delim = ","

    def process(self, message: str) -> Optional[str]:
        parts = message.split(self.delim)
        if parts[0] == "round":
            acts = self.bandits.next_actions()
            lines = [self.delim.join([parts[1], str(g), self.actions[a]])
                     for g, a in enumerate(acts)]
            out = "\n".join(lines)
            for line in lines:
                self.action_queue.put(line)
            return out
        if parts[0] == "reward":
            g = np.array([int(parts[1])])
            a = np.array([self.actions.index(parts[2])])
            r = np.array([float(parts[3])], dtype=np.float32)
            self.bandits.set_rewards(g, a, r)
            return None
        raise ValueError(f"unknown message type {parts[0]!r}")


class RedisServingLoop:
    """The Storm topology over the wire: poll the event and reward queues
    (``rpop``, event queue first like RedisSpout.nextSpoutMessage), feed
    each message through the wrapped service's bolt-execute, and ``lpush``
    action responses — the reference's RedisSpout/RedisActionWriter
    contract against io/respq.RespServer or a real Redis.

    ``config`` uses the reference key names: redis.server.host,
    redis.server.port, redis.event.queue, redis.reward.queue,
    redis.action.queue.  A literal 'stop' message on the event queue ends
    :meth:`run` (transport-level control, not part of the bolt contract).

    The transport comes from :func:`io.respq.make_queue_client` — the
    same factory the serving fleet uses — so the loop inherits its
    config surface: ``redis.server.endpoints`` listing M shards drains
    through the consistent-hash ring, single host/port keeps the plain
    client, byte for byte the old behavior.
    """

    def __init__(self, service, config: Optional[Dict] = None):
        from ..io.respq import make_queue_client
        cfg = dict(config or {})
        self.service = service
        self.client = make_queue_client(cfg)
        self.event_q = cfg.get("redis.event.queue", "eventQueue")
        self.reward_q = cfg.get("redis.reward.queue", "rewardQueue")
        self.action_q = cfg.get("redis.action.queue", "actionQueue")
        self.stopped = False

    def poll_once(self) -> bool:
        """One spout pass; returns True if a message was processed."""
        msg = self.client.rpop(self.event_q)
        if msg is not None:
            if msg == "stop":
                # drain queued rewards first: the client pushes its final
                # rewards before 'stop', and dropping them would silently
                # lose learner updates on every shutdown
                while True:
                    r = self.client.rpop(self.reward_q)
                    if r is None:
                        break
                    self.service.process(r)
                self.stopped = True
                return True
            out = self.service.process(msg)
            if out is not None:
                self.client.lpush(self.action_q, out)
            return True
        msg = self.client.rpop(self.reward_q)
        if msg is not None:
            self.service.process(msg)
            return True
        return False

    def run(self, max_idle_s: float = 30.0, idle_sleep_s: float = 0.005
            ) -> None:
        """Poll until a 'stop' message or ``max_idle_s`` without traffic."""
        import time
        idle_since = time.monotonic()
        while not self.stopped:
            if self.poll_once():
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since > max_idle_s:
                break
            else:
                time.sleep(idle_sleep_s)

    def close(self) -> None:
        self.client.close()
