"""Device-state forms of the host bandit learners (ISSUE 19).

The host learners (:mod:`.learners`) keep per-arm statistics in python
objects and decide one action at a time; the online learning plane
(:mod:`avenir_tpu.online`) keeps the SAME statistics as three ``(A,)``
``float32`` arrays living in a donated pipeline carry and scores a whole
served window in one fused program.  The scoring math is not
re-implemented here: each form calls the shared bodies exported by
:mod:`.learners` (``ucb1_upper_bound`` / ``softmax_weight`` /
``sampson_sample``) with ``jnp`` callables, so the host decision path
and the device window path are the same formula by construction — the
parity tests pin it bit for bit on float32 inputs.

Randomized selection (softMax, sampsonSampler) threads a
``jax.random.PRNGKey`` supplied by the caller; the deterministic score
body stays shared while each side owns its randomness (the host side
draws from ``random.Random``).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .learners import sampson_sample, softmax_weight, ucb1_upper_bound

# the device-resident subset of the factory's algorithm names
ONLINE_ALGORITHMS = ("ucb1", "softMax", "sampsonSampler")


def init_arm_stats(n_arms: int) -> Dict[str, np.ndarray]:
    """Fresh per-arm statistics: count / reward sum / reward sum-sq
    (ActionStat's three fields, vectorized)."""
    return {
        "counts": np.zeros(n_arms, np.float32),
        "totals": np.zeros(n_arms, np.float32),
        "total_sqs": np.zeros(n_arms, np.float32),
    }


def arm_means(counts, totals):
    import jax.numpy as jnp
    return totals / jnp.maximum(counts, 1.0)


def arm_sigmas(counts, totals, total_sqs):
    """ActionStat.std_dev vectorized, with the sampsonSampler's
    ``std_dev or 1.0`` floor folded in (a no-variance arm samples at
    unit sigma, exactly the host rule)."""
    import jax.numpy as jnp
    mean = arm_means(counts, totals)
    var = (total_sqs - counts * mean * mean) / jnp.maximum(counts - 1.0,
                                                           1.0)
    sd = jnp.sqrt(jnp.maximum(var, 0.0))
    return jnp.where(sd > 0.0, sd, 1.0)


def bandit_scores(algorithm: str, counts, totals, total_sqs, key,
                  n_rows: int, temp_constant: float = 0.1):
    """Per-row selection scores ``(n_rows, A)``; the chosen arm of row i
    is ``argmax(scores[i])``.  Untried arms score +inf — the host
    learners' try-everything-once rule."""
    import jax
    import jax.numpy as jnp
    mean = arm_means(counts, totals)
    untried = counts < 0.5
    if algorithm == "ucb1":
        N = jnp.maximum(counts.sum(), 1.0)
        ub = ucb1_upper_bound(mean, jnp.maximum(counts, 1.0), N,
                              log=jnp.log, sqrt=jnp.sqrt)
        scores = jnp.where(untried, jnp.inf, ub)
        return jnp.broadcast_to(scores, (n_rows, counts.shape[0]))
    if algorithm == "softMax":
        w = softmax_weight(mean, temp_constant, exp=jnp.exp,
                           minimum=jnp.minimum)
        # Gumbel-max draws each row ~ w/sum(w) — the same Boltzmann
        # distribution _sample_distr walks on the host
        g = jax.random.gumbel(key, (n_rows, counts.shape[0]),
                              dtype=mean.dtype)
        scores = jnp.log(w)[None, :] + g
        return jnp.where(untried[None, :], jnp.inf, scores)
    if algorithm == "sampsonSampler":
        sigma = arm_sigmas(counts, totals, total_sqs)
        z = jax.random.normal(key, (n_rows, counts.shape[0]),
                              dtype=mean.dtype)
        draw = sampson_sample(mean[None, :], sigma[None, :],
                              jnp.maximum(counts, 1.0)[None, :], z,
                              sqrt=jnp.sqrt)
        return jnp.where(untried[None, :], jnp.inf, draw)
    raise ValueError(f"algorithm {algorithm!r} has no device form; "
                     f"known: {ONLINE_ALGORITHMS}")


def absorb_rewards(counts, totals, total_sqs, arms, rewards, mask):
    """ActionStat.add vectorized over a padded reward batch: masked
    scatter-add into the three arm arrays (duplicate arms accumulate —
    many rewards for one arm in one window all land)."""
    w = mask.astype(counts.dtype)
    r = rewards.astype(counts.dtype) * w
    counts = counts.at[arms].add(w)
    totals = totals.at[arms].add(r)
    total_sqs = total_sqs.at[arms].add(rewards.astype(counts.dtype) * r)
    return counts, totals, total_sqs
