"""Mutual information distributions + feature-selection scores.

Capability parity with explore/MutualInformation.java (SURVEY.md §2.4): one
pass computes class / feature / feature-pair / feature-class / pair-class
distributions, then entropies, mutual informations (natural log, matching
Math.log at MutualInformation.java:730,765,813) and the selection scores of
explore/MutualInformationScore.java:

  * MIM   — rank by I(X;C)                                   (:98)
  * MIFS  — greedy I(X;C) - beta * sum I(X;X_sel)            (:116)
  * JMI   — greedy sum I(X,X_sel;C)                          (:177)
  * DISR  — greedy sum I(X,X_sel;C)/H(X,X_sel,C)             (:185)
  * mRMR  — greedy I(X;C) - mean I(X;X_sel)                  (:265)

TPU design: ALL pairwise joint histograms in one einsum over the per-feature
one-hot tensor — counts[i,j,b,d] = sum_n oh[n,i,b] oh[n,j,d] (and the
class-augmented variant) — instead of the reference's per-pair shuffle keys.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.table import ColumnarTable
from ..parallel.mesh import MeshContext, runtime_context


def _entropy(p: np.ndarray) -> float:
    """Natural-log entropy over the flattened distribution."""
    p = p[p > 0]
    return float(-(p * np.log(p)).sum())


def _mi(joint: np.ndarray, pa: np.ndarray, pb: np.ndarray) -> float:
    """I(A;B) = sum p(a,b) ln(p(a,b)/(p(a)p(b)))."""
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = joint / (pa[:, None] * pb[None, :])
        term = np.where(joint > 0, joint * np.log(np.where(joint > 0, ratio, 1.0)),
                        0.0)
    return float(term.sum())


@dataclass
class MutualInfoStats:
    feature_ordinals: List[int]
    class_values: List[str]
    # distributions (normalized)
    class_p: np.ndarray                      # (C,)
    feature_p: np.ndarray                    # (F, B)  (padded bins are 0)
    feature_class_p: np.ndarray              # (F, B, C)
    pair_p: np.ndarray                       # (F, F, B, B)
    pair_class_p: np.ndarray                 # (F, F, B, B, C)
    num_bins: List[int]

    # ---- entropies / MI ----
    def class_entropy(self) -> float:
        return _entropy(self.class_p)

    def feature_entropy(self, fi: int) -> float:
        return _entropy(self.feature_p[fi])

    def feature_class_mi(self, fi: int) -> float:
        return _mi(self.feature_class_p[fi], self.feature_p[fi], self.class_p)

    def pair_mi(self, fi: int, fj: int) -> float:
        return _mi(self.pair_p[fi, fj], self.feature_p[fi], self.feature_p[fj])

    def pair_class_mi(self, fi: int, fj: int) -> float:
        """I(Xi,Xj;C): joint (B*B, C) vs marginal pair dist and class dist."""
        joint = self.pair_class_p[fi, fj].reshape(-1, len(self.class_p))
        pair = self.pair_p[fi, fj].reshape(-1)
        return _mi(joint, pair, self.class_p)

    def pair_class_entropy(self, fi: int, fj: int) -> float:
        """H(Xi,Xj,C) (MutualInformation.java:815)."""
        return _entropy(self.pair_class_p[fi, fj])


@partial(jax.jit, static_argnums=(3, 4))
def _mi_kernel(bc, cc, m, B, C):
    """All MI distributions for one row chunk — module-level jit keyed on
    (shapes, B, C) so repeat compute_stats calls share one compiled
    program instead of recompiling per call."""
    mf = m.astype(jnp.float32)
    oh = jax.nn.one_hot(bc, B, dtype=jnp.float32) * mf[:, None, None]  # (n,F,B)
    ohc = jax.nn.one_hot(cc, C, dtype=jnp.float32) * mf[:, None]       # (n,C)
    feat = oh.sum(axis=0)                                   # (F, B)
    cls_counts = ohc.sum(axis=0)                            # (C,)
    feat_cls = jnp.einsum("nfb,nc->fbc", oh, ohc)           # (F, B, C)
    pair = jnp.einsum("nib,njd->ijbd", oh, oh)              # (F, F, B, B)
    pair_cls = jnp.einsum("nib,njd,nc->ijbdc", oh, oh, ohc)
    return feat, cls_counts, feat_cls, pair, pair_cls


def compute_stats(table: ColumnarTable, ctx: Optional[MeshContext] = None,
                  chunk: int = 1 << 18) -> MutualInfoStats:
    """All distributions in one (chunked) jitted pass over row-sharded data."""
    ctx = ctx or runtime_context()
    schema = table.schema
    fields = [f for f in schema.feature_fields if f.is_binned]
    F = len(fields)
    nbins = [f.num_bins for f in fields]
    B = max(nbins) if nbins else 1
    class_field = schema.class_attr_field
    class_values = list(class_field.cardinality or [])
    C = len(class_values)

    padded = table.pad_to_multiple(ctx.n_devices)
    bin_codes = np.stack([padded.binned_codes(f.ordinal) for f in fields], axis=1) \
        if fields else np.zeros((padded.n_rows, 0), np.int32)
    cls = padded.columns[class_field.ordinal].astype(np.int32)
    mask = padded.valid_mask

    d_bins = ctx.shard_rows(bin_codes)
    d_cls = ctx.shard_rows(cls)
    d_mask = ctx.shard_rows(mask)

    n = padded.n_rows
    feat = np.zeros((F, B)); cls_counts = np.zeros((C,))
    feat_cls = np.zeros((F, B, C)); pair = np.zeros((F, F, B, B))
    pair_cls = np.zeros((F, F, B, B, C))
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        out = _mi_kernel(d_bins[s:e], d_cls[s:e], d_mask[s:e], B, C)
        feat += np.asarray(out[0]); cls_counts += np.asarray(out[1])
        feat_cls += np.asarray(out[2]); pair += np.asarray(out[3])
        pair_cls += np.asarray(out[4])

    total = max(cls_counts.sum(), 1e-12)
    return MutualInfoStats(
        feature_ordinals=[f.ordinal for f in fields],
        class_values=class_values,
        class_p=cls_counts / total, feature_p=feat / total,
        feature_class_p=feat_cls / total, pair_p=pair / total,
        pair_class_p=pair_cls / total, num_bins=nbins)


# --------------------------------------------------------------------------
# selection scores (host-side greedy loops over the small MI tables)
# --------------------------------------------------------------------------

def mim_score(stats: MutualInfoStats) -> List[Tuple[int, float]]:
    """Mutual information maximization: rank I(X;C) descending."""
    F = len(stats.feature_ordinals)
    scores = [(stats.feature_ordinals[i], stats.feature_class_mi(i))
              for i in range(F)]
    return sorted(scores, key=lambda t: -t[1])


def _greedy(stats: MutualInfoStats, score_fn) -> List[Tuple[int, float]]:
    F = len(stats.feature_ordinals)
    selected: List[int] = []
    out: List[Tuple[int, float]] = []
    while len(selected) < F:
        best, best_score = None, -np.inf
        for i in range(F):
            if i in selected:
                continue
            s = score_fn(i, selected)
            if s > best_score:
                best, best_score = i, s
        selected.append(best)
        out.append((stats.feature_ordinals[best], best_score))
    return out


def mifs_score(stats: MutualInfoStats, redundancy_factor: float = 1.0
               ) -> List[Tuple[int, float]]:
    """MIFS: greedy I(X;C) - beta * sum_sel I(X;X_s)."""
    rel = [stats.feature_class_mi(i) for i in range(len(stats.feature_ordinals))]

    def score(i, selected):
        red = sum(stats.pair_mi(i, j) for j in selected)
        return rel[i] - redundancy_factor * red

    return _greedy(stats, score)


def jmi_score(stats: MutualInfoStats) -> List[Tuple[int, float]]:
    """JMI: first pick = max relevance, then greedy sum I(X,X_sel;C)."""
    return _jmi_helper(stats, disr=False)


def disr_score(stats: MutualInfoStats) -> List[Tuple[int, float]]:
    """DISR: like JMI but each term normalized by H(X,X_sel,C)."""
    return _jmi_helper(stats, disr=True)


def _jmi_helper(stats: MutualInfoStats, disr: bool) -> List[Tuple[int, float]]:
    F = len(stats.feature_ordinals)
    ranked = mim_score(stats)
    first_ord, first_score = ranked[0]
    first = stats.feature_ordinals.index(first_ord)
    selected = [first]
    out = [(first_ord, first_score)]
    while len(selected) < F:
        best, best_score = None, -np.inf
        for i in range(F):
            if i in selected:
                continue
            s = 0.0
            for j in selected:
                v = stats.pair_class_mi(i, j)
                if disr:
                    h = stats.pair_class_entropy(i, j)
                    v = v / h if h > 0 else 0.0
                s += v
            if s > best_score:
                best, best_score = i, s
        selected.append(best)
        out.append((stats.feature_ordinals[best], best_score))
    return out


def mrmr_score(stats: MutualInfoStats) -> List[Tuple[int, float]]:
    """mRMR: greedy I(X;C) - mean_sel I(X;X_s)."""
    rel = [stats.feature_class_mi(i) for i in range(len(stats.feature_ordinals))]

    def score(i, selected):
        if not selected:
            return rel[i]
        red = sum(stats.pair_mi(i, j) for j in selected) / len(selected)
        return rel[i] - red

    return _greedy(stats, score)


SCORE_ALGORITHMS = {
    "mutual.info.maximization": lambda s, rf: mim_score(s),
    "mutual.info.feature.selection": lambda s, rf: mifs_score(s, rf),
    "joint.mutual.info": lambda s, rf: jmi_score(s),
    "double.input.symmetrical.relevance": lambda s, rf: disr_score(s),
    "min.redundancy.max.relevance": lambda s, rf: mrmr_score(s),
}
