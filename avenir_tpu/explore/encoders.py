"""Supervised categorical encoding + boosting weight updates.

Parity targets:
  * CategoricalContinuousEncoding (explore/CategoricalContinuousEncoding.java
    :185-250): per (attr, value) positive/negative class counts ->
      supervisedRatio:   pos * scale / total   (integer division)
      weightOfEvidence:  int(scale * ln((pos/allPos) / (max(neg,1)/allNeg)))
    output lines 'ordinal,value,encoded'.
  * AdaBoostError (explore/AdaBoostError.java:110-165): weighted error of a
    prediction column vs actual column; error = errorSum (weight-normalized)
    or errorSum/errorCount.
  * AdaBoostUpdate (explore/AdaBoostUpdate.java:117-137): per-record weight
    *= exp(±alpha) when error < 0.5, else reset to the initial weight;
    alpha = 0.5 ln((1-e)/e).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.table import ColumnarTable
from ..ops.histogram import joint_histogram
from ..parallel.mesh import MeshContext

SUPERVISED_RATIO = "supervisedRatio"
WEIGHT_OF_EVIDENCE = "weightOfEvidence"


def categorical_continuous_encoding(table: ColumnarTable,
                                    attr_ordinals: Sequence[int],
                                    class_attr_ordinal: int,
                                    pos_class_value: str,
                                    strategy: str = SUPERVISED_RATIO,
                                    scale: int = 100,
                                    ctx: Optional[MeshContext] = None
                                    ) -> List[Tuple[int, str, int]]:
    """(ordinal, categorical value, encoded int) triples."""
    schema = table.schema
    cls_field = schema.find_field_by_ordinal(class_attr_ordinal)
    pos_code = cls_field.cat_code(pos_class_value)
    if pos_code < 0:
        raise ValueError(f"positive class value {pos_class_value!r} not in "
                         f"class cardinality")
    cls = table.columns[class_attr_ordinal]
    is_pos = (cls == pos_code).astype(np.int64)
    all_pos = int(is_pos.sum())
    all_neg = int(len(cls) - all_pos)
    out: List[Tuple[int, str, int]] = []
    for o in attr_ordinals:
        f = schema.find_field_by_ordinal(o)
        card = f.cardinality or []
        counts = np.asarray(joint_histogram(
            jnp.asarray(table.columns[o]), jnp.asarray(is_pos.astype(np.int32)),
            len(card), 2))
        for vi, value in enumerate(card):
            neg, pos = counts[vi, 0], counts[vi, 1]
            total = pos + neg
            if total == 0:
                continue
            if strategy == WEIGHT_OF_EVIDENCE:
                woe = (pos / max(all_pos, 1)) / (max(neg, 1.0) / max(all_neg, 1))
                enc = int(math.log(woe) * scale) if woe > 0 else 0
            else:  # supervisedRatio
                enc = int(pos * scale) // int(total)
            out.append((o, value, enc))
    return out


def adaboost_error(actual: Sequence[str], predicted: Sequence[str],
                   weights: np.ndarray, weight_normalized: bool = True) -> float:
    """Weighted misclassification error (AdaBoostError semantics)."""
    wrong = np.asarray([a != p for a, p in zip(actual, predicted)])
    err_sum = float(weights[wrong].sum())
    if weight_normalized:
        return err_sum
    return err_sum / max(len(actual), 1)


def adaboost_alpha(error: float) -> float:
    """alpha = 0.5 ln((1-e)/e)."""
    e = min(max(error, 1e-12), 1 - 1e-12)
    return 0.5 * math.log((1 - e) / e)


def adaboost_update(weights: np.ndarray, actual: Sequence[str],
                    predicted: Sequence[str], error: float,
                    initial_weight: float = 1.0) -> np.ndarray:
    """New per-record boost weights (AdaBoostUpdate.java:117-137)."""
    if error >= 0.5:
        return np.full_like(np.asarray(weights, dtype=np.float64), initial_weight)
    alpha = adaboost_alpha(error)
    wrong = np.asarray([a != p for a, p in zip(actual, predicted)])
    return np.where(wrong, weights * math.exp(alpha), weights * math.exp(-alpha))
