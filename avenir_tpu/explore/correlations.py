"""Correlation / association measures between attributes.

Parity targets (SURVEY.md §2.4):
  * ContingencyMatrix measures — cramerIndex, concentrationCoeff
    (Goodman-Kruskal tau), uncertaintyCoeff — with the reference's exact
    formulas including its quirks (util/ContingencyMatrix.java:86-186:
    cramer has no sqrt; uncertainty uses log10 and multiplies by the column
    marginal where the textbook divides — parity over propriety).
  * CramerCorrelation job (explore/CramerCorrelation.java): categorical
    attr pairs -> contingency matrix -> cramer index.
  * NumericalCorrelation (explore/NumericalCorrelation.java:87-179):
    Pearson via (n, Σx, Σy, Σxy, Σx², Σy²) tuple algebra; the combiner is
    the per-shard partial sum XLA already does.
  * HeterogeneityReductionCorrelation: concentration ('gini') or
    uncertainty ('entropy') coefficient per attr pair.
  * CategoricalClassAffinity (explore/CategoricalClassAffinity.java):
    per categorical value, affinity to each class value.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.table import ColumnarTable
from ..ops.histogram import joint_histogram
from ..parallel.mesh import MeshContext, runtime_context


class ContingencyMatrix:
    """Exact port of the measures of util/ContingencyMatrix.java (the counts
    themselves come from a device joint histogram)."""

    def __init__(self, table: np.ndarray):
        self.table = np.asarray(table, dtype=np.float64)

    def _aggregates(self):
        row = self.table.sum(axis=1)
        col = self.table.sum(axis=0)
        total = self.table.sum()
        row = np.where(row == 0, 1, row)
        col = np.where(col == 0, 1, col)
        return row, col, total

    def cramer_index(self) -> float:
        """(sum n_ij^2/(r_i c_j) - 1) / (min(R,C)-1)  (:86-124; no sqrt)."""
        row, col, _ = self._aggregates()
        pearson = (self.table ** 2 / (row[:, None] * col[None, :])).sum() - 1.0
        smaller = min(self.table.shape)
        return float(pearson / (smaller - 1))

    def concentration_coeff(self) -> float:
        """Goodman-Kruskal tau (:141-163)."""
        row, col, total = self._aggregates()
        rp = row / total
        cp = col / total
        p = self.table / total
        sum_one = ((p ** 2).sum(axis=1) / rp).sum()
        sum_two = (cp ** 2).sum()
        return float((sum_one - sum_two) / (1.0 - sum_two))

    def uncertainty_coeff(self) -> float:
        """Theil's U with the reference's formula verbatim (:165-186):
        log10, and the joint term is p_ij*log10(p_ij * c_j / r_i)."""
        row, col, total = self._aggregates()
        rp = row / total
        cp = col / total
        p = self.table / total
        with np.errstate(divide="ignore", invalid="ignore"):
            inner = p * np.log10(np.where(p > 0, p * cp[None, :] / rp[:, None], 1.0))
        sum_one = np.where(p > 0, inner, 0.0).sum()
        sum_two = (cp * np.log10(np.where(cp > 0, cp, 1.0))).sum()
        return float(sum_one / sum_two)


def categorical_pair_matrix(table: ColumnarTable, ord_a: int, ord_b: int,
                            ctx: Optional[MeshContext] = None) -> ContingencyMatrix:
    """Joint histogram of two categorical columns on device."""
    fa = table.schema.find_field_by_ordinal(ord_a)
    fb = table.schema.find_field_by_ordinal(ord_b)
    counts = joint_histogram(jnp.asarray(table.columns[ord_a]),
                             jnp.asarray(table.columns[ord_b]),
                             len(fa.cardinality or []), len(fb.cardinality or []))
    return ContingencyMatrix(np.asarray(counts))


def cramer_correlations(table: ColumnarTable, ordinals: Sequence[int],
                        ctx: Optional[MeshContext] = None
                        ) -> List[Tuple[int, int, float]]:
    out = []
    for i, a in enumerate(ordinals):
        for b in ordinals[i + 1:]:
            out.append((a, b, categorical_pair_matrix(table, a, b, ctx)
                        .cramer_index()))
    return out


def heterogeneity_correlations(table: ColumnarTable, ordinals: Sequence[int],
                               algorithm: str = "gini",
                               ctx: Optional[MeshContext] = None
                               ) -> List[Tuple[int, int, float]]:
    """'gini' -> concentration coeff, 'entropy' -> uncertainty coeff
    (HeterogeneityReductionCorrelation.java:76-86)."""
    out = []
    for i, a in enumerate(ordinals):
        for b in ordinals[i + 1:]:
            m = categorical_pair_matrix(table, a, b, ctx)
            v = m.concentration_coeff() if algorithm == "gini" \
                else m.uncertainty_coeff()
            out.append((a, b, v))
    return out


@jax.jit
def _moment_kernel(X, m):
    """(n, F) masked moment pass — module-level jit so repeat correlation
    jobs share one compiled program per shape."""
    Xm = X * m[:, None]
    n = m.sum()
    s1 = Xm.sum(axis=0)                      # Σx per attr
    s2 = (Xm * X).sum(axis=0)                # Σx²
    cross = jnp.einsum("ni,nj->ij", Xm, X)   # Σ x_i x_j
    return n, s1, s2, cross


def numerical_correlations(table: ColumnarTable, ordinals: Sequence[int],
                           ctx: Optional[MeshContext] = None
                           ) -> List[Tuple[int, int, float]]:
    """Pearson r per pair via a single device moment pass
    (NumericalCorrelation.java:87-179's (n,Σx,Σy,Σxy,Σx²,Σy²) algebra)."""
    ctx = ctx or runtime_context()
    padded = table.pad_to_multiple(ctx.n_devices)
    X = np.stack([padded.columns[o] for o in ordinals], axis=1).astype(np.float64)
    mask = padded.valid_mask.astype(np.float64)

    n, s1, s2, cross = (np.asarray(x) for x in _moment_kernel(
        ctx.shard_rows(X.astype(np.float32)), ctx.shard_rows(mask.astype(np.float32))))
    out = []
    for i in range(len(ordinals)):
        for j in range(i + 1, len(ordinals)):
            num = n * cross[i, j] - s1[i] * s1[j]
            den = np.sqrt(n * s2[i] - s1[i] ** 2) * np.sqrt(n * s2[j] - s1[j] ** 2)
            out.append((ordinals[i], ordinals[j],
                        float(num / den) if den > 0 else 0.0))
    return out


def class_affinity(table: ColumnarTable, ordinals: Sequence[int],
                   ctx: Optional[MeshContext] = None
                   ) -> Dict[int, np.ndarray]:
    """Per categorical attr: P(class | value) matrix (value, class) —
    the value->class affinity scores of CategoricalClassAffinity.java."""
    schema = table.schema
    cls_field = schema.class_attr_field
    C = len(cls_field.cardinality or [])
    out = {}
    for o in ordinals:
        f = schema.find_field_by_ordinal(o)
        counts = np.asarray(joint_histogram(
            jnp.asarray(table.columns[o]), jnp.asarray(table.class_codes()),
            len(f.cardinality or []), C))
        row = counts.sum(axis=1, keepdims=True)
        out[o] = counts / np.maximum(row, 1.0)
    return out
