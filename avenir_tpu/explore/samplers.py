"""Class-imbalance samplers + Relief feature relevance.

Parity targets (SURVEY.md §2.4):
  * TopMatchesByClass (explore/TopMatchesByClass.java) — per-class top-k
    nearest records; here one masked top-k over the device distance matrix.
  * ClassBasedOverSampler (explore/ClassBasedOverSampler.java) — SMOTE:
    synthetic minority records interpolated toward a random one of the k
    nearest same-class neighbors.
  * UnderSamplingBalancer (explore/UnderSamplingBalancer.java) — subsample
    the majority class at a rate (or to balance).
  * BaggingSampler (explore/BaggingSampler.java) — bootstrap batches.
  * ReliefFeatureRelevance (explore/ReliefFeatureRelevance.java:199-247):
    score[attr] += diff(nearest miss) - diff(nearest hit), normalized
    range-scaled numeric / 0-1 categorical diffs, divided by sample count.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax

from ..core.table import ColumnarTable
from ..ops.distance import DistanceComputer
from ..parallel.mesh import MeshContext


def top_matches_by_class(table: ColumnarTable, k: int,
                         metric: str = "euclidean",
                         ctx: Optional[MeshContext] = None) -> np.ndarray:
    """(n, k) indices of each record's k nearest SAME-class neighbors
    (self excluded).  Missing neighbors (tiny classes) are -1."""
    comp = DistanceComputer(table.schema, metric=metric)
    d = comp.pairwise(table, table).astype(np.int64)
    cls = table.class_codes()
    same = cls[:, None] == cls[None, :]
    big = np.int64(1) << 40
    d = np.where(same, d, big)
    np.fill_diagonal(d, big)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    dist = np.take_along_axis(d, idx, axis=1)
    return np.where(dist < big, idx, -1)


def smote_oversample(table: ColumnarTable, minority_class: str,
                     k: int = 5, multiplier: int = 1,
                     seed: int = 0) -> List[List[str]]:
    """Synthetic minority rows (as string records): numeric attrs
    interpolated x + u*(neighbor - x), categorical attrs picked from either
    parent — the ClassBasedOverSampler construction."""
    rng = np.random.default_rng(seed)
    schema = table.schema
    cls_field = schema.class_attr_field
    code = cls_field.cat_code(minority_class)
    neighbors = top_matches_by_class(table, k)
    minority = np.nonzero(table.class_codes() == code)[0]
    id_ord = schema.id_fields[0].ordinal if schema.id_fields else None
    out: List[List[str]] = []
    for rep in range(multiplier):
        for i in minority:
            cand = neighbors[i][neighbors[i] >= 0]
            if len(cand) == 0:
                continue
            j = int(rng.choice(cand))
            u = rng.random()
            row: List[str] = []
            for f in schema.fields:
                o = f.ordinal
                if f.id_field:
                    row.append(f"syn_{rep}_{i}")
                elif f.is_numeric:
                    a, b = table.columns[o][i], table.columns[o][j]
                    v = a + u * (b - a)
                    row.append(str(int(round(v))) if f.is_integer else f"{v:.6f}")
                elif f.is_categorical:
                    if o == cls_field.ordinal:
                        row.append(minority_class)
                    else:
                        src = i if rng.random() < 0.5 else j
                        codev = table.columns[o][src]
                        row.append(f.cardinality[codev] if codev >= 0 else "?")
                else:
                    row.append(table.str_columns.get(o, [""] * table.n_rows)[i])
            out.append(row)
    return out


def under_sample(table: ColumnarTable, majority_class: str,
                 rate: float, seed: int = 0) -> np.ndarray:
    """Boolean keep-mask: majority-class rows kept with probability rate,
    everything else kept (UnderSamplingBalancer)."""
    rng = np.random.default_rng(seed)
    code = table.schema.class_attr_field.cat_code(majority_class)
    cls = table.class_codes()
    keep = np.ones((table.n_rows,), dtype=bool)
    maj = cls == code
    keep[maj] = rng.random(int(maj.sum())) < rate
    return keep


def bagging_sample(n: int, rate: float, with_replacement: bool = True,
                   seed: int = 0) -> np.ndarray:
    """Indices of one bagging batch (BaggingSampler)."""
    rng = np.random.default_rng(seed)
    m = int(n * rate)
    if with_replacement:
        return rng.integers(0, n, m)
    return rng.permutation(n)[:m]


def relief_relevance(table: ColumnarTable, attr_ordinals: Sequence[int],
                     sample_count: Optional[int] = None,
                     metric: str = "euclidean", seed: int = 0,
                     ctx: Optional[MeshContext] = None) -> Dict[int, float]:
    """Relief scores per attr: mean over samples of
    diff(x, nearest miss) - diff(x, nearest hit)
    (ReliefFeatureRelevance.java:199-247 with 1 hit + 1 miss per sample)."""
    rng = np.random.default_rng(seed)
    schema = table.schema
    comp = DistanceComputer(schema, metric=metric)
    d = comp.pairwise(table, table).astype(np.int64)
    cls = table.class_codes()
    n = table.n_rows
    big = np.int64(1) << 40
    np.fill_diagonal(d, big)
    same = cls[:, None] == cls[None, :]
    d_hit = np.where(same, d, big)
    d_miss = np.where(~same, d, big)
    hit_idx = np.argmin(d_hit, axis=1)
    miss_idx = np.argmin(d_miss, axis=1)

    samples = np.arange(n) if sample_count is None or sample_count >= n else \
        rng.permutation(n)[:sample_count]
    scores = {o: 0.0 for o in attr_ordinals}
    for o in attr_ordinals:
        f = schema.find_field_by_ordinal(o)
        col = table.columns[o]
        if f.is_numeric:
            rng_width = max(float(f.max) - float(f.min), 1e-12) \
                if f.max is not None and f.min is not None else \
                max(float(col.max() - col.min()), 1e-12)
            dh = np.abs(col[samples] - col[hit_idx[samples]]) / rng_width
            dm = np.abs(col[samples] - col[miss_idx[samples]]) / rng_width
        else:
            dh = (col[samples] != col[hit_idx[samples]]).astype(np.float64)
            dm = (col[samples] != col[miss_idx[samples]]).astype(np.float64)
        scores[o] = float((dm - dh).sum() / len(samples))
    return scores
