"""Rule definition + distributed rule evaluation.

Parity targets:
  * RuleExpression (util/RuleExpression.java:29-73) — ``condition >
    consequent`` split on the FIRST '>' occurrence; the condition is a chombo
    AttributeFilter conjunction.  chombo is not vendored, so the condition
    grammar is re-specified here (same operator vocabulary chombo's
    AttributeFilter predicates use):

        condition   := conjunct (SEP conjunct)*
        conjunct    := <ordinal> <op> <operand>
        op          := eq | ne | gt | ge | lt | le | in | notin
        operand     := number | string | value:value:... (for in/notin)
        SEP         := ' and ' by default (rue.cond.delim overrides)

  * RuleEvaluator (explore/RuleEvaluator.java) — per rule: rows matching the
    condition are counted by class value; confidence = matched-consequent
    fraction (confAccuracy, :252-253) or 1 + binary entropy of the matched
    class distribution in bits (confEntropy, :254-259); support =
    matched/total (:263); output ``ruleName,confidence,support`` 3dp.

TPU design: each conjunct is a vectorized comparison over a column; a rule's
match mask is the AND across conjuncts, and the per-class counts are a
mask × one-hot(class) contraction — one fused device pass per rule batch
instead of the reference's per-record mapper loop + shuffle.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

CONSEQUENT_SEP = ">"
DEFAULT_CONJUNCT_SEP = " and "

_OPS = ("eq", "ne", "gt", "ge", "lt", "le", "in", "notin")


@dataclass
class Conjunct:
    ordinal: int
    op: str
    operand: str

    def _operand_values(self) -> List[str]:
        return self.operand.split(":")

    def evaluate_column(self, col: np.ndarray) -> np.ndarray:
        """Vectorized predicate over a raw string column."""
        if self.op in ("eq", "ne"):
            m = col == self.operand
            return m if self.op == "eq" else ~m
        if self.op in ("in", "notin"):
            m = np.isin(col, self._operand_values())
            return m if self.op == "in" else ~m
        # numeric comparison
        vals = col.astype(np.float64)
        ref = float(self.operand)
        return {"gt": vals > ref, "ge": vals >= ref,
                "lt": vals < ref, "le": vals <= ref}[self.op]

    def evaluate(self, row: Sequence[str]) -> bool:
        return bool(self.evaluate_column(
            np.asarray([row[self.ordinal]], dtype=object))[0])


@dataclass
class RuleExpression:
    """``condition > consequent`` (util/RuleExpression.java:49-55)."""
    conjuncts: List[Conjunct]
    consequent: str

    @classmethod
    def create(cls, rule: str, conjunct_sep: str = DEFAULT_CONJUNCT_SEP
               ) -> "RuleExpression":
        cond, _, consequent = rule.partition(CONSEQUENT_SEP)
        conjuncts = []
        for part in cond.split(conjunct_sep):
            part = part.strip()
            if not part:
                continue
            tokens = part.split(None, 2)
            if len(tokens) != 3 or tokens[1] not in _OPS:
                raise ValueError(f"bad conjunct {part!r}; expected "
                                 f"'<ordinal> <op> <operand>' with op in "
                                 f"{_OPS}")
            conjuncts.append(Conjunct(int(tokens[0]), tokens[1], tokens[2]))
        if not conjuncts:
            raise ValueError(f"rule {rule!r} has no condition")
        return cls(conjuncts, consequent.strip())

    @staticmethod
    def extract_consequent(rule: str) -> str:
        return rule.partition(CONSEQUENT_SEP)[2].strip()

    def match_mask(self, columns: Sequence[np.ndarray]) -> np.ndarray:
        mask = None
        for c in self.conjuncts:
            m = c.evaluate_column(columns[c.ordinal])
            mask = m if mask is None else (mask & m)
        return mask

    def evaluate(self, row: Sequence[str]) -> bool:
        return all(c.evaluate(row) for c in self.conjuncts)


def _confidence(class_counts: Dict[str, int], consequent: str,
                strategy: str, class_values: Sequence[str]) -> float:
    total = sum(class_counts.values())
    if total == 0:
        return 0.0
    p_this = class_counts.get(consequent, 0) / total
    if strategy == "confAccuracy":
        return p_this
    if strategy == "confEntropy":
        # 1 + sum p ln p / ln 2 over the two classes (RuleEvaluator.java
        # :254-259); x*log(x) -> 0 as x -> 0
        idx = list(class_values).index(consequent)
        other = class_values[idx ^ 1]
        p_other = class_counts.get(other, 0) / total
        acc = 0.0
        for p in (p_this, p_other):
            if p > 0:
                acc += p * math.log(p)
        return acc / math.log(2.0) + 1.0
    raise ValueError(f"invalid confidence strategy {strategy!r}")


def evaluate_rules(rules: Dict[str, RuleExpression],
                   columns: Sequence[np.ndarray], class_ordinal: int,
                   data_size: int, conf_strategy: str,
                   class_values: Sequence[str]
                   ) -> List[Tuple[str, float, float]]:
    """(ruleName, confidence, support) per rule, in rule-name order (the
    shuffle's key order).  ``columns`` are raw string columns; ``data_size``
    is the reference's rue.data.size denominator for support."""
    cls_col = columns[class_ordinal]
    out = []
    for name in sorted(rules):
        rule = rules[name]
        mask = rule.match_mask(columns)
        matched = cls_col[mask]
        vals, counts = (np.unique(matched, return_counts=True)
                        if matched.size else (np.array([]), np.array([])))
        class_counts = {str(v): int(c) for v, c in zip(vals, counts)}
        conf = _confidence(class_counts, rule.consequent, conf_strategy,
                           class_values)
        support = sum(class_counts.values()) / data_size
        out.append((name, conf, support))
    return out
