"""Single-hidden-layer MLP classifier (the reference's neural-net component,
python/supv/basic_nn.py): tanh hidden layer, softmax output, cross-entropy
loss with L2 regularization on the weight matrices (not biases), trained by
plain gradient descent in either full-batch mode ("batch",
basic_nn.py build_model_batch) or shuffled per-example SGD ("incr",
build_model_incr), plus a TPU-friendly "minibatch" mode the reference lacks.

TPU-first redesign: parameters are a pytree, the update step is jitted and
`lax.scan`ned so an entire training run is one XLA program; the incremental
mode scans over a fresh random permutation per epoch instead of a Python
loop; `train_ensemble` vmaps whole training runs across seeds to train N
replicas in parallel on one chip (the reference trains one model per process
invocation)."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


Params = Dict[str, jnp.ndarray]


@dataclass
class MLPConfig:
    hidden_dim: int = 3
    n_classes: int = 2
    learning_rate: float = 0.01      # epsilon (basic_nn.py:31)
    reg_lambda: float = 0.01         # reg_lambda (basic_nn.py:85)
    mode: str = "batch"              # batch | incr | minibatch
    iterations: int = 1000           # num_passes
    batch_size: int = 64             # minibatch mode only
    seed: int = 0
    validation_interval: int = 50    # loss recorded every this many passes


def init_params(n_features: int, cfg: MLPConfig, key=None) -> Params:
    """randn/sqrt(fan_in) init, zero biases (basic_nn.py:126-129)."""
    key = key if key is not None else jax.random.PRNGKey(cfg.seed)
    k1, k2 = jax.random.split(key)
    return {
        "W1": jax.random.normal(k1, (n_features, cfg.hidden_dim))
        / jnp.sqrt(n_features),
        "b1": jnp.zeros((cfg.hidden_dim,)),
        "W2": jax.random.normal(k2, (cfg.hidden_dim, cfg.n_classes))
        / jnp.sqrt(cfg.hidden_dim),
        "b2": jnp.zeros((cfg.n_classes,)),
    }


def forward_logits(params: Params, X: jnp.ndarray) -> jnp.ndarray:
    a1 = jnp.tanh(X @ params["W1"] + params["b1"])
    return a1 @ params["W2"] + params["b2"]


def predict_proba(params: Params, X: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.softmax(forward_logits(params, X), axis=-1)


def predict(params: Params, X: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(forward_logits(params, X), axis=-1)


def loss_fn(params: Params, X: jnp.ndarray, y: jnp.ndarray,
            reg_lambda: float) -> jnp.ndarray:
    """Mean cross-entropy + (lambda/2)(|W1|^2+|W2|^2)/n, matching the
    reference's calculate_loss normalization (basic_nn.py:87-103: total
    data loss plus full reg term, all divided by n)."""
    logits = forward_logits(params, X)
    logp = jax.nn.log_softmax(logits, axis=-1)
    n = X.shape[0]
    ce = -logp[jnp.arange(n), y].sum()
    reg = 0.5 * reg_lambda * ((params["W1"] ** 2).sum()
                              + (params["W2"] ** 2).sum())
    return (ce + reg) / n


def _grad_step(params: Params, X, y, lr: float, reg_lambda: float) -> Params:
    """One GD step on the UN-normalized loss with reg gradient lambda*W —
    exactly the reference's batch update (basic_nn.py:141-160: summed
    delta3, dW += reg_lambda*W, W -= epsilon*dW)."""
    def raw_loss(p):
        logits = forward_logits(p, X)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -logp[jnp.arange(X.shape[0]), y].sum()
        reg = 0.5 * reg_lambda * ((p["W1"] ** 2).sum() + (p["W2"] ** 2).sum())
        return ce + reg

    grads = jax.grad(raw_loss)(params)
    return jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)


@partial(jax.jit, static_argnames=("cfg_iters", "interval"))
def _train_batch(params: Params, X, y, Xv, yv, lr, reg_lambda,
                 cfg_iters: int, interval: int):
    # nested scan: validation loss is computed once per interval, not per
    # step (the reference's validation_interval throttling)
    interval = max(interval, 1)
    n_outer, rem = divmod(cfg_iters, interval)

    def inner(p, _):
        return _grad_step(p, X, y, lr, reg_lambda), None

    def outer(p, _):
        p, _ = jax.lax.scan(inner, p, None, length=interval)
        return p, loss_fn(p, Xv, yv, reg_lambda)

    params, losses = jax.lax.scan(outer, params, None, length=n_outer)
    if rem:
        params, _ = jax.lax.scan(inner, params, None, length=rem)
    if n_outer == 0:  # iterations < interval: still record one final loss
        losses = loss_fn(params, Xv, yv, reg_lambda)[None]
    return params, losses


@partial(jax.jit, static_argnames=("cfg_iters", "interval"))
def _train_incr(params: Params, X, y, Xv, yv, lr, reg_lambda, key,
                cfg_iters: int, interval: int):
    n = X.shape[0]

    def epoch(carry, _):
        p, key = carry
        key, sub = jax.random.split(key)
        order = jax.random.permutation(sub, n)

        def ex_step(p, j):
            return _grad_step(p, X[j][None], y[j][None], lr, reg_lambda), 0.0

        p, _ = jax.lax.scan(ex_step, p, order)
        return (p, key), loss_fn(p, Xv, yv, reg_lambda)

    (params, _), losses = jax.lax.scan(epoch, (params, key), None,
                                       length=cfg_iters)
    return params, losses[::max(interval, 1)]


@partial(jax.jit, static_argnames=("cfg_iters", "interval", "batch_size"))
def _train_minibatch(params: Params, X, y, Xv, yv, lr, reg_lambda, key,
                     cfg_iters: int, interval: int, batch_size: int):
    n = X.shape[0]

    def epoch(carry, _):
        p, key = carry
        key, sub = jax.random.split(key)
        order = jax.random.permutation(sub, n)
        n_batches = n // batch_size
        batches = order[:n_batches * batch_size].reshape(n_batches, batch_size)

        def mb_step(p, idx):
            return _grad_step(p, X[idx], y[idx], lr, reg_lambda), 0.0

        p, _ = jax.lax.scan(mb_step, p, batches)
        return (p, key), loss_fn(p, Xv, yv, reg_lambda)

    (params, _), losses = jax.lax.scan(epoch, (params, key), None,
                                       length=cfg_iters)
    return params, losses[::max(interval, 1)]


def train(X: np.ndarray, y: np.ndarray, cfg: MLPConfig,
          X_val: Optional[np.ndarray] = None,
          y_val: Optional[np.ndarray] = None,
          params0: Optional[Params] = None
          ) -> Tuple[Params, np.ndarray]:
    """Train per cfg.mode; returns (params, validation-loss history sampled
    every cfg.validation_interval passes).  Falls back to training loss when
    no validation split is given (basic_nn.py use_validation_data).
    ``params0`` warm-starts from an earlier run (checkpoint resume)."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.int32)
    Xv = jnp.asarray(X_val, jnp.float32) if X_val is not None else X
    yv = jnp.asarray(y_val, jnp.int32) if y_val is not None else y
    params = ({k: jnp.asarray(v) for k, v in params0.items()}
              if params0 is not None else init_params(X.shape[1], cfg))
    key = jax.random.PRNGKey(cfg.seed + 1)
    if cfg.mode == "batch":
        params, losses = _train_batch(params, X, y, Xv, yv, cfg.learning_rate,
                                      cfg.reg_lambda, cfg.iterations,
                                      cfg.validation_interval)
    elif cfg.mode == "incr":
        params, losses = _train_incr(params, X, y, Xv, yv, cfg.learning_rate,
                                     cfg.reg_lambda, key, cfg.iterations,
                                     cfg.validation_interval)
    elif cfg.mode == "minibatch":
        params, losses = _train_minibatch(
            params, X, y, Xv, yv, cfg.learning_rate, cfg.reg_lambda, key,
            cfg.iterations, cfg.validation_interval, cfg.batch_size)
    else:
        raise ValueError(f"invalid training mode {cfg.mode!r} "
                         "(batch | incr | minibatch)")
    return params, np.asarray(losses)


def train_ensemble(X: np.ndarray, y: np.ndarray, cfg: MLPConfig,
                   seeds: Sequence[int]) -> Params:
    """vmap full batch-mode training runs over seeds: returns stacked params
    with a leading replica axis.  N independent models in one XLA program."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.int32)

    def one(seed):
        p = init_params(X.shape[1], cfg, key=jax.random.PRNGKey(seed))
        p, _ = _train_batch(p, X, y, X, y, cfg.learning_rate, cfg.reg_lambda,
                            cfg.iterations, cfg.validation_interval)
        return p

    return jax.vmap(one)(jnp.asarray(list(seeds), dtype=jnp.uint32))


def ensemble_predict(stacked: Params, X: np.ndarray) -> jnp.ndarray:
    """Soft vote over the replica axis of train_ensemble output: argmax of
    the replica-mean class probabilities."""
    X = jnp.asarray(X, jnp.float32)
    probs = jax.vmap(lambda p: predict_proba(p, X))(stacked)   # (R, n, C)
    return jnp.argmax(probs.mean(axis=0), axis=-1)


# ---- model artifact (CSV lines, core.artifacts contract) ----

def to_lines(params: Params, delim: str = ",") -> List[str]:
    lines = []
    for name in ("W1", "b1", "W2", "b2"):
        arr = np.asarray(params[name])
        arr2 = arr.reshape(1, -1) if arr.ndim == 1 else arr
        lines.append(f"#{name}{delim}{arr2.shape[0]}{delim}{arr2.shape[1]}")
        for row in arr2:
            lines.append(delim.join(repr(float(v)) for v in row))
    return lines


def from_lines(lines: Sequence[str], delim: str = ",") -> Params:
    params: Params = {}
    i = 0
    while i < len(lines):
        head = lines[i].strip()
        if not head.startswith("#"):
            i += 1
            continue
        name, r, c = head[1:].split(delim)
        r, c = int(r), int(c)
        rows = [[float(v) for v in lines[i + 1 + k].split(delim)]
                for k in range(r)]
        arr = jnp.asarray(np.asarray(rows))
        params[name] = arr[0] if name.startswith("b") else arr
        i += 1 + r
    return params
