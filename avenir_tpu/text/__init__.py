"""Text pack (SURVEY.md §2.8 `text`): word counting with analyzer-style
tokenization (text/WordCounter.java)."""

from .wordcount import STANDARD_STOPWORDS, tokenize, word_count

__all__ = ["STANDARD_STOPWORDS", "tokenize", "word_count"]
