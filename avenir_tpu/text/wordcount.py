"""Word counting with analyzer-style tokenization.

Parity target: text/WordCounter.java — mapper tokenizes a text field (or the
whole line when the ordinal is not positive, :101-106) with Lucene's
StandardAnalyzer (:93, lowercasing + English stop-word removal), reducer
counts occurrences and emits ``word<delim>count`` (:135-146).

TPU note: tokenization is host-side string work (as in the reference's
mapper); the count itself is a vectorized ``np.unique`` over the token array
— word counting is IO-bound, not a device workload, so no device round-trip
is forced here.  The Bayesian text mode reuses ``tokenize``.
"""

from __future__ import annotations

import re
from typing import List, Sequence, Tuple

import numpy as np

# Lucene's ENGLISH_STOP_WORDS_SET, the default for StandardAnalyzer
# (what text/WordCounter.java:93 instantiates)
STANDARD_STOPWORDS = frozenset((
    "a", "an", "and", "are", "as", "at", "be", "but", "by", "for", "if",
    "in", "into", "is", "it", "no", "not", "of", "on", "or", "such", "that",
    "the", "their", "then", "there", "these", "they", "this", "to", "was",
    "will", "with",
))

_TOKEN_RE = re.compile(r"[a-z0-9']+")


def tokenize(text: str, stopwords: frozenset = STANDARD_STOPWORDS
             ) -> List[str]:
    """StandardAnalyzer-equivalent tokenization: lowercase, split on
    non-alphanumeric runs, drop stop words.  (The reference's comment says
    'stemming' but StandardAnalyzer does not stem; neither do we.)"""
    tokens = _TOKEN_RE.findall(text.lower())
    return [t.strip("'") for t in tokens
            if t.strip("'") and t.strip("'") not in stopwords]


def word_count(texts: Sequence[str],
               stopwords: frozenset = STANDARD_STOPWORDS
               ) -> List[Tuple[str, int]]:
    """(word, count) sorted by word — the shuffle's key order, so output
    lines match the reference reducer's emission order."""
    all_tokens: List[str] = []
    for text in texts:
        all_tokens.extend(tokenize(text, stopwords))
    if not all_tokens:
        return []
    words, counts = np.unique(np.asarray(all_tokens, dtype=object),
                              return_counts=True)
    return [(str(w), int(c)) for w, c in zip(words, counts)]
