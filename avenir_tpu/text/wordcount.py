"""Word counting with analyzer-style tokenization.

Parity target: text/WordCounter.java — mapper tokenizes a text field (or the
whole line when the ordinal is not positive, :101-106) with Lucene's
StandardAnalyzer (:93, lowercasing + English stop-word removal), reducer
counts occurrences and emits ``word<delim>count`` (:135-146).

TPU note: tokenization is host-side string work (as in the reference's
mapper); the count itself is a vectorized ``np.unique`` over the token array
— word counting is IO-bound, not a device workload, so no device round-trip
is forced here.  The Bayesian text mode reuses ``tokenize``.
"""

from __future__ import annotations

import re
from typing import List, Sequence, Tuple

import numpy as np

# Lucene's ENGLISH_STOP_WORDS_SET, the default for StandardAnalyzer
# (what text/WordCounter.java:93 instantiates)
STANDARD_STOPWORDS = frozenset((
    "a", "an", "and", "are", "as", "at", "be", "but", "by", "for", "if",
    "in", "into", "is", "it", "no", "not", "of", "on", "or", "such", "that",
    "the", "their", "then", "there", "these", "they", "this", "to", "was",
    "will", "with",
))

# UAX#29-style word boundaries, the rules Lucene 4.4's StandardTokenizer
# implements for Latin-script text: unicode alphanumeric runs, joined by
#   - . / apostrophe between letters or between digits (MidNumLet +
#     Single_Quote, WB6/7 + WB11/12: don't, o'neill's, example.com, 3.14)
#   - underscore between alphanumerics (ExtendNumLet: foo_bar stays whole)
_TOKEN_RE = re.compile(
    r"[^\W_]+"
    r"(?:(?:_|(?<=[^\W\d_])['’.](?=[^\W\d_])|(?<=\d)['’.](?=\d))"
    r"[^\W_]+)*",
    re.UNICODE)


def tokenize(text: str, stopwords: frozenset = STANDARD_STOPWORDS
             ) -> List[str]:
    """StandardAnalyzer(Version.LUCENE_44)-equivalent tokenization:
    UAX#29-style word segmentation (see ``_TOKEN_RE``), lowercase, drop
    the English stop set.  (The reference's comment says 'stemming' but
    StandardAnalyzer does not stem; neither do we.)

    Pinned against hand-derived Lucene 4.4 output in
    tests/test_bayes_text.py::test_tokenizer_lucene_parity.  Known
    residual divergences, by design:

    * ',' between digits (MidNum) is NOT a joiner here: Lucene emits
      ``1,000`` as one token, but every downstream artifact (word counts,
      the text-Bayes model file) is comma-delimited, so a
      delimiter-bearing token corrupts the file on the reference's own
      format — we split to ``1`` + ``000`` and keep tokens
      delimiter-clean instead;
    * tokens with LEADING/TRAILING underscores lose them (Lucene keeps
      ``_foo_`` verbatim);
    * non-Latin segmentation extras (Katakana runs, Thai) are out of
      scope for the reference's corpora."""
    tokens = _TOKEN_RE.findall(text.lower())
    return [t for t in tokens if t not in stopwords]


def word_count(texts: Sequence[str],
               stopwords: frozenset = STANDARD_STOPWORDS
               ) -> List[Tuple[str, int]]:
    """(word, count) sorted by word — the shuffle's key order, so output
    lines match the reference reducer's emission order."""
    all_tokens: List[str] = []
    for text in texts:
        all_tokens.extend(tokenize(text, stopwords))
    if not all_tokens:
        return []
    words, counts = np.unique(np.asarray(all_tokens, dtype=object),
                              return_counts=True)
    return [(str(w), int(c)) for w, c in zip(words, counts)]
