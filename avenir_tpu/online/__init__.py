"""The online learning plane (ISSUE 19): serving and learning fused
into ONE cached device program per served window.

Layers:

* :mod:`.state` — the device-resident learner state (bandit arm
  statistics, logistic weights, MLP parameters, the threaded PRNG key)
  and its deterministic byte round trip (registry snapshots must be
  bit-identical across save/restore).
* :mod:`.plane` — the fused window program: an absorb → learn → predict
  :class:`~avenir_tpu.pipeline.compiler.ChunkPipeline` whose carries ARE
  the learner state, one dispatch per window at the ``online.window``
  ledger site; plus the host-side pending-outcome table that joins
  ``reward,<id>,<value>`` wire messages to the decisions they reward.
* :mod:`.service` — the wire tier: drains one RESP stream of mixed
  predict/reward traffic, runs windows, answers predictions, and feeds
  the supervisor.

The supervisor itself (journaled probation, registry snapshot cadence,
accuracy-floor rollback) lives with the other closed-loop machinery in
:mod:`avenir_tpu.control.controller` as :class:`OnlineSupervisor`.
"""

from .plane import OnlineWindowPlane, PendingOutcomeTable  # noqa: F401
from .service import OnlineLearnerService  # noqa: F401
from .state import OnlineLearnerConfig, state_from_bytes, state_to_bytes  # noqa: F401
