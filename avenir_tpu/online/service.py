"""The online learner's wire tier: one RESP stream, mixed verbs.

``predict,<id>,<f1>,...,<fN>`` rows are served; ``reward,<id>,<value>``
rows are joined to the decision ``<id>`` was answered with (the
backward-compatible wire growth pattern of ``t=``/``d=``/``m=``: old
producers never emit the verb, old consumers never see it — and the
native C plane declines any batch containing it via ``AWP_FALLBACK``,
so python owns reward parsing the way it owns every judged field).

Reward acknowledgement is pinned to the snapshot cadence: a leased
reward message is acked only after a registry snapshot COVERING its
absorption commits, so a crash between absorb and snapshot redelivers
the reward instead of silently losing its effect (the chaos-drill
contract; without a supervisor, acks release at window end).
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from .plane import OnlineWindowPlane

REWARD_VERB = "reward"
STOP_VERB = "stop"


def reward_ack_token(rid: str, delim: str = ",") -> str:
    """The ack-queue value for a leased reward message: its lease id
    (``reward:<id>``, the broker's reward lease key) plus a marker
    field, so ``ackpush`` pops the lease without colliding with the
    prediction reply for the same request id."""
    return f"{REWARD_VERB}:{rid}{delim}acked"


class OnlineLearnerService:
    """Parse a drained window, run the fused program, answer.

    The service is transport-agnostic (the RESP loop below and the
    in-process benchmarks both feed :meth:`process_window`); it owns
    verb parsing, reply labels, the supervisor hand-off, and the
    held-until-snapshot reward-ack buffer.
    """

    def __init__(self, plane: OnlineWindowPlane, delim: str = ",",
                 counters=None, supervisor=None, name: str = "online"):
        from ..core.metrics import Counters
        self.plane = plane
        self.config = plane.config
        self.delim = delim
        self.counters = counters if counters is not None else Counters()
        self.supervisor = supervisor
        self.name = name
        self._held_acks: List[str] = []
        if supervisor is not None:
            supervisor.attach(plane)

    # ---- labels --------------------------------------------------------
    def decision_label(self, decision: Tuple[int, float, int]) -> str:
        arm, prob, cls = decision
        cfg = self.config
        if cfg.head == "logistic":
            return cfg.pos_label if prob >= cfg.threshold \
                else cfg.neg_label
        if cfg.head == "mlp":
            return cfg.mlp_label(cls)
        return cfg.actions[arm]

    def outcome_label(self, value: float) -> str:
        cfg = self.config
        if cfg.head == "mlp":
            return cfg.mlp_label(int(value))
        # logistic AND bandit: a positive outcome is the positive class
        # (for the bandit head this turns the accuracy floor into a
        # mean-reward floor — the regret guardrail, TPU_NOTES §31)
        return cfg.pos_label if value >= cfg.threshold else cfg.neg_label

    # ---- the window ----------------------------------------------------
    def process_window(self, messages: Sequence[str]
                       ) -> Tuple[List[str], List[str]]:
        """One served window: parse, dispatch once, answer.

        Returns ``(replies, ready_reward_acks)`` — replies are
        ``<id><delim><label>`` lines in request order; the ack tokens
        are the reward leases now safe to release (see module doc).
        """
        import warnings
        cfg = self.config
        d = self.delim
        requests: List[Tuple[str, np.ndarray]] = []
        rewards: List[Tuple[str, float]] = []
        new_acks: List[str] = []
        bad = 0
        for msg in messages:
            parts = msg.split(d)
            verb = parts[0]
            if verb == "predict" and len(parts) >= 2 and parts[1]:
                fields = parts[2:]
                if len(fields) != cfg.n_features:
                    bad += 1
                    continue
                try:
                    row = np.asarray([float(f) for f in fields],
                                     np.float32)
                except ValueError:
                    bad += 1
                    continue
                requests.append((parts[1], row))
            elif verb == REWARD_VERB:
                # reward,<id>,<value> — exactly three fields, finite
                # value; anything else is a bad request (and the near
                # miss family the wire fuzz pins)
                if len(parts) != 3 or not parts[1]:
                    bad += 1
                    continue
                try:
                    val = float(parts[2])
                except ValueError:
                    bad += 1
                    continue
                if not math.isfinite(val):
                    bad += 1
                    continue
                rewards.append((parts[1], val))
                new_acks.append(reward_ack_token(parts[1], d))
            elif verb == STOP_VERB:
                continue                  # the loop's token, not ours
            else:
                bad += 1
        if bad:
            self.counters.increment("Online", "BadRequests", bad)
            warnings.warn(f"online learner {self.name!r}: {bad} "
                          f"malformed message(s) dropped", RuntimeWarning)
        decisions: List[Tuple[str, int, float, int]] = []
        outcomes: List[Tuple[Tuple[int, float, int], float]] = []
        if requests or rewards:
            decisions, outcomes = self.plane.run_window(requests,
                                                        rewards)
        replies = [f"{rid}{d}{self.decision_label((arm, prob, cls))}"
                   for rid, arm, prob, cls in decisions]
        self.counters.increment("Online", "Windows", 1)
        self.counters.increment("Online", "Requests", len(requests))
        self.counters.increment("Online", "Rewards", len(rewards))
        self._held_acks.extend(new_acks)
        snapshot_committed = False
        if self.supervisor is not None:
            pred = [self.decision_label(dec) for dec, _ in outcomes]
            actual = [self.outcome_label(val) for _, val in outcomes]
            events = self.supervisor.on_window(pred, actual) or {}
            snapshot_committed = bool(events.get("snapshot"))
        ready: List[str] = []
        if self.supervisor is None or snapshot_committed:
            ready, self._held_acks = self._held_acks, []
        return replies, ready

    def flush_acks(self) -> List[str]:
        """Release every held reward ack (shutdown path: the final
        snapshot has been taken, or the caller accepts redelivery)."""
        ready, self._held_acks = self._held_acks, []
        return ready

    # ---- observability -------------------------------------------------
    def stats(self) -> dict:
        s = self.plane.run_stats()
        s["held_acks"] = len(self._held_acks)
        if self.supervisor is not None:
            s.update(self.supervisor.stats())
        return s

    def export(self, counters=None) -> None:
        c = counters if counters is not None else self.counters
        self.plane.export(c)
        for k, v in self.plane.pending.stats().items():
            c.set("Online", k.capitalize(), v)

    def bind_metrics(self, registry) -> None:
        """``avenir_online_*`` gauges over the live service (the §21
        registry probe discipline: refreshed per scrape)."""
        g = registry.gauge(
            "avenir_online_state",
            "online learning plane state (windows, pending joins, "
            "reward accounting, supervisor counts)",
            labels=("learner", "key"))

        def probe():
            for k, v in self.stats().items():
                g.set(v, learner=self.name, key=k)
        registry.register_probe(probe)


class OnlineRespLoop:
    """Drain one RESP stream of mixed predict/reward traffic through
    the service: leased delivery in, ``ackpush`` replies out (reply +
    predict-lease ack in one trip), reward acks released on the
    snapshot cadence.  A worker killed mid-window never acked — its
    whole window redelivers after the lease expires."""

    def __init__(self, service: OnlineLearnerService, client,
                 request_queue: str = "requestQueue",
                 reply_queue: str = "predictionQueue",
                 reward_ack_queue: str = "rewardAckQueue",
                 batch: int = 64, lease_s: float = 30.0,
                 block_s: float = 0.05):
        self.service = service
        self.client = client
        self.request_queue = request_queue
        self.reply_queue = reply_queue
        self.reward_ack_queue = reward_ack_queue
        self.batch = int(batch)
        self.lease_s = float(lease_s)
        self.block_s = float(block_s)

    def run(self, max_windows: Optional[int] = None) -> int:
        windows = 0
        while max_windows is None or windows < max_windows:
            msgs = self.client.lease_many(self.request_queue, self.batch,
                                          self.lease_s,
                                          block_s=self.block_s)
            if not msgs:
                if max_windows is None:
                    break
                continue
            stop = STOP_VERB in msgs
            msgs = [m for m in msgs if m != STOP_VERB]
            if msgs:
                replies, acks = self.service.process_window(msgs)
                if replies:
                    self.client.ackpush(self.reply_queue,
                                        self.request_queue, replies)
                if acks:
                    self.client.ackpush(self.reward_ack_queue,
                                        self.request_queue, acks)
                windows += 1
            if stop:
                final = self.service.flush_acks()
                if final:
                    self.client.ackpush(self.reward_ack_queue,
                                        self.request_queue, final)
                break
        return windows
