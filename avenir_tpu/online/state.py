"""Device-resident learner state + its deterministic byte round trip.

The state is the carry tuple of the fused window pipeline — three
pytrees, one per stage:

* ``bandit``  — per-arm count / reward-sum / reward-sum-sq arrays
  (``reinforce.online_forms.init_arm_stats``), the device twin of the
  host learners' ``ActionStat`` table;
* ``weights`` — the SGD family: the logistic coefficient vector
  (intercept first, ``regress.logistic`` layout) and, when an MLP head
  is configured, the ``nn.mlp`` parameter pytree;
* ``rng``     — the threaded ``jax.random`` key plus the window step
  counter (randomized selection must be resumable: a restored snapshot
  replays the SAME key stream).

Serialization is deliberately not ``np.savez``: zip members carry
timestamps, and the supervisor's rollback contract is BIT-identical
bytes (snapshot → restore → snapshot must round-trip exactly, and the
chaos drill compares raw sidecar payloads).  The format is a JSON
header naming each leaf (path, dtype, shape) followed by the raw
``tobytes`` payloads in header order.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

import numpy as np

_MAGIC = b"AVONL1\n"


@dataclass(frozen=True)
class OnlineLearnerConfig:
    """Shape of the online learner: which heads exist and their sizes.
    The config fingerprints the pipeline (stage versions + carry
    signatures), so two services with the same config share one
    compiled program through the ProgramCache."""

    actions: Tuple[str, ...]              # bandit arm names (>= 1)
    n_features: int = 0                   # numeric features per request
    algorithm: str = "ucb1"               # ucb1 | softMax | sampsonSampler
    head: str = "bandit"                  # bandit | logistic | mlp
    temp_constant: float = 0.1            # softMax temperature
    learning_rate: float = 0.05
    l2: float = 0.0
    mlp_hidden: int = 0                   # > 0 adds the MLP head
    mlp_classes: int = 2
    pos_label: str = "1"                  # logistic head reply labels
    neg_label: str = "0"
    threshold: float = 0.5
    seed: int = 42
    labels: Tuple[str, ...] = ()          # mlp head reply labels

    def __post_init__(self):
        from ..reinforce.online_forms import ONLINE_ALGORITHMS
        if not self.actions:
            raise ValueError("OnlineLearnerConfig needs >= 1 action")
        if self.algorithm not in ONLINE_ALGORITHMS:
            raise ValueError(
                f"algorithm {self.algorithm!r} has no device form; "
                f"known: {ONLINE_ALGORITHMS}")
        if self.head not in ("bandit", "logistic", "mlp"):
            raise ValueError(f"unknown head {self.head!r}")
        if self.head == "mlp" and self.mlp_hidden <= 0:
            raise ValueError("head='mlp' needs mlp_hidden > 0")
        if self.mlp_hidden > 0 and self.n_features <= 0:
            raise ValueError("an MLP head needs n_features > 0")

    @property
    def n_arms(self) -> int:
        return len(self.actions)

    @property
    def design_width(self) -> int:
        """Logistic design-matrix width: intercept + features."""
        return self.n_features + 1

    def fingerprint(self) -> str:
        return (f"online:{self.algorithm}:{self.head}:{self.n_arms}"
                f":{self.n_features}:{self.mlp_hidden}"
                f":{self.mlp_classes}")

    def mlp_label(self, idx: int) -> str:
        if self.labels and idx < len(self.labels):
            return self.labels[idx]
        return str(idx)


def init_state(config: OnlineLearnerConfig) -> Tuple[Any, Any, Any]:
    """Fresh carry tuple (bandit, weights, rng) as host arrays — the
    pipeline uploads them on first dispatch."""
    import jax
    from ..reinforce.online_forms import init_arm_stats
    bandit = init_arm_stats(config.n_arms)
    weights: Dict[str, Any] = {
        "w": np.zeros(config.design_width, np.float32)}
    if config.mlp_hidden > 0:
        from ..nn.mlp import MLPConfig, init_params
        mcfg = MLPConfig(hidden_dim=config.mlp_hidden,
                         n_classes=config.mlp_classes,
                         seed=config.seed)
        params = init_params(config.n_features, mcfg)
        weights["mlp"] = {k: np.asarray(v, np.float32)
                          for k, v in params.items()}
    rng = {"key": np.asarray(jax.random.PRNGKey(config.seed)),
           "step": np.int32(0)}
    return bandit, weights, rng


# ---- deterministic byte round trip ------------------------------------

def _flatten(carries) -> List[Tuple[str, np.ndarray]]:
    import jax
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(carries)[0]
    out = []
    for path, leaf in leaves_with_paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out.append((key, np.asarray(leaf)))
    return out


def state_to_bytes(carries) -> bytes:
    """Serialize a carry tuple to deterministic bytes (same state →
    same bytes, always — the rollback bit-identity pin)."""
    leaves = _flatten(carries)
    header = [{"path": k, "dtype": str(a.dtype), "shape": list(a.shape)}
              for k, a in leaves]
    hdr = json.dumps(header, sort_keys=True,
                     separators=(",", ":")).encode()
    parts = [_MAGIC, struct.pack("<I", len(hdr)), hdr]
    for _, a in leaves:
        parts.append(np.ascontiguousarray(a).tobytes())
    return b"".join(parts)


def state_from_bytes(payload: bytes, template) -> Any:
    """Rebuild a carry tuple from :func:`state_to_bytes` output.  The
    ``template`` (a freshly-initialized carry tuple of the same config)
    supplies the tree structure; every leaf must match the serialized
    dtype/shape or the restore is refused — a silent mismatch would
    retrace the pipeline or corrupt state."""
    import jax
    if not payload.startswith(_MAGIC):
        raise ValueError("not an online learner state payload")
    off = len(_MAGIC)
    (hlen,) = struct.unpack_from("<I", payload, off)
    off += 4
    header = json.loads(payload[off:off + hlen].decode())
    off += hlen
    t_leaves = _flatten(template)
    if [h["path"] for h in header] != [k for k, _ in t_leaves]:
        raise ValueError(
            f"state layout mismatch: payload has "
            f"{[h['path'] for h in header]}, template has "
            f"{[k for k, _ in t_leaves]}")
    leaves = []
    for h, (key, t) in zip(header, t_leaves):
        dt = np.dtype(h["dtype"])
        shape = tuple(h["shape"])
        if dt != t.dtype or shape != t.shape:
            raise ValueError(
                f"leaf {key!r}: payload {dt}{shape} vs template "
                f"{t.dtype}{t.shape}")
        n = dt.itemsize * int(np.prod(shape, dtype=np.int64)) \
            if shape else dt.itemsize
        arr = np.frombuffer(payload[off:off + n],
                            dtype=dt).reshape(shape).copy()
        off += n
        leaves.append(arr)
    if off != len(payload):
        raise ValueError(f"trailing bytes in state payload "
                         f"({len(payload) - off})")
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, leaves)
