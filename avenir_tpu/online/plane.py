"""The fused serve+learn window program + the reward join table.

One served window = ONE device dispatch.  The window pipeline is a
three-stage :class:`~avenir_tpu.pipeline.compiler.ChunkPipeline`
(dispatch site ``online.window``) whose carries ARE the learner state:

* ``absorb``  — scatter this window's joined rewards into the
  device-resident bandit arm statistics (the carry), forwarding the
  updated arrays downstream;
* ``learn``   — one SGD step of the logistic weights (and the MLP
  parameters when configured) on the rewarded rows, calling the SAME
  gradient bodies the offline trainers jit
  (``LogisticTrainer._partials_impl`` / ``_combine_impl``,
  ``nn.mlp.forward_logits``), forwarding the updated weights;
* ``predict`` — score the window's requests with the JUST-updated
  state: bandit arm selection through the shared score bodies
  (``reinforce.online_forms``), logistic probabilities, MLP classes.
  Carries the threaded PRNG key.

Stage order is absorb → learn → predict deliberately: rewards that
arrived before the window are absorbed first, so predictions always use
the freshest state without a second dispatch.

Rewards are joined to the decisions they reward on the HOST, by request
id, in a bounded :class:`PendingOutcomeTable` with TTL shedding.  The
join cannot live on device: a reward may arrive any number of windows
after its request (or never), so the id → (features, decision) map is
unbounded-in-time state with string keys — exactly what HBM carries are
wrong for.  The device sees only the joined, padded (arm, value,
features) rows.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..pipeline.compiler import ONLINE_SITE, ChunkPipeline, Stage
from .state import OnlineLearnerConfig, init_state, state_from_bytes, \
    state_to_bytes

DEFAULT_WINDOW_BUCKETS = (8, 64, 256)

_STAGE_VERSION = "1"


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class PendingOutcomeTable:
    """Bounded id → (features, chosen arm) map awaiting rewards.

    ``put`` on a full table evicts the oldest entry (Evicted); ``join``
    pops the entry for a reward id (a miss is an orphan — the request
    was never seen, already rewarded, or already shed); ``shed`` drops
    entries older than the TTL (Shed).  All three outcomes are counted
    — a silently vanishing reward would void the learning guarantees.
    """

    def __init__(self, capacity: int = 4096, ttl_s: float = 300.0,
                 clock=time.monotonic):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self.capacity = int(capacity)
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._entries: "OrderedDict[str, Tuple[np.ndarray, Any, float]]" \
            = OrderedDict()
        self.evicted = 0
        self.shed = 0
        self.orphans = 0
        self.joined = 0

    def __len__(self) -> int:
        return len(self._entries)

    def put(self, rid: str, x: np.ndarray, decision: Any) -> None:
        if rid in self._entries:          # re-decision: newest wins
            self._entries.pop(rid)
        elif len(self._entries) >= self.capacity:
            self._entries.popitem(last=False)
            self.evicted += 1
        self._entries[rid] = (x, decision, self._clock())

    def join(self, rid: str) -> Optional[Tuple[np.ndarray, Any]]:
        ent = self._entries.pop(rid, None)
        if ent is None:
            self.orphans += 1
            return None
        self.joined += 1
        return ent[0], ent[1]

    def shed_expired(self) -> int:
        """Drop entries past the TTL (insertion order == age order)."""
        if self.ttl_s <= 0:
            return 0
        cutoff = self._clock() - self.ttl_s
        n = 0
        while self._entries:
            rid, (_, _, t) = next(iter(self._entries.items()))
            if t > cutoff:
                break
            self._entries.popitem(last=False)
            n += 1
        self.shed += n
        return n

    def stats(self) -> Dict[str, int]:
        return {"pending": len(self._entries), "joined": self.joined,
                "orphans": self.orphans, "shed": self.shed,
                "evicted": self.evicted}


class OnlineWindowPlane:
    """Owns the fused window pipeline + the pending-outcome table.

    ``run_window(requests, rewards)`` is the whole hot path: join the
    rewards, pad both sides to shape buckets, ONE ``run_chunk``
    dispatch, one stacked readback, record the new decisions as
    pending.  Every window with the same (request-bucket,
    reward-bucket) pair reuses one compiled program via the
    process-global ProgramCache — a warm service retraces nothing.
    """

    def __init__(self, config: OnlineLearnerConfig, ctx=None,
                 cache=None, buckets: Sequence[int] = DEFAULT_WINDOW_BUCKETS,
                 pending_capacity: int = 4096, pending_ttl_s: float = 300.0,
                 clock=time.monotonic):
        self.config = config
        wanted = tuple(sorted(set(int(b) for b in buckets)))
        if not wanted or wanted[0] < 1:
            raise ValueError(f"bad window buckets {buckets!r}")
        self.pending = PendingOutcomeTable(pending_capacity,
                                          pending_ttl_s, clock=clock)
        self.windows = 0
        self._pipeline = ChunkPipeline(
            self._build_stages(), ctx=ctx,
            schema_fp=config.fingerprint(), cache=cache,
            name="online-window", site=ONLINE_SITE)
        # the row-sharded upload contract is "row count pre-padded to
        # the mesh" (shard_rows), so every bucket rounds up to a
        # multiple of the device count
        nd = max(int(self._pipeline.ctx.n_devices), 1)
        self.buckets = tuple(sorted(set(
            ((b + nd - 1) // nd) * nd for b in wanted)))

    # ---- stage kernels -------------------------------------------------
    def _build_stages(self) -> List[Stage]:
        cfg = self.config
        bandit0, weights0, rng0 = init_state(cfg)

        def absorb_kernel(carry, consts, inputs, upstream):
            from ..reinforce.online_forms import absorb_rewards
            counts, totals, total_sqs = absorb_rewards(
                carry["counts"], carry["totals"], carry["total_sqs"],
                inputs["r_arm"], inputs["r_val"], inputs["r_mask"])
            nc = {"counts": counts, "totals": totals,
                  "total_sqs": total_sqs}
            return nc, dict(nc)

        def learn_kernel(carry, consts, inputs, upstream):
            import jax
            import jax.numpy as jnp
            from ..regress.logistic import LogisticTrainer
            X, vals, m = inputs["r_x"], inputs["r_val"], inputs["r_mask"]
            n = m.sum()
            any_rows = n > 0
            # logistic: outcome >= threshold is the positive class; the
            # gradient bodies are the offline trainer's own (padded rows
            # are all-zero X, so their x*(y-p) terms vanish)
            y = (vals >= cfg.threshold).astype(jnp.float32) * m
            grad_sum, _ll = LogisticTrainer._partials_impl(
                None, carry["w"], X, y)
            # padded rows still contribute to _partials_impl's y-p term
            # through the intercept-free zero rows ONLY via y, which the
            # mask already zeroed; the intercept column is zeroed on
            # padded rows by the host prepare
            w_new = _combine(carry["w"], grad_sum, jnp.maximum(n, 1.0))
            nc = {"w": jnp.where(any_rows, w_new, carry["w"])}
            outs = {"w": nc["w"]}
            if "mlp" in carry:
                from ..nn.mlp import forward_logits
                y_cls = jnp.clip(vals.astype(jnp.int32), 0,
                                 cfg.mlp_classes - 1)
                Xf = X[:, 1:]             # MLP sees raw features

                def raw_loss(p):
                    logits = forward_logits(p, Xf)
                    logp = jax.nn.log_softmax(logits, axis=-1)
                    ce = -(logp[jnp.arange(Xf.shape[0]), y_cls]
                           * m).sum()
                    reg = 0.5 * cfg.l2 * ((p["W1"] ** 2).sum()
                                          + (p["W2"] ** 2).sum())
                    return ce + reg

                grads = jax.grad(raw_loss)(carry["mlp"])
                stepped = jax.tree_util.tree_map(
                    lambda p, g: p - cfg.learning_rate * g,
                    carry["mlp"], grads)
                nc["mlp"] = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(any_rows, a, b),
                    stepped, carry["mlp"])
                outs["mlp"] = nc["mlp"]
            return nc, outs

        def _combine(w, grad_sum, n):
            # LogisticTrainer._combine_impl body with this config's
            # hyper-parameters (the method reads them off self.params)
            grad = grad_sum - cfg.l2 * w
            return w + cfg.learning_rate * grad / n

        def predict_kernel(carry, consts, inputs, upstream):
            import jax
            import jax.numpy as jnp
            from ..reinforce.online_forms import bandit_scores
            X = inputs["x"]
            key, sub = jax.random.split(carry["key"])
            scores = bandit_scores(
                cfg.algorithm, upstream["absorb.counts"],
                upstream["absorb.totals"], upstream["absorb.total_sqs"],
                sub, X.shape[0], cfg.temp_constant)
            outs: Dict[str, Any] = {
                "arm": jnp.argmax(scores, axis=1).astype(jnp.int32),
                "prob": jax.nn.sigmoid(X @ upstream["learn.w"]),
            }
            if "learn.mlp" in upstream:
                from ..nn.mlp import forward_logits
                logits = forward_logits(upstream["learn.mlp"], X[:, 1:])
                outs["cls"] = jnp.argmax(logits, axis=1).astype(jnp.int32)
            nc = {"key": key, "step": carry["step"] + 1}
            return nc, outs

        returns = ("arm", "prob") + (("cls",)
                                     if "mlp" in weights0 else ())
        return [
            Stage(name="absorb", kernel=absorb_kernel,
                  version=_STAGE_VERSION,
                  carry_init=lambda: bandit0),
            Stage(name="learn", kernel=learn_kernel,
                  version=_STAGE_VERSION,
                  carry_init=lambda: weights0),
            Stage(name="predict", kernel=predict_kernel,
                  version=_STAGE_VERSION,
                  carry_init=lambda: rng0, returns=returns),
        ]

    # ---- the window ----------------------------------------------------
    def run_window(self, requests: Sequence[Tuple[str, np.ndarray]],
                   rewards: Sequence[Tuple[str, float]]
                   ) -> Tuple[List[Tuple[str, int, float, int]],
                              List[Tuple[Tuple[int, float, int], float]]]:
        """One fused dispatch over a served window.

        ``requests``: (request id, feature row) pairs — the row is the
        raw numeric feature vector, ``n_features`` wide.
        ``rewards``: (request id, outcome value) pairs, joined against
        the pending table; unknown ids count as orphans.

        Returns ``(decisions, outcomes)``: one ``(rid, arm, prob,
        cls)`` decision per request (cls is -1 without an MLP head),
        recorded as pending; and one ``(decision, value)`` outcome per
        successfully joined reward, ``decision`` being the (arm, prob,
        cls) the rewarded request was answered with — the supervisor's
        predicted-vs-actual feed.
        """
        cfg = self.config
        W = cfg.design_width
        joined: List[Tuple[int, float, np.ndarray]] = []
        outcomes: List[Tuple[Tuple[int, float, int], float]] = []
        for rid, val in rewards:
            ent = self.pending.join(rid)
            if ent is not None:
                joined.append((ent[1][0], float(val), ent[0]))
                outcomes.append((ent[1], float(val)))
        self.pending.shed_expired()

        B = _bucket(max(len(requests), 1), self.buckets)
        R = _bucket(max(len(joined), 1), self.buckets)
        x = np.zeros((B, W), np.float32)
        for i, (_, row) in enumerate(requests):
            x[i, 0] = 1.0
            if cfg.n_features:
                x[i, 1:] = row
        r_x = np.zeros((R, W), np.float32)
        r_arm = np.zeros(R, np.int32)
        r_val = np.zeros(R, np.float32)
        r_mask = np.zeros(R, np.float32)
        for i, (arm, val, row) in enumerate(joined):
            r_x[i] = row
            r_arm[i] = arm
            r_val[i] = val
            r_mask[i] = 1.0
        inputs = self._pipeline.upload({
            "x": x, "r_x": r_x, "r_arm": r_arm, "r_val": r_val,
            "r_mask": r_mask})
        rets = self._pipeline.run_chunk(inputs)
        arms = np.asarray(rets["predict.arm"])
        probs = np.asarray(rets["predict.prob"])
        cls = np.asarray(rets["predict.cls"]) \
            if "predict.cls" in rets else None
        self.windows += 1
        out = []
        for i, (rid, row) in enumerate(requests):
            decision = (int(arms[i]), float(probs[i]),
                        int(cls[i]) if cls is not None else -1)
            # the decision row joins its future reward: store the
            # DESIGN row (intercept set) so the learn stage gets it
            self.pending.put(rid, x[i].copy(), decision)
            out.append((rid,) + decision)
        return out, outcomes

    # ---- state access (supervisor hooks) -------------------------------
    @property
    def carries(self):
        return self._pipeline.carries

    def state_bytes(self) -> bytes:
        return state_to_bytes(self._pipeline.carries)

    def restore(self, payload: bytes) -> None:
        template = tuple(init_state(self.config))
        self._pipeline.install_carries(
            state_from_bytes(payload, template))

    def logistic_w(self) -> np.ndarray:
        """The logistic coefficient vector as a host array — the
        registry snapshot's model payload."""
        return np.asarray(self._pipeline.carries[1]["w"],
                          dtype=np.float32)

    def run_stats(self) -> Dict[str, int]:
        s = self._pipeline.run_stats()
        s["windows"] = self.windows
        s.update(self.pending.stats())
        return s

    def export(self, counters, group: str = "OnlineProgramCache") -> None:
        self._pipeline.export(counters, group=group)
