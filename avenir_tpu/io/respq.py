"""Minimal Redis-protocol (RESP2) queue transport for the serving loop.

The reference's online RL rides Redis lists as queues: the Storm spout
``rpop``s the event and reward queues and the action writer ``lpush``es
``<eventID>,<action...>`` lines (storm/RedisSpout.java:30-95,
RedisActionWriter.java:47-61).  This module provides both halves of that
contract with no external dependency:

  * :class:`RespServer` — a threaded TCP server speaking the RESP2 subset
    the queue contract needs (LPUSH, RPOP, BRPOP, LLEN, DEL, PING, INFO),
    backed by in-memory deques.  A real ``redis-cli``/client library can
    talk to it.
  * :class:`RespClient` — a blocking client usable against this server OR
    a real Redis instance (the wire format is the same), exposing exactly
    the three verbs the reference uses.  A dropped TCP connection
    mid-call reconnects once with backoff instead of poisoning the
    client (see :meth:`RespClient._call`).
  * :class:`ShardedRespClient` — the horizontal broker tier: one client
    over M RESP endpoints, consistent-hashing request ids across the
    ring (:class:`HashRing`) with per-shard pipelining on every fan-out
    verb.  Requests and their replies share an id, so they land on the
    SAME shard and reassembly is just collection.  A dead shard degrades
    the client to the surviving ring (structured warning + a
    ``Broker/BrokerShardDown`` counter) — values from a failed push are
    re-routed, never dropped.

Durability + delivery guarantees (ISSUE 17): the server optionally
journals every accepted mutation (``durable=commit|fsync``,
``io/qjournal.py``) and replays it on restart, and the ``LEASE`` /
``ACKPUSH`` verbs replace destructive pops with visibility-timeout
leases whose ack piggybacks on the batched reply push — at-least-once
delivery, upgraded to exactly-once EFFECT by request-id reply dedup
(server-side answered set + the shared consumer-side
:func:`dedup_replies`).  ``durable=off`` + the classic verbs remain
byte-identical to the pre-durability wire (pinned by golden tests).

Security note: like stock Redis, there is no auth — bind to loopback
(the default) or a trusted network only.
"""

from __future__ import annotations

import bisect
import hashlib
import os
import socket
import socketserver
import threading
import time
import warnings
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..core.metrics import Counters
from ..telemetry import instant
from ..telemetry import reqtrace
from . import native_wire
from . import qjournal

DURABLE_ENV = "AVENIR_TPU_BROKER_DURABLE"
DURABLE_MODES = ("off", "commit", "fsync")


def resolve_durable(value: Optional[str] = None) -> str:
    """The ``ps.broker.durable`` knob / ``AVENIR_TPU_BROKER_DURABLE``
    env twin: ``off`` (today's bytes and behavior, the default),
    ``commit`` (journal write+flush per accepted batch — survives
    process kill), ``fsync`` (plus fsync — survives power loss)."""
    mode = (value if value is not None
            else os.environ.get(DURABLE_ENV) or "off").strip().lower()
    if mode not in DURABLE_MODES:
        raise ValueError(
            f"broker durable mode must be one of {DURABLE_MODES}, "
            f"got {value!r}")
    return mode


def _lease_rid(value: str, delim: str) -> Optional[str]:
    """The lease identity of a queued value: request messages
    (``predict``/``predictq``) lease by their id field; reward messages
    (``reward,<id>,<value>``) lease by ``reward:<id>`` — a verb-scoped
    key, because a reward for request ``<id>`` must coexist in the
    pending set with the prediction lease of the same ``<id>`` (the
    online learner acks predictions by reply id and rewards by the
    snapshot-gated ``reward:<id>`` token); anything else (control words
    like ``stop``/``reload``, malformed lines) has no identity and is
    delivered destructively, exactly as before."""
    parts = value.split(delim, 2)
    if parts[0] in ("predict", "predictq") and len(parts) > 1 and parts[1]:
        return parts[1]
    if parts[0] == "reward" and len(parts) > 1 and parts[1]:
        return f"reward:{parts[1]}"
    return None


def dedup_replies(values: Sequence[str], delim: str = ","
                  ) -> Tuple[Dict[str, str], int]:
    """First-wins reply dedup by request id — the consumer half of the
    exactly-once contract (at-least-once delivery + idempotent effect).
    Returns ``({rid: reply_tail}, duplicates_dropped)`` where the tail
    is the reply with its id stripped (the label for ``<id>,<label>``).
    Shared by the CLI reply collector, the drills, and any client
    reassembling replies from the ring."""
    by_id: Dict[str, str] = {}
    dups = 0
    for v in values:
        rid, _, rest = v.partition(delim)
        if rid in by_id:
            dups += 1
            continue
        by_id[rid] = rest
    return by_id, dups


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def _encode_command(args: List[str]) -> bytes:
    """Client -> server: RESP array of bulk strings."""
    out = [f"*{len(args)}\r\n".encode()]
    for a in args:
        b = a.encode()
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


def _read_line(rf) -> bytes:
    line = rf.readline()
    if not line:
        raise ConnectionError("peer closed")
    return line.rstrip(b"\r\n")


def _read_reply(rf):
    """Parse one RESP reply: +simple, -error, :int, $bulk (None for -1),
    *array."""
    line = _read_line(rf)
    kind, rest = line[:1], line[1:]
    if kind == b"+":
        return rest.decode()
    if kind == b"-":
        raise RuntimeError(f"server error: {rest.decode()}")
    if kind == b":":
        return int(rest)
    if kind == b"$":
        n = int(rest)
        if n == -1:
            return None
        body = rf.read(n + 2)[:n]
        return body.decode()
    if kind == b"*":
        n = int(rest)
        if n == -1:
            return None
        return [_read_reply(rf) for _ in range(n)]
    raise RuntimeError(f"unparseable reply {line!r}")


def _read_command(rf) -> Optional[List[str]]:
    """Server side: one client command (RESP array of bulk strings, plus
    the inline fallback real Redis also accepts)."""
    line = rf.readline()
    if not line:
        return None
    line = line.rstrip(b"\r\n")
    if not line:
        return []
    if line[:1] == b"*":
        n = int(line[1:])
        args = []
        for _ in range(n):
            hdr = _read_line(rf)
            if hdr[:1] != b"$":
                raise RuntimeError(f"expected bulk string, got {hdr!r}")
            ln = int(hdr[1:])
            args.append(rf.read(ln + 2)[:ln].decode())
        return args
    return line.decode().split()  # inline command


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        srv: "RespServer" = self.server.owner  # type: ignore[attr-defined]
        srv._track(self.connection, add=True)
        try:
            while True:
                try:
                    args = _read_command(self.rfile)
                except (ConnectionError, ValueError, RuntimeError, OSError):
                    return
                if args is None:
                    return
                if not args:
                    continue
                try:
                    self.wfile.write(srv.dispatch(args))
                    self.wfile.flush()
                except OSError:
                    return   # peer (or kill()) closed the socket mid-reply
        finally:
            srv._track(self.connection, add=False)


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class RespServer:
    """In-memory Redis-list queue server.  ``start()`` binds and serves on
    a daemon thread; ``port`` is resolved after start (pass 0 for an
    ephemeral port).

    Durability (ISSUE 17): with ``durable`` in ``commit``/``fsync`` every
    queue mutation is journaled (``io/qjournal.py``) under ``journal_dir``
    BEFORE the in-memory deque mutates, and ``start()`` replays the
    journal — a killed-and-restarted shard (same dir) comes back with
    exactly the accepted-but-unanswered set.  ``off`` (default) is
    byte-for-byte today's broker.

    Leases: the ``LEASE`` verb delivers request messages under a
    visibility-timeout lease instead of a destructive pop (Redis
    ``RPOPLPUSH``-style reliable delivery).  ``ACKPUSH`` pushes a batch
    of replies AND acks the leases their request ids held — the ack
    piggybacks on the reply trip, so the worker's crash window closes
    without extra round trips.  An expired lease re-enqueues at the POP
    end (redelivered before fresh traffic — age order), and replies for
    already-acked ids are dropped server-side (first wins).  Leases work
    with or without the journal; together they give exactly-once
    EFFECT without the pushing client re-offering."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 durable: Optional[str] = None,
                 journal_dir: Optional[str] = None,
                 counters: Optional[Counters] = None,
                 acked_cap: int = 65536,
                 journal_segment_bytes: int = 4 << 20):
        self.host, self.port = host, port
        self.durable = resolve_durable(durable)
        if self.durable != "off" and not journal_dir:
            raise ValueError(
                f"durable={self.durable!r} needs a journal_dir")
        self.journal_dir = journal_dir
        self.counters = counters if counters is not None else Counters()
        # queues hold (seq, value): seq is the journal identity of one
        # accepted value — assigned even with the journal off, so leases
        # and durability compose without a format switch
        self._queues: Dict[str, deque] = {}
        self._next_seq = 1
        # queue -> rid -> (seq, value, expiry_monotonic): outstanding
        # leases; queue -> OrderedDict(rid -> True): answered ids (the
        # server half of reply dedup), bounded at acked_cap first-in
        # first-evicted — an id evicted here can in principle dup past
        # the broker, which is why consumers ALSO dedup (dedup_replies)
        self._leases: Dict[str, Dict[str, Tuple[int, str, float]]] = {}
        self._acked: Dict[str, "OrderedDict[str, bool]"] = {}
        self._acked_cap = int(acked_cap)
        self._journal: Optional[qjournal.QueueJournal] = None
        self._journal_segment_bytes = int(journal_segment_bytes)
        self._journal_errors = 0
        self.redelivered = 0
        self.journal_replayed = 0
        self.dup_replies_dropped = 0
        # a Condition so BRPOP can park its handler thread until an LPUSH
        # arrives (ThreadingTCPServer: blocking one handler blocks only
        # that client's connection); its lock is the queues lock
        self._lock = threading.Condition()
        self._server: Optional[_TCPServer] = None
        self._thread: Optional[threading.Thread] = None
        # live client sockets, so kill() can sever them the way a dead
        # broker process would (stop() alone only closes the listener;
        # established connections would keep serving from the ghost)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # flipped by kill(): parked BRPOP handlers re-check it on every
        # wakeup, so severing the sockets can't leave a ghost waiter
        # parked on the condition for the life of the process
        self._killed = False

    def _track(self, conn, add: bool) -> None:
        with self._conns_lock:
            if add:
                self._conns.add(conn)
            else:
                self._conns.discard(conn)

    # ---- durability plumbing ----
    def _journal_batch(self, payloads: List[bytes]) -> None:
        """Append encoded records; a journal that cannot write degrades
        the shard to in-memory with a warning instead of refusing
        traffic (availability-first — the drills pin replay, not
        refusal)."""
        if self._journal is None or not payloads:
            return
        try:
            self._journal.append(payloads)
        except (OSError, MemoryError) as exc:
            self._journal_errors += 1
            self.counters.increment("Broker", "JournalWriteErrors")
            if self._journal_errors == 1:
                warnings.warn(
                    f"respq: journal write failed "
                    f"({type(exc).__name__}: {exc}); shard continues "
                    "IN-MEMORY (durability degraded)", RuntimeWarning)

    def _journal_snapshot(self) -> Tuple[dict, dict, int]:
        """Rotation checkpoint source: every outstanding value — queued
        OR under lease (leased-not-acked is still unanswered work) —
        plus the acked-id sets, oldest-first by seq."""
        with self._lock:
            queues: Dict[str, List[Tuple[int, str]]] = {
                k: sorted(q, key=lambda it: it[0])
                for k, q in self._queues.items()}
            for k, tab in self._leases.items():
                if not tab:
                    continue
                items = queues.setdefault(k, [])
                items.extend((seq, v) for seq, v, _exp in tab.values())
                items.sort(key=lambda it: it[0])
            acked = {k: list(od) for k, od in self._acked.items() if od}
            return queues, acked, self._next_seq

    def _trim_acked(self, od: "OrderedDict[str, bool]") -> None:
        while len(od) > self._acked_cap:
            od.popitem(last=False)

    # ---- leases ----
    def _expire_locked(self, key: str) -> List[Tuple[str, str]]:
        """Re-enqueue expired leases of ``key`` at the POP end (served
        before fresh traffic — redelivery honors request age).  Returns
        ``(queue, rid)`` pairs for instant emission OUTSIDE the lock."""
        tab = self._leases.get(key)
        if not tab:
            return []
        now = time.monotonic()
        expired = [rid for rid, ent in tab.items() if ent[2] <= now]
        if not expired:
            return []
        q = self._queues.setdefault(key, deque())
        out = []
        for rid in expired:
            seq, v, _exp = tab.pop(rid)
            q.append((seq, v))
            out.append((key, rid))
        self.redelivered += len(out)
        self.counters.increment("Broker", "Redelivered", len(out))
        self._lock.notify_all()
        return out

    def _next_expiry_locked(self, key: str) -> Optional[float]:
        tab = self._leases.get(key)
        if not tab:
            return None
        return min(ent[2] for ent in tab.values())

    @staticmethod
    def _note_redelivered(red: List[Tuple[str, str]]) -> None:
        for key, rid in red:
            instant("broker.redeliver", cat="broker", queue=key, rid=rid)

    # ---- command dispatch (the RESP subset the queue contract uses) ----
    def dispatch(self, args: List[str]) -> bytes:
        cmd = args[0].upper()
        try:
            if cmd == "PING":
                return b"+PONG\r\n"
            if cmd == "LPUSH":
                with self._lock:
                    q = self._queues.setdefault(args[1], deque())
                    items = []
                    for v in args[2:]:
                        items.append((self._next_seq, v))
                        self._next_seq += 1
                    if self._journal is not None:
                        self._journal_batch([
                            qjournal.encode_push(seq, args[1], v)
                            for seq, v in items])
                    for it in items:
                        q.appendleft(it)
                    self._lock.notify_all()   # wake parked BRPOP waiters
                    return b":%d\r\n" % len(q)
            if cmd == "BRPOP":
                # blocking pop: park THIS connection's handler thread
                # until a value arrives or the timeout lapses (seconds,
                # fractional ok; 0 = block indefinitely, as in Redis).
                # Reply is [key, value] or nil — the real BRPOP wire form.
                # The condition is held ONLY across the queue check/pop;
                # the reply is encoded after release so a slow handler
                # never extends the critical section other waiters (and
                # every LPUSH) contend on.
                key = args[1]
                timeout = float(args[2])
                deadline = None if timeout <= 0 \
                    else time.monotonic() + timeout
                popped: Optional[str] = None
                red: List[Tuple[str, str]] = []
                with self._lock:
                    while not self._killed:
                        red.extend(self._expire_locked(key))
                        q = self._queues.get(key)
                        if q:
                            seq, popped = q.pop()
                            if self._journal is not None:
                                self._journal_batch(
                                    [qjournal.encode_ack(seq, key, "")])
                            if not q:
                                del self._queues[key]
                            break
                        nxt = self._next_expiry_locked(key)
                        if deadline is None:
                            if nxt is None:
                                self._lock.wait()
                            else:
                                self._lock.wait(
                                    max(nxt - time.monotonic(), 0.001))
                        else:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                break
                            if nxt is not None:
                                remaining = max(
                                    min(remaining, nxt - time.monotonic()),
                                    0.001)
                            self._lock.wait(remaining)
                self._note_redelivered(red)
                if popped is None:
                    return b"*-1\r\n"
                k, v = key.encode(), popped.encode()
                return (b"*2\r\n$%d\r\n%s\r\n$%d\r\n%s\r\n"
                        % (len(k), k, len(v), v))
            if cmd == "RPOP":
                if len(args) > 2:
                    # Redis >= 6.2 count form: ONE command drains up to
                    # n values (array reply; nil when the list is gone) —
                    # the server half of rpop_many's single round trip
                    n = int(args[2])
                    red = []
                    with self._lock:
                        red.extend(self._expire_locked(args[1]))
                        q = self._queues.get(args[1])
                        if not q:
                            self._note_redelivered(red)
                            return b"*-1\r\n"
                        vals = []
                        acks = []
                        while q and len(vals) < n:
                            seq, v = q.pop()
                            if self._journal is not None:
                                acks.append(qjournal.encode_ack(
                                    seq, args[1], ""))
                            vals.append(v.encode())
                        self._journal_batch(acks)
                        if not q:
                            del self._queues[args[1]]
                    self._note_redelivered(red)
                    return b"*%d\r\n%s" % (
                        len(vals),
                        b"".join(b"$%d\r\n%s\r\n" % (len(v), v)
                                 for v in vals))
                red = []
                with self._lock:
                    red.extend(self._expire_locked(args[1]))
                    q = self._queues.get(args[1])
                    if not q:
                        self._note_redelivered(red)
                        return b"$-1\r\n"
                    seq, popped = q.pop()
                    if self._journal is not None:
                        self._journal_batch(
                            [qjournal.encode_ack(seq, args[1], "")])
                    v = popped.encode()
                    if not q:
                        del self._queues[args[1]]  # Redis drops empty lists
                self._note_redelivered(red)
                return b"$%d\r\n%s\r\n" % (len(v), v)
            if cmd == "LEASE":
                # LEASE <key> <n> <lease_s> <block_s> [<delim>] — deliver
                # up to n values under a visibility-timeout lease instead
                # of a destructive pop (the RPOPLPUSH-equivalent).  A
                # leased value stays journal-outstanding until ACKPUSH
                # acks its id; expiry re-enqueues it.  Values without a
                # lease identity (control words) deliver destructively.
                # block_s > 0 parks like BRPOP, waking early for lease
                # expiries so a redelivery never waits out a full park.
                return self._lease(args)
            if cmd == "ACKPUSH":
                # ACKPUSH <pushq> <ackq> <delim> <v...> — push replies
                # AND ack the leases their request ids hold on <ackq>;
                # replies whose id was already answered are dropped
                # (first wins).  ONE trip closes the worker crash window.
                return self._ackpush(args)
            if cmd == "LLEN":
                # snapshot under the BRPOP condition, format outside —
                # depth probes (the autoscaler sensor polls this) must
                # not stretch the critical section parked poppers and
                # every LPUSH serialize on
                with self._lock:
                    n = len(self._queues.get(args[1], ()))
                return b":%d\r\n" % n
            if cmd == "INFO":
                # queue-depth observability WITHOUT popping: one bulk
                # string of "queue_depth:<name>=<n>" lines (every queue,
                # or just the named ones when keys are given).  The lock
                # is held only long enough to copy the lengths.  Lease /
                # journal lines appear ONLY when present, so the default
                # broker's INFO stays byte-identical.
                with self._lock:
                    if len(args) > 1:
                        depths = {k: len(self._queues.get(k, ()))
                                  for k in args[1:]}
                        leased = {k: len(self._leases.get(k, ()))
                                  for k in args[1:]}
                    else:
                        depths = {k: len(q)
                                  for k, q in self._queues.items()}
                        leased = {k: len(t)
                                  for k, t in self._leases.items()}
                lines = (["# Queues", f"queues:{len(depths)}"] +
                         [f"queue_depth:{k}={n}"
                          for k, n in sorted(depths.items())])
                lines += [f"queue_leased:{k}={n}"
                          for k, n in sorted(leased.items()) if n]
                if self.durable != "off":
                    lines.append(f"durable:{self.durable}")
                    if self._journal is not None:
                        st = self._journal.stats()
                        lines += [
                            f"journal_segments:{st['segments']}",
                            f"journal_bytes:{st['bytes']}",
                            f"journal_records:{st['records']}"]
                body = "\n".join(lines).encode()
                return b"$%d\r\n%s\r\n" % (len(body), body)
            if cmd == "DEL":
                with self._lock:
                    n = 0
                    dels = []
                    for k in args[1:]:
                        had = self._queues.pop(k, None) is not None
                        held = self._leases.pop(k, None)
                        answered = self._acked.pop(k, None)
                        if had:
                            n += 1
                        if (had or held or answered) \
                                and self._journal is not None:
                            dels.append(qjournal.encode_del(k))
                    self._journal_batch(dels)
                return b":%d\r\n" % n
            return b"-ERR unknown command '%s'\r\n" % cmd.encode()
        except IndexError:
            return b"-ERR wrong number of arguments\r\n"

    def _lease(self, args: List[str]) -> bytes:
        key = args[1]
        n = int(args[2])
        lease_s = float(args[3])
        block_s = float(args[4])
        delim = args[5] if len(args) > 5 else ","
        deadline = None if block_s <= 0 else time.monotonic() + block_s
        out: List[bytes] = []
        red: List[Tuple[str, str]] = []
        with self._lock:
            while not self._killed:
                red.extend(self._expire_locked(key))
                q = self._queues.get(key)
                if q:
                    tab = self._leases.setdefault(key, {})
                    answered = self._acked.get(key)
                    jr = self._journal is not None
                    recs: List[bytes] = []
                    while q and len(out) < n:
                        seq, v = q.pop()
                        rid = _lease_rid(v, delim)
                        if rid is not None and answered \
                                and rid in answered:
                            # a redelivered copy raced its own ack:
                            # retire it instead of double-serving
                            if jr:
                                recs.append(
                                    qjournal.encode_ack(seq, key, ""))
                            continue
                        if rid is not None and lease_s > 0:
                            tab[rid] = (seq, v,
                                        time.monotonic() + lease_s)
                        elif jr:
                            recs.append(qjournal.encode_ack(seq, key, ""))
                        out.append(v.encode())
                    if not q:
                        del self._queues[key]
                    self._journal_batch(recs)
                    if out:
                        break
                if deadline is None:
                    break   # non-blocking
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                nxt = self._next_expiry_locked(key)
                if nxt is not None:
                    remaining = max(min(remaining,
                                        nxt - time.monotonic()), 0.001)
                self._lock.wait(remaining)
        self._note_redelivered(red)
        if not out:
            return b"*-1\r\n"
        return b"*%d\r\n%s" % (
            len(out),
            b"".join(b"$%d\r\n%s\r\n" % (len(v), v) for v in out))

    def _ackpush(self, args: List[str]) -> bytes:
        pushq, ackq, delim = args[1], args[2], args[3]
        values = args[4:]
        dups = 0
        with self._lock:
            tab = self._leases.get(ackq)
            answered = self._acked.setdefault(ackq, OrderedDict())
            jr = self._journal is not None
            recs: List[bytes] = []
            accepted: List[str] = []
            for v in values:
                rid = v.split(delim, 1)[0]
                if rid in answered:
                    dups += 1   # first reply won; drop the duplicate
                    continue
                ent = tab.pop(rid, None) if tab else None
                # journal the ack even with no lease held HERE (a
                # destructively-popped or cross-shard request): the
                # answered-set must survive restart for dedup to hold
                if jr:
                    recs.append(qjournal.encode_ack(
                        ent[0] if ent is not None else 0, ackq, rid))
                answered[rid] = True
                accepted.append(v)
            self._trim_acked(answered)
            q = self._queues.setdefault(pushq, deque())
            items = []
            for v in accepted:
                items.append((self._next_seq, v))
                self._next_seq += 1
                if jr:
                    recs.append(
                        qjournal.encode_push(items[-1][0], pushq, v))
            self._journal_batch(recs)
            for it in items:
                q.appendleft(it)
            if not q:
                self._queues.pop(pushq, None)
            self._lock.notify_all()
            depth = len(q)
        if dups:
            self.dup_replies_dropped += dups
            self.counters.increment("Broker", "DupRepliesDropped", dups)
        return b":%d\r\n" % depth

    def start(self) -> "RespServer":
        replayed = None
        if self.durable != "off" and self._journal is None:
            self._journal = qjournal.QueueJournal(
                self.journal_dir, mode=self.durable,
                segment_bytes=self._journal_segment_bytes)
            replayed = self._journal.replay()
            with self._lock:
                for k, items in replayed.queues.items():
                    # items are oldest-first; the deque pops from the
                    # RIGHT, so newest go leftmost
                    self._queues[k] = deque(reversed(items))
                for k, ids in replayed.acked.items():
                    od = self._acked.setdefault(k, OrderedDict())
                    for rid in ids:
                        od[rid] = True
                    self._trim_acked(od)
                self._next_seq = max(self._next_seq, replayed.next_seq)
            self._journal.snapshot_provider = self._journal_snapshot
            self._journal.open_for_append()
            self.journal_replayed += replayed.restored
            self.counters.increment("Broker", "JournalReplayed",
                                    replayed.restored)
        self._server = _TCPServer((self.host, self.port), _Handler)
        self._server.owner = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        if replayed is not None and (replayed.records or replayed.restored
                                     or replayed.torn):
            instant("broker.journal_replay", cat="broker",
                    endpoint=f"{self.host}:{self.port}",
                    records=replayed.records, restored=replayed.restored,
                    torn=int(replayed.torn))
        return self

    def _stop_listener(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def stop(self) -> None:
        """Graceful teardown: close the listener, then compact + sync +
        close the journal so the NEXT start replays from the checkpoint
        alone (cheap restart)."""
        self._stop_listener()
        if self._journal is not None:
            with self._lock:
                try:
                    self._journal.checkpoint()
                    self._journal.sync()
                except Exception as exc:  # noqa: BLE001 - teardown
                    warnings.warn(
                        f"respq: journal shutdown checkpoint failed "
                        f"({type(exc).__name__}: {exc}); next start "
                        "replays the segments instead", RuntimeWarning)
                self._journal.close()

    def kill(self) -> None:
        """Die like a crashed broker process: stop listening AND sever
        every established client connection (their next call raises),
        dropping the in-memory queues.  ``stop()`` is the graceful
        teardown; this is what the killed-shard drills simulate.  The
        journal is ABANDONED exactly where the crash left it (no
        checkpoint, no sync — a possibly-torn tail): a new server on the
        same ``journal_dir`` replays it."""
        self._stop_listener()
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        # parked BRPOP handlers are waiting on the condition, not the
        # socket: flip the killed flag and wake them — each wait loop
        # exits, answers nil into the severed socket, and the handler
        # thread ends (without the flag an indefinite waiter would
        # re-check the empty queue and park forever)
        with self._lock:
            self._killed = True
            self._queues.clear()
            self._leases.clear()
            self._acked.clear()
            self._lock.notify_all()
        if self._journal is not None:
            self._journal.close()   # file handle only; no checkpoint

    # ---- observability ----
    def journal_stats(self) -> dict:
        return {} if self._journal is None else self._journal.stats()

    def bind_metrics(self, registry, endpoint: Optional[str] = None):
        """Export broker durability state on a ``MetricsRegistry``:
        queue/lease depths, redeliveries, and journal bytes/segments/
        fsync latency as a labeled gauge family, plus the Broker/*
        counters via ``attach_counters``.  Returns the probe (for
        ``unregister_probe`` at teardown)."""
        ep = endpoint or f"{self.host}:{self.port}"
        g = registry.gauge(
            "avenir_broker_durable",
            "durable broker state (io/respq.py RespServer)",
            labels=("endpoint", "key"))

        def probe():
            with self._lock:
                depth = sum(len(q) for q in self._queues.values())
                leased = sum(len(t) for t in self._leases.values())
            g.set(depth, endpoint=ep, key="queue_depth")
            g.set(leased, endpoint=ep, key="leased")
            g.set(self.redelivered, endpoint=ep, key="redelivered")
            g.set(self.journal_replayed, endpoint=ep,
                  key="journal_replayed")
            if self._journal is not None:
                st = self._journal.stats()
                g.set(st["bytes"], endpoint=ep, key="journal_bytes")
                g.set(st["segments"], endpoint=ep,
                      key="journal_segments")
                g.set(st["fsync_ms_ema"], endpoint=ep,
                      key="journal_fsync_ms")
        registry.register_probe(probe)
        registry.attach_counters(self.counters)
        return probe


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class RespClient:
    """Blocking client for the three verbs the reference uses.  Works
    against :class:`RespServer` or a real Redis.

    A dropped TCP connection mid-call (server restart, transient network
    fault) no longer poisons the client: ``_call`` reconnects ONCE with
    short exponential backoff and re-issues the command before
    surfacing the error (``reconnect=False`` restores the old
    fail-fast).  Two caveats: (1) if the DROP happened after the server
    executed the command but before the reply arrived, the re-issue can
    apply a write twice — the same at-least-once window every
    reconnecting Redis client has; exactly-once consumers dedupe by
    request id.  (2) a reply TIMEOUT (server alive but stalled past the
    socket timeout) reconnects so the next call starts on a clean
    connection but does NOT re-issue — the command may have executed,
    and re-issuing a destructive read (RPOP) would pop, and lose, a
    second batch; the timeout surfaces to the caller instead."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout: float = 10.0, reconnect: bool = True,
                 delim: str = ",", counters=None, stamp: bool = True):
        self.host, self.port = host, int(port)
        self.timeout = float(timeout)
        self._reconnect = bool(reconnect)
        self._rpop_count_ok = True
        # LEASE/ACKPUSH are this broker's verbs; against a real Redis
        # (or a pre-lease server) the first -ERR permanently falls back
        # to the destructive rpop/lpush path — same pattern as
        # _rpop_count_ok
        self._lease_ok = True
        self._ackpush_ok = True
        # request-trace stamping (ISSUE 15): with ps.trace.sample set,
        # every Nth predict push gets the wire trace field at THIS
        # client.  ``stamp=False`` is for inner clients whose owner
        # already stamped (the shard ring, which knows the owning
        # shard); ``delim`` is the wire field separator.
        self._delim = delim
        self._stamp = bool(stamp)
        # reconnect observability: tally + trace instant per reconnect,
        # so a silent reconnect storm shows up in scrapes and timelines
        # instead of only as stderr warnings
        self.counters = counters
        self.reconnects = 0
        self._sock = None
        self._rf = None
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection((self.host, self.port),
                                              timeout=self.timeout)
        # request/reply round trips are small packets; Nagle would add
        # 40ms stalls to every serving poll
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rf = self._sock.makefile("rb")

    def _reconnect_once(self, why: BaseException) -> None:
        """Drop the poisoned half-connection and re-establish — the
        connect itself retried with ``core.faults.with_retry`` (base
        0.05s, 2x backoff, 4 tries); raises the last connect failure
        when the server stays unreachable."""
        from ..core.faults import with_retry
        try:
            if self._rf is not None:
                self._rf.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        with_retry(self._connect, attempts=4, base_delay=0.05,
                   retry_on=(OSError,),
                   what=f"respq reconnect to {self.host}:{self.port}")
        self.reconnects += 1
        if self.counters is not None:
            self.counters.increment("Broker", "Reconnects")
        instant("broker.reconnect", cat="broker",
                endpoint=f"{self.host}:{self.port}",
                attempt=self.reconnects,
                cause=f"{type(why).__name__}: {why}")
        warnings.warn(
            f"respq: connection to {self.host}:{self.port} dropped "
            f"({type(why).__name__}: {why}); reconnected",
            RuntimeWarning)

    def _recover(self, exc: BaseException) -> None:
        """Shared reconnect policy for a failed command exchange:
        re-establish the connection, then decide whether the caller may
        re-issue.  A TIMEOUT means the server may be alive and may have
        EXECUTED the command — re-issuing a destructive read would pop
        (and lose) a second batch — so the fresh connection is kept for
        the NEXT call and the timeout re-raises.  A hard drop
        re-establishes and returns (the caller re-issues once)."""
        if not self._reconnect:
            raise exc
        if isinstance(exc, socket.timeout):
            try:
                self._reconnect_once(exc)
            except OSError:
                pass   # surface the original timeout, not the connect
            raise exc
        self._reconnect_once(exc)

    def _call(self, *args: str):
        return self._call_raw(_encode_command(list(args)))

    def _call_raw(self, payload: bytes):
        """One command exchange from an already-encoded RESP buffer —
        the native reply encoder (io/native_wire.encode_lpush) lands
        here so a whole batch of replies is ONE sendall; same
        reconnect/re-issue policy as :meth:`_call`."""
        try:
            self._sock.sendall(payload)
            return _read_reply(self._rf)
        except (ConnectionError, OSError) as exc:
            self._recover(exc)   # raises unless a re-issue is safe
            self._sock.sendall(payload)
            return _read_reply(self._rf)

    def ping(self) -> bool:
        return self._call("PING") == "PONG"

    def lpush(self, queue: str, value: str) -> int:
        # enabled() gate first: sampling off must stay allocation-free
        # on the per-request push path (no temp list, no call into
        # stamp_values)
        if self._stamp and reqtrace.enabled():
            value = reqtrace.stamp_values(
                [value], delim=self._delim,
                broker=f"{self.host}:{self.port}")[0]
        return int(self._call("LPUSH", queue, value))

    def lpush_many(self, queue: str, values: List[str]) -> int:
        """Push ``values`` as ONE variadic LPUSH (n round trips collapse
        to one — the producer half of the wire micro-batching).  Returns
        the queue length after the push; no-op 0 on an empty list.
        Predict messages pass the head-sampling stamp (one global read
        when ``ps.trace.sample`` is off).

        The command buffer is built by the native codec when available
        (one C pass over the batch instead of a python loop of
        per-value bulk-string encodes) — byte-identical to
        ``_encode_command`` by the golden/fuzz contract, and None from
        the encoder (no toolchain, embedded join byte) falls back to
        the python encode of the SAME values."""
        if not values:
            return 0
        if self._stamp:
            values = reqtrace.stamp_values(
                values, delim=self._delim,
                broker=f"{self.host}:{self.port}")
        payload = native_wire.encode_lpush(queue, values)
        if payload is not None:
            return int(self._call_raw(payload))
        return int(self._call("LPUSH", queue, *values))

    def rpop(self, queue: str) -> Optional[str]:
        return self._call("RPOP", queue)

    def brpop(self, queue: str, timeout_s: float = 0.05) -> Optional[str]:
        """Blocking pop: park on the server until a value arrives or
        ``timeout_s`` lapses (fractional seconds; None on timeout) — the
        idle half of the fleet drain, so N parked workers cost the host
        nothing instead of N spin-polling cores.  ``timeout_s`` must be
        positive and stay under the client socket timeout — ENFORCED,
        not just documented: a park outliving the socket timeout would
        hit the reconnect path mid-BRPOP, and the abandoned server-side
        waiter could pop (and lose) the next pushed value.  Poll in a
        loop for long parks."""
        if not 0.0 < float(timeout_s) < self.timeout:
            raise ValueError(
                f"brpop timeout_s must be in (0, {self.timeout}) — the "
                f"client socket timeout; got {timeout_s!r}.  Park in a "
                f"loop for longer waits")
        reply = self._call("BRPOP", queue, repr(float(timeout_s)))
        if reply is None:
            return None
        return reply[1]   # [key, value]

    def rpop_many(self, queue: str, n: int) -> List[str]:
        """Drain up to ``n`` values in ONE round trip.  Prefers the
        Redis >= 6.2 ``RPOP key count`` form (one command, one array
        reply — the server parses n commands' worth of work once); falls
        back permanently to PIPELINED single RPOPs (one socket write
        carrying n commands) the first time the server rejects the count
        argument (real pre-6.2 Redis).  Returns the non-nil values in
        queue order; may be shorter than n."""
        if n <= 0:
            return []
        if self._rpop_count_ok:
            try:
                reply = self._call("RPOP", queue, str(n))
            except RuntimeError:
                # old server: remember and fall back to pipelining
                self._rpop_count_ok = False
            else:
                return [] if reply is None else list(reply)
        try:
            return self._pipelined_rpops(queue, n)
        except (ConnectionError, OSError) as exc:
            # same reconnect contract as _call (timeouts re-raise: the
            # burst may have executed); on a hard drop the whole
            # pipelined burst re-issues against the fresh connection
            self._recover(exc)
            return self._pipelined_rpops(queue, n)

    def _pipelined_rpops(self, queue: str, n: int) -> List[str]:
        self._sock.sendall(
            b"".join(_encode_command(["RPOP", queue]) for _ in range(n)))
        out: List[str] = []
        first_err: Optional[RuntimeError] = None
        for _ in range(n):
            try:
                v = _read_reply(self._rf)
            except RuntimeError as exc:
                # a -ERR reply is one consumed line; keep reading the
                # remaining pipelined replies or the connection would
                # desynchronize (the next command's _call would read a
                # stale RPOP reply as its own answer)
                first_err = first_err or exc
                continue
            if v is not None:
                out.append(v)
        if first_err is not None:
            raise first_err
        return out

    def lease_many(self, queue: str, n: int, lease_s: float,
                   block_s: float = 0.0) -> List[str]:
        """Acquire up to ``n`` values under a visibility-timeout lease
        (``LEASE``) — the at-least-once replacement for
        :meth:`rpop_many`: a worker that dies before acking gets its
        values redelivered after ``lease_s``.  ``block_s > 0`` parks on
        the server like BRPOP (must stay under the socket timeout).

        Unlike a destructive read, a LEASE is SAFE to re-issue after a
        connection drop: values the lost exchange leased simply expire
        and redeliver.  Against a server without the verb (real Redis)
        this falls back permanently to ``rpop_many`` (+ ``brpop`` for
        the park) — delivery is then destructive, as before."""
        if n <= 0:
            return []
        if block_s > 0 and not block_s < self.timeout:
            raise ValueError(
                f"lease_many block_s must stay under the client socket "
                f"timeout ({self.timeout}); got {block_s!r}")
        if self._lease_ok:
            try:
                reply = self._call("LEASE", queue, str(int(n)),
                                   repr(float(lease_s)),
                                   repr(float(block_s)), self._delim)
            except RuntimeError:
                self._lease_ok = False
            else:
                return [] if reply is None else list(reply)
        vals = self.rpop_many(queue, n)
        if vals or block_s <= 0:
            return vals
        v = self.brpop(queue, block_s)
        return [] if v is None else [v]

    def ackpush(self, push_queue: str, ack_queue: str,
                values: List[str]) -> int:
        """Push a reply batch AND ack the leases its request ids hold on
        ``ack_queue`` — ONE round trip (``ACKPUSH``), so the ack
        piggybacks on the reply push the worker already makes.  Replies
        for already-answered ids are dropped server-side (first wins).
        Safe to re-issue after a drop: a double-delivered ack batch
        dedups on the answered set.  Falls back permanently to plain
        :meth:`lpush_many` (no ack, no dedup) against a server without
        the verb."""
        if not values:
            return 0
        if self._ackpush_ok:
            try:
                return int(self._call("ACKPUSH", push_queue, ack_queue,
                                      self._delim, *values))
            except RuntimeError:
                self._ackpush_ok = False
        return self.lpush_many(push_queue, values)

    def llen(self, queue: str) -> int:
        return int(self._call("LLEN", queue))

    def info(self, *queues: str) -> Dict[str, int]:
        """Per-queue depths via the ``INFO`` command — observable WITHOUT
        popping (the autoscaler's queue-depth sensor and operator depth
        probes).  Returns ``{queue: depth}``; all queues by default, the
        named ones when given.  Against a real Redis (whose INFO reports
        server stats, not queue depths) the dict is empty — callers fall
        back to :meth:`llen` per queue."""
        reply = self._call("INFO", *queues)
        out: Dict[str, int] = {}
        for line in (reply or "").splitlines():
            if line.startswith("queue_depth:"):
                key, _, depth = line[len("queue_depth:"):].rpartition("=")
                try:
                    out[key] = int(depth)
                except ValueError:
                    continue
        return out

    def delete(self, *queues: str) -> int:
        return int(self._call("DEL", *queues))

    def close(self) -> None:
        try:
            self._rf.close()
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# sharded broker client
# ---------------------------------------------------------------------------

def _hash64(key: str) -> int:
    """Stable 64-bit ring hash (md5 head): identical placement in every
    process and across runs — python's builtin hash() is seed-randomized
    per process, which would put each fleet host on a DIFFERENT ring."""
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


Endpoint = Union[str, Tuple[str, int]]


def _norm_endpoint(ep: Endpoint) -> str:
    if isinstance(ep, str):
        return ep
    host, port = ep
    return f"{host}:{int(port)}"


class HashRing:
    """Consistent-hash ring over broker endpoints, ``replicas`` virtual
    nodes each.  The property the shard tier leans on: removing (or
    adding) one of M endpoints remaps only the ids that hashed TO it
    (~1/M of the key space) — every surviving assignment stays put, so a
    shard death never reshuffles the whole fleet's queues (pinned by
    tests/test_broker.py)."""

    __slots__ = ("endpoints", "replicas", "_hashes", "_owners")

    def __init__(self, endpoints: Sequence[str], replicas: int = 64):
        self.endpoints = [_norm_endpoint(e) for e in endpoints]
        if len(set(self.endpoints)) != len(self.endpoints):
            raise ValueError(f"duplicate broker endpoints: {self.endpoints}")
        self.replicas = int(replicas)
        points = sorted((_hash64(f"{ep}#{r}"), ep)
                        for ep in self.endpoints
                        for r in range(self.replicas))
        self._hashes = [h for h, _ in points]
        self._owners = [ep for _, ep in points]

    def lookup(self, key: str) -> str:
        """The endpoint owning ``key`` (first ring point clockwise)."""
        if not self._owners:
            raise RuntimeError("broker ring is empty (every shard down)")
        i = bisect.bisect_right(self._hashes, _hash64(str(key)))
        return self._owners[i % len(self._owners)]

    def without(self, endpoint: str) -> "HashRing":
        return HashRing([e for e in self.endpoints if e != endpoint],
                        self.replicas)


class ShardedRespClient:
    """One client over M RESP broker shards: consistent-hash fan-out.

    Request ids route by :class:`HashRing` lookup, so a request
    (``predict,<id>,...``) and its reply (``<id>,<label>``) land on the
    SAME shard and a collector simply fans ``rpop_many`` across the ring
    and reassembles by id.  Per-shard pipelining everywhere: one
    variadic LPUSH per shard per push batch, one RPOP-count (or
    pipelined) drain per shard per poll.

    Degraded-ring semantics: a shard whose connection fails (after the
    underlying :class:`RespClient`'s own reconnect attempt) is marked
    down with a structured warning and a ``Broker/BrokerShardDown``
    counter, and the ring shrinks to the survivors — values from the
    failed push are RE-ROUTED onto the surviving shards, never dropped.
    Messages already queued inside the dead shard's memory are the
    producer's re-offer window (unanswered ids get re-sent — the bench's
    killed-shard protocol).  When the LAST shard dies the client raises:
    there is nowhere left to degrade to.

    Like :class:`RespClient`, not thread-safe — one instance per thread
    (each fleet worker owns its own)."""

    def __init__(self, endpoints: Sequence[Endpoint],
                 timeout: float = 10.0, replicas: int = 64,
                 delim: str = ",", counters=None):
        eps = [_norm_endpoint(e) for e in endpoints]
        if not eps:
            raise ValueError("need at least one broker endpoint")
        self._delim = delim
        self._timeout = float(timeout)
        self.counters = counters
        self._clients: Dict[str, RespClient] = {}
        self._down: List[str] = []
        # rid -> endpoint it was LEASED from, so the piggybacked ack
        # reaches the shard actually holding the lease even after ring
        # membership changed in between; bounded first-in first-evicted
        # (an evicted entry just means the ack routes by ring lookup
        # and the lease expires into a redelivery — dedup absorbs it)
        self._lease_src: "OrderedDict[str, str]" = OrderedDict()
        self._lease_src_cap = 65536
        # a down shard is probed for REJOIN at most once per interval:
        # the kill-and-restart drill needs the restarted shard (journal
        # replayed) to re-enter the ring without rebuilding every client
        self.rejoin_interval_s = 1.0
        self._last_rejoin = 0.0
        live: List[str] = []
        first_err: Optional[BaseException] = None
        for ep in eps:
            host, _, port = ep.rpartition(":")
            try:
                # inner clients do NOT stamp: the ring stamps per push
                # group below, where the owning shard is known
                self._clients[ep] = RespClient(host or "127.0.0.1",
                                               int(port), timeout=timeout,
                                               delim=delim,
                                               counters=counters,
                                               stamp=False)
            except OSError as exc:
                first_err = first_err or exc
                self._note_down(ep, exc)
            else:
                live.append(ep)
        if not live:
            raise ConnectionError(
                f"no broker shard reachable out of {eps}") from first_err
        self._ring = HashRing(live, replicas=replicas)
        self._rr = 0   # rotating start index: fair drain across shards

    # ---- ring state ----
    @property
    def live_endpoints(self) -> List[str]:
        return list(self._ring.endpoints)

    @property
    def down_endpoints(self) -> List[str]:
        return list(self._down)

    def shard_of(self, request_id: str) -> str:
        """Which live shard owns ``request_id`` (tests + operators)."""
        return self._ring.lookup(request_id)

    def id_of(self, value: str) -> str:
        """The routing id of a wire message: ``predict,<id>,...`` and
        ``reward,<id>,<value>`` route by the id field — a reward MUST
        land on the shard holding the request it rewards, or the
        online learner draining that shard never joins them — anything
        else (a reply ``<id>,<label>``, a control word) by its first
        field."""
        parts = value.split(self._delim, 2)
        if parts[0] in ("predict", "reward") and len(parts) > 1:
            return parts[1]
        if parts[0].startswith("reward:"):
            # a reward-ack token (``reward:<id>,acked``) must chase the
            # shard that leased ``reward,<id>,...`` — i.e. <id>'s shard
            return parts[0][len("reward:"):]
        return parts[0]

    def _note_down(self, ep: str, exc: BaseException) -> None:
        self._down.append(ep)
        if self.counters is not None:
            self.counters.increment("Broker", "BrokerShardDown")
        survivors = sum(1 for e in self._clients if e != ep)
        instant("broker.shard_down", cat="broker", endpoint=ep,
                cause=f"{type(exc).__name__}: {exc}",
                survivors=survivors)
        warnings.warn(
            f"broker: shard {ep} down ({type(exc).__name__}: {exc}); "
            f"degrading to the surviving ring ({survivors} shard(s) "
            f"left)", RuntimeWarning)

    def _mark_down(self, ep: str, exc: BaseException) -> None:
        """Shrink the ring past a dead shard; raises when it was the
        last one (nowhere to degrade to)."""
        if ep not in self._clients:
            return
        self._note_down(ep, exc)
        cli = self._clients.pop(ep)
        try:
            cli.close()
        except OSError:
            pass
        self._ring = self._ring.without(ep)
        if not self._ring.endpoints:
            raise ConnectionError(
                f"broker: last shard {ep} is down "
                f"({type(exc).__name__}: {exc})") from exc

    def _maybe_rejoin(self) -> None:
        """Probe down shards (rate-limited) and fold a revived one back
        into the ring — the client half of the killed-and-restarted
        shard drill: a shard that came back with its journal replayed
        re-owns its id range (consistent hashing: only ids that hashed
        to it move back; every surviving assignment stays put)."""
        if not self._down:
            return
        now = time.monotonic()
        # rate-limited while the ring still has survivors; when EVERY
        # shard is down there is nothing left to throttle for — probe
        # on every verb so a restarted shard is folded back the moment
        # it binds (the fleet's broker-outage grace retry depends on
        # this to recover from a total ring loss)
        if self._ring.endpoints and \
                now - self._last_rejoin < self.rejoin_interval_s:
            return
        self._last_rejoin = now
        for ep in list(self._down):
            host, _, port = ep.rpartition(":")
            try:
                cli = RespClient(host or "127.0.0.1", int(port),
                                 timeout=self._timeout, delim=self._delim,
                                 counters=self.counters, stamp=False)
            except OSError:
                continue
            self._down.remove(ep)
            self._clients[ep] = cli
            self._ring = HashRing(self._ring.endpoints + [ep],
                                  replicas=self._ring.replicas)
            if self.counters is not None:
                self.counters.increment("Broker", "BrokerShardUp")
            instant("broker.shard_up", cat="broker", endpoint=ep,
                    survivors=len(self._ring.endpoints))
            warnings.warn(
                f"broker: shard {ep} is back; rejoined the ring "
                f"({len(self._ring.endpoints)} shard(s) live)",
                RuntimeWarning)

    def _note_leased(self, values: List[str], ep: str) -> None:
        for v in values:
            rid = _lease_rid(v, self._delim)
            if rid is not None:
                self._lease_src[rid] = ep
        while len(self._lease_src) > self._lease_src_cap:
            self._lease_src.popitem(last=False)

    # ---- fan-out verbs ----
    def ping(self) -> bool:
        """True when every LIVE shard answers PONG.  Like every other
        fan-out verb, a shard failing the probe degrades the ring
        (warning + counter) instead of crashing the caller — a liveness
        probe that raises on exactly the condition it probes for would
        be useless; the last shard dying still raises."""
        self._maybe_rejoin()
        ok = True
        for ep in self.live_endpoints:
            if ep not in self._clients:
                continue
            try:
                ok = self._clients[ep].ping() and ok
            except (ConnectionError, OSError) as exc:
                self._mark_down(ep, exc)
                ok = False
        return ok

    def lpush(self, queue: str, value: str) -> int:
        return self.lpush_many(queue, [value])

    def lpush_many(self, queue: str, values: List[str]) -> int:
        """Push a batch: group by owning shard, ONE variadic LPUSH per
        shard.  A shard failing mid-push degrades the ring and its
        group re-routes onto the survivors (accepted values are never
        dropped by the client).  Returns the summed post-push depth of
        the touched shards."""
        self._maybe_rejoin()
        total = 0
        pending = list(values)
        while pending:
            groups: Dict[str, List[str]] = {}
            for v in pending:
                groups.setdefault(self._ring.lookup(self.id_of(v)),
                                  []).append(v)
            pending = []
            for ep, vals in groups.items():
                # head-sampling stamp AFTER routing, so the flow start
                # names the owning shard; a re-route keeps the original
                # stamp (the field-present check makes re-stamping a
                # no-op) — the enqueue time is the FIRST offer
                vals = reqtrace.stamp_values(vals, delim=self._delim,
                                             broker=ep)
                try:
                    total += self._clients[ep].lpush_many(queue, vals)
                except (ConnectionError, OSError) as exc:
                    self._mark_down(ep, exc)   # raises when ring empties
                    pending.extend(vals)       # re-route on the new ring
        return total

    def broadcast(self, queue: str, value: str) -> int:
        """Push ``value`` onto EVERY live shard (control fan-out: a
        'reload' must be seen whichever shard a fleet drains first).
        Returns how many shards accepted it."""
        n = 0
        for ep in self.live_endpoints:
            try:
                self._clients[ep].lpush(queue, value)
                n += 1
            except (ConnectionError, OSError) as exc:
                self._mark_down(ep, exc)
        return n

    def rpop(self, queue: str) -> Optional[str]:
        vs = self.rpop_many(queue, 1)
        return vs[0] if vs else None

    def rpop_many(self, queue: str, n: int) -> List[str]:
        """Drain up to ``n`` values across the ring: pipelined
        ``rpop_many`` per shard, visiting shards from a rotating start
        index so one busy shard cannot starve the others.  A failing
        shard degrades the ring; the poll continues on the survivors."""
        if n <= 0:
            return []
        self._maybe_rejoin()
        out: List[str] = []
        eps = self.live_endpoints
        self._rr += 1
        start = self._rr
        for i in range(len(eps)):
            ep = eps[(start + i) % len(eps)]
            if ep not in self._clients:
                continue
            try:
                out.extend(self._clients[ep].rpop_many(queue, n - len(out)))
            except (ConnectionError, OSError) as exc:
                self._mark_down(ep, exc)
            if len(out) >= n:
                break
        return out

    def lease_many(self, queue: str, n: int, lease_s: float,
                   block_s: float = 0.0) -> List[str]:
        """Lease up to ``n`` values across the ring: one non-blocking
        LEASE sweep from a rotating start, then (idle + ``block_s``) a
        blocking LEASE on ONE rotating shard — the at-least-once drain.
        Records which shard leased each id so the piggybacked ack
        (:meth:`ackpush`) routes back to the lease holder."""
        if n <= 0:
            return []
        self._maybe_rejoin()
        out: List[str] = []
        eps = self.live_endpoints
        self._rr += 1
        start = self._rr
        for i in range(len(eps)):
            ep = eps[(start + i) % len(eps)]
            cli = self._clients.get(ep)
            if cli is None:
                continue
            try:
                got = cli.lease_many(queue, n - len(out), lease_s)
            except (ConnectionError, OSError) as exc:
                self._mark_down(ep, exc)
                continue
            self._note_leased(got, ep)
            out.extend(got)
            if len(out) >= n:
                break
        if out or block_s <= 0:
            return out
        eps = self.live_endpoints
        if not eps:
            raise RuntimeError("broker ring is empty (every shard down)")
        self._rr += 1
        ep = eps[self._rr % len(eps)]
        cli = self._clients.get(ep)
        if cli is None:
            return []
        try:
            got = cli.lease_many(queue, n, lease_s, block_s)
        except (ConnectionError, OSError) as exc:
            self._mark_down(ep, exc)
            return []
        self._note_leased(got, ep)
        return got

    def ackpush(self, push_queue: str, ack_queue: str,
                values: List[str]) -> int:
        """Reply push + lease ack, grouped by the shard each id was
        LEASED from (falling back to ring lookup when unknown).  A
        shard failing mid-ack degrades the ring and its replies
        re-route to the survivors — the reply is never dropped; the
        orphaned lease expires into a redelivery that the answered-set
        (or the consumer-side :func:`dedup_replies`) absorbs."""
        if not values:
            return 0
        self._maybe_rejoin()
        total = 0
        pending = list(values)
        while pending:
            groups: Dict[str, List[str]] = {}
            for v in pending:
                rid = v.split(self._delim, 1)[0]
                ep = self._lease_src.get(rid)
                if ep is None or ep not in self._clients:
                    ep = self._ring.lookup(self.id_of(v))
                groups.setdefault(ep, []).append(v)
            pending = []
            for ep, vals in groups.items():
                try:
                    total += self._clients[ep].ackpush(
                        push_queue, ack_queue, vals)
                except (ConnectionError, OSError) as exc:
                    self._mark_down(ep, exc)   # raises when ring empties
                    pending.extend(vals)
                else:
                    for v in vals:
                        self._lease_src.pop(
                            v.split(self._delim, 1)[0], None)
        return total

    def brpop(self, queue: str, timeout_s: float = 0.05) -> Optional[str]:
        """Park-when-idle over the ring: one non-blocking sweep first,
        then a real BRPOP on ONE rotating shard for the timeout.  A
        value landing on a different shard during the park is picked up
        at the next poll — bounded by ``timeout_s``, which the fleet
        keeps in the low milliseconds."""
        self._maybe_rejoin()
        vs = self.rpop_many(queue, 1)
        if vs:
            return vs[0]
        eps = self.live_endpoints
        if not eps:
            raise RuntimeError("broker ring is empty (every shard down)")
        self._rr += 1
        ep = eps[self._rr % len(eps)]
        try:
            return self._clients[ep].brpop(queue, timeout_s)
        except (ConnectionError, OSError) as exc:
            self._mark_down(ep, exc)
            return None

    def llen(self, queue: str) -> int:
        """Summed depth across the live ring (down shards excluded)."""
        total = 0
        for ep in self.live_endpoints:
            if ep not in self._clients:
                continue
            try:
                total += self._clients[ep].llen(queue)
            except (ConnectionError, OSError) as exc:
                self._mark_down(ep, exc)
        return total

    def depths(self, *queues: str) -> Dict[str, Dict[str, int]]:
        """Per-shard per-queue depths via INFO (no popping):
        ``{endpoint: {queue: depth}}`` — the observable the autoscaler
        sensor and the killed-shard bench read."""
        self._maybe_rejoin()
        out: Dict[str, Dict[str, int]] = {}
        for ep in self.live_endpoints:
            if ep not in self._clients:
                continue
            try:
                out[ep] = self._clients[ep].info(*queues)
            except (ConnectionError, OSError) as exc:
                self._mark_down(ep, exc)
        return out

    def delete(self, *queues: str) -> int:
        n = 0
        for ep in self.live_endpoints:
            if ep not in self._clients:
                continue
            try:
                n += self._clients[ep].delete(*queues)
            except (ConnectionError, OSError) as exc:
                self._mark_down(ep, exc)
        return n

    def close(self) -> None:
        for cli in self._clients.values():
            cli.close()
        self._clients.clear()


def make_queue_client(config: Optional[Dict] = None, delim: str = ",",
                      counters=None
                      ) -> Union[RespClient, ShardedRespClient]:
    """Build the right client for a serving config: the plain
    :class:`RespClient` for one ``redis.server.host``/``port``, the
    :class:`ShardedRespClient` when ``redis.server.endpoints`` lists a
    ring (list of ``host:port`` / ``(host, port)``, or one
    comma-separated string).  The single-endpoint path stays the plain
    client on purpose — no ring hashing on the hot path when there is
    nothing to shard."""
    cfg = dict(config or {})
    endpoints = cfg.get("redis.server.endpoints")
    if endpoints:
        if isinstance(endpoints, str):
            endpoints = [e.strip() for e in endpoints.split(",")
                         if e.strip()]
        endpoints = [_norm_endpoint(e) for e in endpoints]
        if len(endpoints) > 1:
            return ShardedRespClient(endpoints, delim=delim,
                                     counters=counters)
        host, _, port = endpoints[0].rpartition(":")
        return RespClient(host or "127.0.0.1", int(port), delim=delim,
                          counters=counters)
    return RespClient(cfg.get("redis.server.host", "127.0.0.1"),
                      int(cfg.get("redis.server.port", 6379)),
                      delim=delim, counters=counters)
