"""Minimal Redis-protocol (RESP2) queue transport for the serving loop.

The reference's online RL rides Redis lists as queues: the Storm spout
``rpop``s the event and reward queues and the action writer ``lpush``es
``<eventID>,<action...>`` lines (storm/RedisSpout.java:30-95,
RedisActionWriter.java:47-61).  This module provides both halves of that
contract with no external dependency:

  * :class:`RespServer` — a threaded TCP server speaking the RESP2 subset
    the queue contract needs (LPUSH, RPOP, BRPOP, LLEN, DEL, PING), backed
    by in-memory deques.  A real ``redis-cli``/client library can talk to
    it.
  * :class:`RespClient` — a blocking client usable against this server OR
    a real Redis instance (the wire format is the same), exposing exactly
    the three verbs the reference uses.

Security note: like stock Redis, there is no auth — bind to loopback
(the default) or a trusted network only.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from collections import deque
from typing import Dict, List, Optional


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def _encode_command(args: List[str]) -> bytes:
    """Client -> server: RESP array of bulk strings."""
    out = [f"*{len(args)}\r\n".encode()]
    for a in args:
        b = a.encode()
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


def _read_line(rf) -> bytes:
    line = rf.readline()
    if not line:
        raise ConnectionError("peer closed")
    return line.rstrip(b"\r\n")


def _read_reply(rf):
    """Parse one RESP reply: +simple, -error, :int, $bulk (None for -1),
    *array."""
    line = _read_line(rf)
    kind, rest = line[:1], line[1:]
    if kind == b"+":
        return rest.decode()
    if kind == b"-":
        raise RuntimeError(f"server error: {rest.decode()}")
    if kind == b":":
        return int(rest)
    if kind == b"$":
        n = int(rest)
        if n == -1:
            return None
        body = rf.read(n + 2)[:n]
        return body.decode()
    if kind == b"*":
        n = int(rest)
        if n == -1:
            return None
        return [_read_reply(rf) for _ in range(n)]
    raise RuntimeError(f"unparseable reply {line!r}")


def _read_command(rf) -> Optional[List[str]]:
    """Server side: one client command (RESP array of bulk strings, plus
    the inline fallback real Redis also accepts)."""
    line = rf.readline()
    if not line:
        return None
    line = line.rstrip(b"\r\n")
    if not line:
        return []
    if line[:1] == b"*":
        n = int(line[1:])
        args = []
        for _ in range(n):
            hdr = _read_line(rf)
            if hdr[:1] != b"$":
                raise RuntimeError(f"expected bulk string, got {hdr!r}")
            ln = int(hdr[1:])
            args.append(rf.read(ln + 2)[:ln].decode())
        return args
    return line.decode().split()  # inline command


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        srv: "RespServer" = self.server.owner  # type: ignore[attr-defined]
        while True:
            try:
                args = _read_command(self.rfile)
            except (ConnectionError, ValueError, RuntimeError):
                return
            if args is None:
                return
            if not args:
                continue
            self.wfile.write(srv.dispatch(args))
            self.wfile.flush()


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class RespServer:
    """In-memory Redis-list queue server.  ``start()`` binds and serves on
    a daemon thread; ``port`` is resolved after start (pass 0 for an
    ephemeral port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._queues: Dict[str, deque] = {}
        # a Condition so BRPOP can park its handler thread until an LPUSH
        # arrives (ThreadingTCPServer: blocking one handler blocks only
        # that client's connection); its lock is the queues lock
        self._lock = threading.Condition()
        self._server: Optional[_TCPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ---- command dispatch (the RESP subset the queue contract uses) ----
    def dispatch(self, args: List[str]) -> bytes:
        cmd = args[0].upper()
        try:
            if cmd == "PING":
                return b"+PONG\r\n"
            if cmd == "LPUSH":
                with self._lock:
                    q = self._queues.setdefault(args[1], deque())
                    for v in args[2:]:
                        q.appendleft(v)
                    self._lock.notify_all()   # wake parked BRPOP waiters
                    return b":%d\r\n" % len(q)
            if cmd == "BRPOP":
                # blocking pop: park THIS connection's handler thread
                # until a value arrives or the timeout lapses (seconds,
                # fractional ok; 0 = block indefinitely, as in Redis).
                # Reply is [key, value] or nil — the real BRPOP wire form.
                key = args[1]
                timeout = float(args[2])
                deadline = None if timeout <= 0 \
                    else time.monotonic() + timeout
                with self._lock:
                    while True:
                        q = self._queues.get(key)
                        if q:
                            v = q.pop().encode()
                            if not q:
                                del self._queues[key]
                            k = key.encode()
                            return (b"*2\r\n$%d\r\n%s\r\n$%d\r\n%s\r\n"
                                    % (len(k), k, len(v), v))
                        if deadline is None:
                            self._lock.wait()
                        else:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                return b"*-1\r\n"
                            self._lock.wait(remaining)
            if cmd == "RPOP":
                if len(args) > 2:
                    # Redis >= 6.2 count form: ONE command drains up to
                    # n values (array reply; nil when the list is gone) —
                    # the server half of rpop_many's single round trip
                    n = int(args[2])
                    with self._lock:
                        q = self._queues.get(args[1])
                        if not q:
                            return b"*-1\r\n"
                        vals = []
                        while q and len(vals) < n:
                            vals.append(q.pop().encode())
                        if not q:
                            del self._queues[args[1]]
                    return b"*%d\r\n%s" % (
                        len(vals),
                        b"".join(b"$%d\r\n%s\r\n" % (len(v), v)
                                 for v in vals))
                with self._lock:
                    q = self._queues.get(args[1])
                    if not q:
                        return b"$-1\r\n"
                    v = q.pop().encode()
                    if not q:
                        del self._queues[args[1]]  # Redis drops empty lists
                return b"$%d\r\n%s\r\n" % (len(v), v)
            if cmd == "LLEN":
                with self._lock:
                    return b":%d\r\n" % len(self._queues.get(args[1], ()))
            if cmd == "DEL":
                with self._lock:
                    n = sum(1 for k in args[1:] if self._queues.pop(k, None)
                            is not None)
                return b":%d\r\n" % n
            return b"-ERR unknown command '%s'\r\n" % cmd.encode()
        except IndexError:
            return b"-ERR wrong number of arguments\r\n"

    def start(self) -> "RespServer":
        self._server = _TCPServer((self.host, self.port), _Handler)
        self._server.owner = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class RespClient:
    """Blocking client for the three verbs the reference uses.  Works
    against :class:`RespServer` or a real Redis."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout: float = 10.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        # request/reply round trips are small packets; Nagle would add
        # 40ms stalls to every serving poll
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rf = self._sock.makefile("rb")
        self._rpop_count_ok = True

    def _call(self, *args: str):
        self._sock.sendall(_encode_command(list(args)))
        return _read_reply(self._rf)

    def ping(self) -> bool:
        return self._call("PING") == "PONG"

    def lpush(self, queue: str, value: str) -> int:
        return int(self._call("LPUSH", queue, value))

    def lpush_many(self, queue: str, values: List[str]) -> int:
        """Push ``values`` as ONE variadic LPUSH (n round trips collapse
        to one — the producer half of the wire micro-batching).  Returns
        the queue length after the push; no-op 0 on an empty list."""
        if not values:
            return 0
        return int(self._call("LPUSH", queue, *values))

    def rpop(self, queue: str) -> Optional[str]:
        return self._call("RPOP", queue)

    def brpop(self, queue: str, timeout_s: float = 0.05) -> Optional[str]:
        """Blocking pop: park on the server until a value arrives or
        ``timeout_s`` lapses (fractional seconds; None on timeout) — the
        idle half of the fleet drain, so N parked workers cost the host
        nothing instead of N spin-polling cores.  ``timeout_s`` must stay
        comfortably under the client socket timeout."""
        reply = self._call("BRPOP", queue, repr(float(timeout_s)))
        if reply is None:
            return None
        return reply[1]   # [key, value]

    def rpop_many(self, queue: str, n: int) -> List[str]:
        """Drain up to ``n`` values in ONE round trip.  Prefers the
        Redis >= 6.2 ``RPOP key count`` form (one command, one array
        reply — the server parses n commands' worth of work once); falls
        back permanently to PIPELINED single RPOPs (one socket write
        carrying n commands) the first time the server rejects the count
        argument (real pre-6.2 Redis).  Returns the non-nil values in
        queue order; may be shorter than n."""
        if n <= 0:
            return []
        if self._rpop_count_ok:
            try:
                reply = self._call("RPOP", queue, str(n))
            except RuntimeError:
                # old server: remember and fall back to pipelining
                self._rpop_count_ok = False
            else:
                return [] if reply is None else list(reply)
        self._sock.sendall(
            b"".join(_encode_command(["RPOP", queue]) for _ in range(n)))
        out: List[str] = []
        first_err: Optional[RuntimeError] = None
        for _ in range(n):
            try:
                v = _read_reply(self._rf)
            except RuntimeError as exc:
                # a -ERR reply is one consumed line; keep reading the
                # remaining pipelined replies or the connection would
                # desynchronize (the next command's _call would read a
                # stale RPOP reply as its own answer)
                first_err = first_err or exc
                continue
            if v is not None:
                out.append(v)
        if first_err is not None:
            raise first_err
        return out

    def llen(self, queue: str) -> int:
        return int(self._call("LLEN", queue))

    def delete(self, *queues: str) -> int:
        return int(self._call("DEL", *queues))

    def close(self) -> None:
        try:
            self._rf.close()
            self._sock.close()
        except OSError:
            pass
