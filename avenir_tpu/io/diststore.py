"""Random-access entity-distance store: the rebuild's equivalent of the
reference's Hadoop MapFile wrapper (util/EntityDistanceMapFileAccessor.java,
used by cluster/AgglomerativeGraphical.java and EdgeWeightedCluster.java).

Same layout idea as a MapFile — a data file plus an index — without Hadoop:
``<store>/data.txt`` holds one ``key<delim>value`` line per entity and
``<store>/index.json`` maps key -> (byte offset, byte length) into the data
file, so ``read(key)`` is a seek + bounded read regardless of store size.
Values are the reference's alternating ``target,distance`` pair lists.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple


class EntityDistanceStore:
    DATA = "data.txt"
    INDEX = "index.json"

    def __init__(self, store_dir: str, delim: str = ","):
        self.store_dir = store_dir
        self.delim = delim
        self._index: Optional[Dict[str, Tuple[int, int]]] = None
        self._fh = None

    # ---- writing (EntityDistanceMapFileAccessor.write :69-92) ----
    @classmethod
    def write(cls, lines: Iterable[str], store_dir: str,
              delim: str = ",") -> "EntityDistanceStore":
        """Each input line is ``srcId<delim>target1<delim>dist1<delim>...``;
        the first field becomes the key, the remainder the stored value."""
        os.makedirs(store_dir, exist_ok=True)
        index: Dict[str, Tuple[int, int]] = {}
        data_path = os.path.join(store_dir, cls.DATA)
        with open(data_path, "wb") as fh:
            for line in lines:
                line = line.rstrip("\n")
                if not line.strip():
                    continue
                pos = line.find(delim)
                if pos < 0:
                    raise ValueError(f"no delimiter in store line {line!r}")
                key, value = line[:pos], line[pos + 1:]
                off = fh.tell()
                blob = value.encode()
                fh.write(blob + b"\n")
                index[key] = (off, len(blob))
        with open(os.path.join(store_dir, cls.INDEX), "w") as fh:
            json.dump({"delim": delim,
                       "index": {k: list(v) for k, v in index.items()}}, fh)
        return cls(store_dir, delim)

    @classmethod
    def write_from_file(cls, in_path: str, store_dir: str,
                        delim: str = ",") -> "EntityDistanceStore":
        with open(in_path, "r") as fh:
            return cls.write(fh, store_dir, delim)

    # ---- reading (EntityDistanceMapFileAccessor.read :100-132) ----
    def _load_index(self) -> Dict[str, Tuple[int, int]]:
        if self._index is None:
            with open(os.path.join(self.store_dir, self.INDEX)) as fh:
                meta = json.load(fh)
            self.delim = meta["delim"]
            self._index = {k: (v[0], v[1]) for k, v in meta["index"].items()}
        return self._index

    def _data(self):
        if self._fh is None:
            self._fh = open(os.path.join(self.store_dir, self.DATA), "rb")
        return self._fh

    def read_raw(self, key: str) -> Optional[str]:
        entry = self._load_index().get(key)
        if entry is None:
            return None
        off, length = entry
        fh = self._data()
        fh.seek(off)
        return fh.read(length).decode()

    def read(self, key: str) -> Optional[List[Tuple[str, float]]]:
        """(target entity, distance) pairs for a source entity; None if the
        key is absent (the reference returns an empty map after logging)."""
        raw = self.read_raw(key)
        if raw is None:
            return None
        items = raw.split(self.delim)
        pairs = []
        for i in range(0, len(items) - 1, 2):
            pairs.append((items[i], float(items[i + 1])))
        return pairs

    def keys(self) -> List[str]:
        return list(self._load_index().keys())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "EntityDistanceStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
